"""Live-target standing verification: the monitor's suite-backed mode.

`jepsen monitor --suite kvdb` swaps the in-process `_OpSource` for a
pool of real suite clients talking to real daemon processes, and runs a
*live nemesis driver* inside the standing loop: coverage-guided fault
schedules (nemesis/search.py) are materialized window after window, each
window's outcome is fingerprinted (resilience counters, verdict and
anomaly signatures, heal-vs-abandon ledger records, epoch restarts), and
the next window evolves toward novelty.  Three standing guarantees:

  * **Honest degradation, never a wedge.**  A dead client reconnects
    with backoff; a dead node is quarantined and readmitted by the
    health monitor; a frontier death after discard is an epoch restart
    with a dossier; an unhealed window left by a crash is swept by
    `core.repair` on the next start.
  * **Intent before inject.**  Every fault flows through the same
    nemesis packages batch tests use, so the fault ledger journals a
    compensator before the wound lands — a SIGKILL'd monitor leaves a
    ledger a fresh one can replay.
  * **Guaranteed heals.**  Every window ends by applying the schedule's
    per-family final heal in a `finally:` block, stop-flag or not, and
    daemons that die *outside* a fault window are restarted by the
    supervisor (counted `monitor.live.daemon-restarts`).

Crash-safety: the search frontier checkpoints atomically to
`search.json` after every window, so a killed monitor resumes both its
verdict stream (fresh epoch, honest unknown for the dying one) and its
coverage search exactly where they stopped.
"""

from __future__ import annotations

import contextlib
import logging
import os
import queue
import random
import threading
import time
from typing import Any, Callable, Optional

from .. import telemetry
from ..control import health
from ..control import util as cutil
from ..history import FAIL, INFO
from ..history.core import Op
from ..nemesis import ledger as fault_ledger
from ..nemesis import search
from .loop import _atomic_json, _write_dossier

log = logging.getLogger(__name__)

#: Status document the dashboard and the smoke read, under the store dir.
LIVE_STATUS_FILE = "live-status.json"

#: Subdirectory of the store dir holding the live run's cluster state:
#: fault ledger, repair reports.  Stable across restarts so a resumed
#: monitor finds the crashed run's ledger.
LIVE_DIR = "live"

#: suite name -> callable returning the adapter dict.  Lazy imports keep
#: `import jepsen_tpu.monitor` free of suite (and compiler) baggage.
SUITES: dict[str, Callable[[], dict]] = {}


def _register(name: str, modname: str) -> None:
    def load() -> dict:
        import importlib

        mod = importlib.import_module(f"jepsen_tpu.suites.{modname}")
        return mod.live_suite()

    SUITES[name] = load


for _name in ("kvdb", "logd", "electd", "txnd", "repkv"):
    _register(_name, _name)


def resolve_suite(name: str) -> dict:
    try:
        loader = SUITES[name]
    except KeyError:
        raise ValueError(
            f"unknown monitor suite {name!r}; have {sorted(SUITES)}"
        ) from None
    return loader()


# ---------------------------------------------------------------------------
# Live op source: a pool of real suite clients
# ---------------------------------------------------------------------------


class LiveSource:
    """Suite-backed replacement for the loop's `_OpSource`: one worker
    thread per (key, process) running a real client against a real
    daemon, emitting (key, Op) events through a bounded queue in the
    exact shape the in-process source produces — invoke then
    completion, `process = key * procs_per_key + p`, a monotonic global
    index assigned at dequeue.

    Wound behavior is the tentpole's contract: a quarantined node
    fast-fails without dialing; a failed open retries with exponential
    backoff and signals the health monitor; an invoke that raises
    becomes an honest `info` completion, the client is dropped, and the
    worker reconnects."""

    QUEUE_DEPTH = 4096
    BACKOFF_MIN = 0.05
    BACKOFF_MAX = 2.0
    #: info-completion error prefixes that mean the protocol stream may
    #: be desynchronized: drop the client and reconnect.
    DESYNC_ERRORS = ("timeout", "io", "connection", "closed")

    def __init__(self, test: dict, adapter: dict, *, keys: int,
                 procs_per_key: int, rate: float, seed: int):
        self.test = test
        self.adapter = adapter
        self.keys = keys
        self.procs = procs_per_key
        self.seed = seed
        # Per-worker pacing: the pool as a whole targets ~rate
        # completions/s; each worker's share is rate / (keys * procs).
        per_worker = max(1e-3, rate / max(1, keys * procs_per_key))
        self.interval = 1.0 / per_worker
        self.index = 0
        self.q: queue.Queue = queue.Queue(maxsize=self.QUEUE_DEPTH)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> None:
        for key in range(self.keys):
            for p in range(self.procs):
                t = threading.Thread(
                    target=self._work, args=(key, p),
                    name=f"live-src-{key}-{p}", daemon=True,
                )
                self._threads.append(t)
                t.start()

    # -- loop-facing API ------------------------------------------------

    def next_event(self, timeout: float = 0.25
                   ) -> Optional[tuple[int, Op]]:
        """The next (key, op) event, or None if the pool produced
        nothing within `timeout` (wounded cluster, all nodes down)."""
        try:
            key, op = self.q.get(timeout=timeout)
        except queue.Empty:
            return None
        self.index += 1
        return key, op.replace(index=self.index)

    def drain(self, deadline_s: float = 5.0) -> list[tuple[int, Op]]:
        """Stops the workers and returns every event still in flight
        (keeping queue space free so blocked workers can finish their
        final put and exit)."""
        self._stop.set()
        leftovers: list[tuple[int, Op]] = []

        def pop(timeout: Optional[float]) -> bool:
            try:
                key, op = (self.q.get(timeout=timeout) if timeout
                           else self.q.get_nowait())
            except queue.Empty:
                return False
            self.index += 1
            leftovers.append((key, op.replace(index=self.index)))
            return True

        deadline = time.monotonic() + deadline_s
        while (any(t.is_alive() for t in self._threads)
               and time.monotonic() < deadline):
            pop(0.05)
        for t in self._threads:
            t.join(timeout=0.5)
        while pop(None):
            pass
        return leftovers

    # -- worker ---------------------------------------------------------

    def _emit(self, key: int, op: Op) -> None:
        while not self._stop.is_set():
            try:
                self.q.put((key, op), timeout=0.25)
                return
            except queue.Full:
                continue

    def _pace(self, t_start: float, extra: float = 0.0) -> None:
        budget = self.interval + extra - (time.monotonic() - t_start)
        if budget > 0:
            self._stop.wait(budget)

    def _work(self, key: int, p: int) -> None:
        from ..suites._common import live_register_mix

        test, adapter = self.test, self.adapter
        proc = key * self.procs + p
        rng = random.Random((self.seed * 1_000_003) ^ proc)
        lo, hi = adapter.get("values", (0, 5))
        next_op = live_register_mix(
            rng, with_cas=bool(adapter.get("with_cas")), lo=lo, hi=hi
        )
        node = adapter["node"](test, key)
        template = adapter["client"](test, key)
        client = None
        backoff = self.BACKOFF_MIN
        connected_once = False
        try:
            while not self._stop.is_set():
                t_start = time.monotonic()
                if health.is_quarantined(test, node):
                    # Fast-fail: don't burn a dial timeout on a node
                    # the health monitor already wrote off.
                    inv = Op(type="invoke", f="read", value=None,
                             process=proc)
                    self._emit(key, inv)
                    self._emit(key, inv.complete(
                        FAIL, error="node-quarantined"))
                    telemetry.count("monitor.live.fastfail-quarantined")
                    self._pace(t_start, extra=0.05)
                    continue
                if client is None:
                    try:
                        client = template.open(test, node)
                    except Exception as e:  # noqa: BLE001 — retry forever
                        health.signal(test, node, "open-failed")
                        telemetry.count("monitor.live.open-retries")
                        log.debug("live open %s/%s failed: %r",
                                  node, proc, e)
                        self._stop.wait(backoff)
                        backoff = min(backoff * 2, self.BACKOFF_MAX)
                        continue
                    backoff = self.BACKOFF_MIN
                    if connected_once:
                        telemetry.count("monitor.live.client-reconnects")
                    connected_once = True
                f, value = next_op()
                inv = Op(type="invoke", f=f, value=value, process=proc)
                self._emit(key, inv)
                try:
                    comp = client.invoke(test, inv)
                except Exception as e:  # noqa: BLE001 — wound, not crash
                    telemetry.count("monitor.live.client-errors")
                    health.signal(test, node, "invoke-failed")
                    self._close(client)
                    client = None
                    comp = inv.complete(
                        INFO, error=f"{type(e).__name__}: {e}")
                self._emit(key, comp)
                if client is not None and comp.type == INFO:
                    err = str((comp.ext or {}).get("error", "")).lower()
                    if err.startswith(self.DESYNC_ERRORS):
                        self._close(client)
                        client = None
                        telemetry.count("monitor.live.client-drops")
                self._pace(t_start)
        finally:
            self._close(client)

    def _close(self, client: Any) -> None:
        if client is None:
            return
        try:
            client.close(self.test)
        except Exception:  # noqa: BLE001 — already broken
            pass


# ---------------------------------------------------------------------------
# Live nemesis driver: coverage-guided fault windows inside the run
# ---------------------------------------------------------------------------


class LiveNemesisDriver(threading.Thread):
    """Materializes one evolved fault schedule per window against the
    live cluster, fingerprints the outcome, and checkpoints the search
    frontier so a killed monitor resumes where it stopped.

    Window discipline: every op flows through the registry nemesis
    packages (ledger intent precedes every wound), the node-loss floor
    is enforced at evolution time, and the per-family final heals run
    in a `finally:` so neither an error nor a stop-flag leaves a wound
    open at thread exit."""

    FRONTIER_CAP = 32
    RECENT_CAP = 8

    def __init__(self, test: dict, *, families: tuple,
                 search_dir: str, store_dir: str, seed: int,
                 checker_status: Callable[[], dict],
                 gap_s: float = 0.75, seed_duration_s: float = 2.0):
        super().__init__(name="live-nemesis", daemon=True)
        self.test = test
        self.families = tuple(families)
        self.search_dir = search_dir
        self.store_dir = store_dir
        self.checker_status = checker_status
        self.gap_s = gap_s
        self.seed_duration_s = seed_duration_s
        self.rng = random.Random(seed ^ 0x5EED)
        nodes = list(test.get("nodes") or [])
        # Single-node suites must keep a floor of 0 — the whole point
        # of a kill window there is taking the only daemon down and
        # healing it; floor 1 would strip every node-down event.
        self.min_nodes = (0 if len(nodes) <= 1
                          else search.floor_from_test(test))
        self.coverage = search.CoverageMap()
        self.frontier: list[search.Schedule] = []
        self.windows = 0
        self.novel_windows = 0
        self.recent: list[dict] = []
        #: Nodes a kill/pause op of the current window took down on
        #: purpose — the supervisor must not "rescue" them mid-window.
        self.scheduled_down: set = set()
        self.faults_active = False
        self._halt = threading.Event()
        self._restore()

    # -- persistence ----------------------------------------------------

    def _restore(self) -> None:
        state = search.load_state(self.search_dir)
        if not state:
            return
        self.coverage.features = set(state.get("coverage") or [])
        self.windows = int(state.get("windows") or 0)
        self.novel_windows = int(state.get("novel-windows") or 0)
        for d in state.get("frontier") or []:
            try:
                self.frontier.append(search.Schedule.from_json(d))
            except Exception:  # noqa: BLE001 — drop a torn genome
                log.warning("live search: dropping unparsable genome")
        self.recent = list(state.get("recent") or [])[-self.RECENT_CAP:]
        telemetry.count("monitor.live.resumes")
        log.info(
            "live search resumed: %d windows, %d coverage features, "
            "%d frontier genomes", self.windows, len(self.coverage),
            len(self.frontier),
        )

    def _checkpoint(self) -> None:
        os.makedirs(self.search_dir, exist_ok=True)
        search._write_json_atomic(
            os.path.join(self.search_dir, search.STATE_FILE),
            {
                "mode": "live-monitor",
                "families": list(self.families),
                "windows": self.windows,
                "novel-windows": self.novel_windows,
                "coverage": sorted(self.coverage.features),
                "frontier": [s.to_json() for s in self.frontier],
                "recent": self.recent,
            },
        )
        _atomic_json(
            os.path.join(self.store_dir, LIVE_STATUS_FILE), self.status()
        )

    def status(self) -> dict:
        return {
            "families": list(self.families),
            "windows": self.windows,
            "novel-windows": self.novel_windows,
            "coverage": len(self.coverage),
            "frontier": len(self.frontier),
            "recent": self.recent,
        }

    # -- window machinery -----------------------------------------------

    def run(self) -> None:
        while not self._halt.is_set():
            try:
                self._window()
            except Exception:  # noqa: BLE001 — the driver must outlive
                telemetry.count("monitor.live.nemesis-errors")
                log.exception("live nemesis window %d failed",
                              self.windows)
            if self._halt.wait(self.gap_s):
                break

    def stop_and_join(self, timeout: float = 30.0) -> None:
        self._halt.set()
        if self.is_alive():
            self.join(timeout=timeout)
            if self.is_alive():
                log.warning("live nemesis driver did not stop in %.0fs",
                            timeout)

    def _sleep_until(self, deadline: float) -> None:
        while not self._halt.is_set():
            budget = deadline - time.monotonic()
            if budget <= 0:
                return
            self._halt.wait(min(budget, 0.25))

    def _window(self) -> None:
        test = self.test
        nodes = list(test.get("nodes") or [])
        sched = search.evolve(
            self.frontier, self.families, len(nodes), self.min_nodes,
            self.rng, window=self.windows,
            seed_duration=self.seed_duration_s,
        )
        pkg = search.compile_schedule(
            sched, {"interval": 1.0}, nodes=nodes
        )
        nem = pkg["nemesis"]
        led = fault_ledger.ledger_of(test)
        watermark = len(led.records()) if led else 0
        before = dict(telemetry.resilience_counters())
        status0 = self.checker_status()
        error: Optional[str] = None
        t0 = time.monotonic()
        self.faults_active = True
        try:
            if nem is not None:
                nem.setup(test)
            for t, op_d in pkg["timeline"]:
                if self._halt.is_set():
                    break
                self._sleep_until(t0 + t)
                self._mark_scheduled(op_d)
                if nem is not None:
                    nem.invoke(test, Op.from_dict(
                        dict(op_d, process="nemesis")))
                if op_d.get("f") in ("kill", "pause", "partition",
                                     "start-partition", "start-packet",
                                     "bump"):
                    telemetry.count("monitor.live.faults-injected")
            # Quiesce past the schedule horizon so wounds have time to
            # show up in the op stream before the heals land.
            self._sleep_until(t0 + sched.horizon)
        except Exception as e:  # noqa: BLE001 — heal anyway, fingerprint
            error = f"{type(e).__name__}: {e}"
            telemetry.count("monitor.live.nemesis-errors")
            log.warning("live window %d inject failed: %r",
                        self.windows, e)
        finally:
            # Guaranteed per-family heals: stop-flag, error, or clean
            # run, every family's idempotent final heal is applied.
            for fam in sorted(sched.families):
                heal = search._FINAL_HEAL.get(fam)
                if heal is None:
                    continue
                try:
                    if nem is not None:
                        nem.invoke(test, Op.from_dict(
                            dict(heal, process="nemesis")))
                    telemetry.count("monitor.live.heals")
                except Exception as e:  # noqa: BLE001 — keep healing
                    telemetry.count("monitor.live.heal-errors")
                    log.warning("live heal %s failed: %r", fam, e)
            if nem is not None:
                with contextlib.suppress(Exception):
                    nem.teardown(test)
            self.scheduled_down.clear()
            self.faults_active = False

        self._fingerprint(sched, watermark=watermark, before=before,
                          status0=status0, error=error, t0=t0, led=led)

    def _mark_scheduled(self, op_d: dict) -> None:
        f = op_d.get("f")
        if f in ("kill", "pause"):
            targets = op_d.get("value")
            self.scheduled_down.update(
                targets if isinstance(targets, (list, tuple))
                else self.test.get("nodes") or []
            )
        elif f in ("start", "resume"):
            targets = op_d.get("value")
            if isinstance(targets, (list, tuple)):
                self.scheduled_down.difference_update(targets)
            else:
                self.scheduled_down.clear()

    def _fingerprint(self, sched: search.Schedule, *, watermark: int,
                     before: dict, status0: dict, error: Optional[str],
                     t0: float, led) -> None:
        from ..forensics import window_fingerprint

        after = telemetry.resilience_counters()
        delta = {
            k: round(v - before.get(k, 0), 6)
            for k, v in after.items()
            if isinstance(v, (int, float)) and v - before.get(k, 0) > 0
        }
        status1 = self.checker_status()
        epoch_delta = (status1.get("epoch-restarts", 0)
                       - status0.get("epoch-restarts", 0))
        records = led.records()[watermark:] if led else []
        outcome = {
            "resilience": delta,
            # Epoch restarts are the live run's verdict signal: a
            # window that forced one is honestly unknown, not invalid.
            "results": {"valid": True if epoch_delta == 0 else None},
            "ledger": records,
            "hang": False,
            "error": error,
        }
        sig = search.signature(outcome)
        novel = self.coverage.add(sig)
        if novel:
            self.novel_windows += 1
            telemetry.count("monitor.live.novel-windows")
            self.frontier.append(sched)
            del self.frontier[:-self.FRONTIER_CAP]
        self.windows += 1
        telemetry.count("monitor.live.windows")
        outstanding = len(led.outstanding()) if led else 0
        telemetry.gauge("monitor.live.outstanding", outstanding)
        telemetry.gauge("monitor.live.coverage-features",
                        len(self.coverage))
        record = {
            "window": self.windows,
            "t": time.time(),
            "families": sorted(sched.families),
            "events": len(sched.events),
            "duration-s": round(time.monotonic() - t0, 3),
            "fingerprint": window_fingerprint(sig),
            "novel": sorted(novel),
            "epoch-restarts": epoch_delta,
            "ledger-records": len(records),
            "outstanding": outstanding,
            "error": error,
        }
        self.recent.append(record)
        del self.recent[:-self.RECENT_CAP]
        self._checkpoint()
        _write_dossier(
            self.store_dir, f"live-window-{self.windows}",
            dict(record, schedule=sched.to_json(),
                 signature=sorted(sig)),
        )
        log.info(
            "live window %d: families=%s novel=%d coverage=%d "
            "epoch-restarts=%d outstanding=%d",
            self.windows, ",".join(sorted(sched.families)), len(novel),
            len(self.coverage), epoch_delta, outstanding,
        )


# ---------------------------------------------------------------------------
# Daemon supervision: restarts outside fault windows
# ---------------------------------------------------------------------------


class _Supervisor(threading.Thread):
    """Detects a daemon that died *outside* a fault window (OOM, bug,
    disk full — not the nemesis) and restarts it via
    `retrying_daemon_start`, counted `monitor.live.daemon-restarts`.
    Scheduled wounds are the driver's business: the sweep skips nodes
    in `driver.scheduled_down`, quarantined nodes, and entire sweeps
    while a window is active."""

    def __init__(self, test: dict, driver: Optional[LiveNemesisDriver],
                 port_of: Callable[[dict, Any], int],
                 interval_s: float = 1.0, fails_needed: int = 2):
        super().__init__(name="live-supervisor", daemon=True)
        self.test = test
        self.driver = driver
        self.port_of = port_of
        self.interval_s = interval_s
        self.fails_needed = fails_needed
        self._halt = threading.Event()
        self._probe = health.tcp_probe(port_of)

    def stop_and_join(self, timeout: float = 5.0) -> None:
        self._halt.set()
        if self.is_alive():
            self.join(timeout=timeout)

    def run(self) -> None:
        fails: dict = {}
        while not self._halt.wait(self.interval_s):
            if self.driver is not None and self.driver.faults_active:
                continue
            down = (self.driver.scheduled_down
                    if self.driver is not None else set())
            for node in self.test.get("nodes") or []:
                if node in down or health.is_quarantined(
                        self.test, node):
                    fails.pop(node, None)
                    continue
                if self._probe(self.test, node):
                    fails.pop(node, None)
                    continue
                fails[node] = fails.get(node, 0) + 1
                if fails[node] < self.fails_needed:
                    continue
                fails.pop(node, None)
                self._restart(node)

    def _restart(self, node: Any) -> None:
        sess = (self.test.get("sessions") or {}).get(node)
        db = self.test.get("db")
        if sess is None or db is None:
            return
        log.warning("live supervisor: daemon on %s is down outside a "
                    "fault window; restarting", node)
        try:
            cutil.retrying_daemon_start(
                sess, lambda: db.start(self.test, sess, node),
                self.port_of(self.test, node),
                await_timeout_s=5.0, interval_s=0.1,
            )
            telemetry.count("monitor.live.daemon-restarts")
        except Exception as e:  # noqa: BLE001 — keep supervising
            telemetry.count("monitor.live.restart-failures")
            log.warning("live supervisor: restart of %s failed: %r",
                        node, e)


# ---------------------------------------------------------------------------
# Lifecycle: wiring a suite cluster into the standing loop
# ---------------------------------------------------------------------------


class LiveContext:
    """Owns the live run's cluster: resolves the suite adapter, sweeps
    a crashed predecessor's ledger with `core.repair`, boots the
    daemons, and runs the source/driver/supervisor trio.  `run_monitor`
    calls `start` before its loop, `shutdown` first in its finally (so
    leftovers still reach the checker), and `finalize` last (teardown,
    residue probe, summary block)."""

    def __init__(self, cfg) -> None:
        self.cfg = cfg
        self.adapter: dict = {}
        self.test: dict = {}
        self.source: Optional[LiveSource] = None
        self.driver: Optional[LiveNemesisDriver] = None
        self.supervisor: Optional[_Supervisor] = None
        self.repair_report: Optional[dict] = None
        self._stack = contextlib.ExitStack()
        self._led: Optional[fault_ledger.FaultLedger] = None
        self._hm = None

    # -- startup --------------------------------------------------------

    def start(self, checker_status: Callable[[], dict]) -> LiveSource:
        from .. import core as jcore
        from .. import db as jdb
        from .. import oses
        from ..control import with_sessions

        cfg = self.cfg
        self.adapter = resolve_suite(cfg.suite)
        test = self.adapter["test"]({
            "store-dir": cfg.store_dir,
            "nodes": list(cfg.nodes) or None,
        })
        live_dir = os.path.join(cfg.store_dir, LIVE_DIR)
        os.makedirs(live_dir, exist_ok=True)
        ledger_path = fault_ledger.ledger_path(live_dir)

        # Crash recovery: a predecessor SIGKILL'd between inject and
        # heal left outstanding intent — sweep it before touching the
        # cluster, so setup starts from a healed machine.
        if fault_ledger.read_outstanding(ledger_path):
            log.warning("live monitor: predecessor left outstanding "
                        "faults; running repair sweep")
            self.repair_report = jcore.repair(live_dir, dict(test))
            telemetry.count("monitor.live.resume-repairs")

        test["fault-ledger"] = self._led = fault_ledger.FaultLedger(
            ledger_path)
        test["health-probe"] = health.tcp_probe(self.adapter["port"])
        test["node-health"] = self._hm = health.HealthMonitor(test)
        test.setdefault("node-loss-policy", "tolerate")
        self.test = test

        self._stack.enter_context(with_sessions(test))
        oses.setup(test)
        jdb.cycle(test)

        families = self._families()
        search_dir = cfg.search_dir or os.path.join(live_dir, "search")
        if families:
            self.driver = LiveNemesisDriver(
                test, families=families, search_dir=search_dir,
                store_dir=cfg.store_dir, seed=cfg.seed,
                checker_status=checker_status,
                gap_s=cfg.window_gap_s,
                seed_duration_s=cfg.live_seed_duration_s,
            )
        if cfg.supervise:
            self.supervisor = _Supervisor(
                test, self.driver, self.adapter["port"])
        self.source = LiveSource(
            test, self.adapter, keys=cfg.keys,
            procs_per_key=cfg.procs_per_key, rate=cfg.rate,
            seed=cfg.seed,
        )
        self.source.start()
        if self.supervisor is not None:
            self.supervisor.start()
        if self.driver is not None:
            self.driver.start()
        log.info(
            "live monitor: suite=%s nodes=%s families=%s search=%s",
            cfg.suite, test.get("nodes"), list(families), search_dir,
        )
        return self.source

    def _families(self) -> tuple:
        cfg, adapter, test = self.cfg, self.adapter, self.test
        allowed = adapter.get("families")
        if cfg.live_faults:
            fams = tuple(cfg.live_faults)
            if fams == ("none",):
                return ()
            if allowed:
                dropped = [f for f in fams if f not in allowed]
                if dropped:
                    log.warning(
                        "live monitor: suite %s forbids %s (kept %s)",
                        cfg.suite, dropped, list(allowed))
                fams = tuple(f for f in fams if f in allowed)
            return fams
        if allowed:
            return tuple(allowed)
        # Capability-probed defaults: node-down families are always
        # safe; partition needs more than one node to part; packet
        # and clock faults are machine-global unless the transport
        # declares it isolates them (Remote.isolation) — a LocalRemote
        # tenant skips them, an ssh/k8s/netns-backed cluster gets the
        # full family set.
        fams = ["kill", "pause"]
        if len(test.get("nodes") or []) > 1:
            fams.insert(0, "partition")
        isolation = getattr(test.get("remote"), "isolation",
                            frozenset())
        if "net" in isolation:
            fams.append("packet")
        if "clock" in isolation:
            fams.append("clock")
        return tuple(fams)

    # -- shutdown -------------------------------------------------------

    def shutdown(self) -> list[tuple[int, Op]]:
        """Graceful-drain half of the teardown: stop the driver (its
        window `finally` heals any open wounds), stop the supervisor,
        and drain the source so the loop can feed the leftovers."""
        if self.driver is not None:
            self.driver.stop_and_join()
        if self.supervisor is not None:
            self.supervisor.stop_and_join()
        if self.source is not None:
            return self.source.drain()
        return []

    def finalize(self) -> dict:
        """Cluster teardown + the summary's "live" block: daemons are
        stopped (their kill/pause intents healed by tag), residue is
        probed while sessions are still open, and every handle closes."""
        from .. import db as jdb
        from .. import oses

        test = self.test
        status: dict = {
            "suite": self.cfg.suite,
            "nodes": list(test.get("nodes") or []),
            "driver": (self.driver.status()
                       if self.driver is not None else None),
            "repair-on-start": self.repair_report,
            "daemon-restarts": telemetry.counter_value(
                "monitor.live.daemon-restarts"),
            "client-reconnects": telemetry.counter_value(
                "monitor.live.client-reconnects"),
        }
        try:
            try:
                jdb.teardown(test)
            except Exception as e:  # noqa: BLE001 — still probe residue
                log.warning("live teardown failed: %r", e)
                status["teardown-error"] = f"{type(e).__name__}: {e}"
            if self._led is not None:
                for tag in ("db-kill", "db-pause"):
                    self._led.heal_matching(tag=tag, by="db-teardown")
                status["residue"] = fault_ledger.probe_residue(
                    test, ledger=self._led)
                status["outstanding-at-exit"] = len(
                    self._led.outstanding())
            with contextlib.suppress(Exception):
                oses.teardown(test)
        finally:
            if self._hm is not None:
                with contextlib.suppress(Exception):
                    self._hm.stop()
            if self._led is not None:
                with contextlib.suppress(Exception):
                    self._led.close()
            self._stack.close()
        return status
