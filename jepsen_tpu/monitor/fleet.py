"""`jepsen fleet` — supervised multi-tenant standing-verification fleet.

One supervisor process runs N tenants' live monitors (each a child
process wrapping ``run_monitor --suite``) against a shared
router-fronted checkerd federation, with hard tenant isolation as the
design invariant:

  - **Registry** (`fleet.json` + `fleet.jsonl`): the tenant set is a
    crash-safe document — every mutation appends a fsync'd journal
    record *before* the snapshot is atomically rewritten, so a SIGKILL
    between the two recovers by replaying journal records past the
    snapshot's sequence number, and a torn journal tail is skipped,
    never fatal.  Add/remove/drain/restart mutate one tenant without
    touching the others; concurrent mutators (CLI vs. supervisor)
    serialize on a flock'd lock file.

  - **Supervision tree**: each tenant child is restarted through a
    per-tenant :class:`~jepsen_tpu.checkerd.overload.CircuitBreaker`
    (exponential backoff + jitter); a child that dies before
    ``min_uptime_s`` counts as a crash-loop, and ``park_after``
    consecutive crash-loops park the tenant (persisted in the
    registry, dossier written) while every sibling keeps running.
    ``jepsen fleet restart --tenant X`` bumps the spec's generation;
    the reconcile loop notices and performs a rolling restart through
    the monitor's graceful SIGTERM drain path, escalating to SIGKILL
    only after ``drain_timeout_s``.

  - **Fault containment**: every tenant owns a private store dir
    (``<root>/tenants/<name>/store``) — and with it a private search
    dir, fault ledger, slo.jsonl, and daemon port range (ports hash
    from the store dir).  The registry rejects a tenant whose explicit
    node set intersects any sibling's, so one tenant's nemesis can
    never target another tenant's nodes; a monitor dying mid-inject is
    repaired by the existing ``core.repair`` sweep on *that tenant's*
    next start only, because the ledger lives under its store.

  - **Retention**: the supervisor periodically runs
    :func:`jepsen_tpu.monitor.retention.sweep` per tenant, bounding
    dossier count, age, and total disk under the spec's budget.

The supervisor's own observable state is ``fleet-status.json``
(atomic rewrite per tick) — the document `/api/fleet` and
``jepsen fleet status`` read.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, Optional, Tuple

from .. import telemetry
from ..checkerd.overload import CircuitBreaker
from .retention import RetentionPolicy, disk_bytes, sweep
from .loop import _atomic_json, _write_dossier

log = logging.getLogger("jepsen.fleet")

FLEET_FILE = "fleet.json"
FLEET_JOURNAL = "fleet.jsonl"
FLEET_LOCK = "fleet.lock"
FLEET_STATUS = "fleet-status.json"
TENANTS_DIR = "tenants"

#: Registry tenant states.  ``running`` is supervised; ``drained`` is
#: deliberately stopped (graceful) but still registered; ``parked`` is
#: the crash-loop escalation — stopped until an operator resumes it.
TENANT_STATES = ("running", "drained", "parked")


def tenant_store_dir(root: str, name: str) -> str:
    """The one directory a tenant may touch — store, search dir,
    fault ledger, series, forensics, slo.jsonl all live under it."""
    return os.path.join(root, TENANTS_DIR, name, "store")


# ---------------------------------------------------------------------------
# Tenant spec


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's standing-monitor configuration, as persisted in
    the registry.  ``generation`` is bumped by ``fleet restart`` to
    request a rolling restart; ``state`` tracks the registry-level
    lifecycle (see TENANT_STATES)."""

    name: str
    suite: str = "kvdb"
    nodes: Tuple[str, ...] = ()
    rate: float = 50.0
    duration_s: float = 3600.0       # epoch length; clean exit => restart
    keys: int = 2
    procs_per_key: int = 2
    cadence_s: float = 1.0
    live_faults: Tuple[str, ...] = ()
    sinks: Tuple[str, ...] = ()
    endpoint: Optional[str] = None   # overrides the fleet-wide endpoint
    weight: float = 1.0              # DRR weight (daemon --tenant-weight)
    deadline_s: float = 120.0        # tee verdict deadline (shed budget)
    tee_window_ops: int = 4096
    retain_dossiers: int = 64
    retain_days: float = 14.0
    retain_bytes: Optional[int] = None
    state: str = "running"
    generation: int = 0

    def to_json(self) -> dict:
        return {
            "name": self.name, "suite": self.suite,
            "nodes": list(self.nodes), "rate": self.rate,
            "duration-s": self.duration_s, "keys": self.keys,
            "procs-per-key": self.procs_per_key,
            "cadence-s": self.cadence_s,
            "live-faults": list(self.live_faults),
            "sinks": list(self.sinks), "endpoint": self.endpoint,
            "weight": self.weight, "deadline-s": self.deadline_s,
            "tee-window-ops": self.tee_window_ops,
            "retain-dossiers": self.retain_dossiers,
            "retain-days": self.retain_days,
            "retain-bytes": self.retain_bytes,
            "state": self.state, "generation": self.generation,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "TenantSpec":
        return cls(
            name=doc["name"], suite=doc.get("suite", "kvdb"),
            nodes=tuple(doc.get("nodes") or ()),
            rate=float(doc.get("rate", 50.0)),
            duration_s=float(doc.get("duration-s", 3600.0)),
            keys=int(doc.get("keys", 2)),
            procs_per_key=int(doc.get("procs-per-key", 2)),
            cadence_s=float(doc.get("cadence-s", 1.0)),
            live_faults=tuple(doc.get("live-faults") or ()),
            sinks=tuple(doc.get("sinks") or ()),
            endpoint=doc.get("endpoint"),
            weight=float(doc.get("weight", 1.0)),
            deadline_s=float(doc.get("deadline-s", 120.0)),
            tee_window_ops=int(doc.get("tee-window-ops", 4096)),
            retain_dossiers=int(doc.get("retain-dossiers", 64)),
            retain_days=float(doc.get("retain-days", 14.0)),
            retain_bytes=doc.get("retain-bytes"),
            state=doc.get("state", "running"),
            generation=int(doc.get("generation", 0)),
        )

    def retention_policy(self) -> RetentionPolicy:
        return RetentionPolicy(retain_dossiers=self.retain_dossiers,
                               retain_days=self.retain_days,
                               budget_bytes=self.retain_bytes)


# ---------------------------------------------------------------------------
# Crash-safe registry


class FleetRegistry:
    """Tenant registry: `fleet.json` snapshot + `fleet.jsonl` journal.

    Durability protocol (the jepsenlint append→fsync→apply rule):
    every mutation (1) takes the flock, (2) appends one journal record
    with the next sequence number and fsyncs it, (3) atomically
    rewrites the snapshot.  A crash after (2) is recovered by
    :meth:`load` replaying journal records with ``seq >`` the
    snapshot's; a torn final journal line is skipped.
    """

    def __init__(self, root: str) -> None:
        self.root = root
        self.path = os.path.join(root, FLEET_FILE)
        self.journal = os.path.join(root, FLEET_JOURNAL)
        self._lockpath = os.path.join(root, FLEET_LOCK)

    # -- reads ----------------------------------------------------------

    def _read_snapshot(self) -> Tuple[int, Dict[str, TenantSpec]]:
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return 0, {}
        tenants = {}
        for name, td in (doc.get("tenants") or {}).items():
            try:
                tenants[name] = TenantSpec.from_json(td)
            except (KeyError, TypeError, ValueError):
                continue
        return int(doc.get("seq", 0)), tenants

    def _read_journal(self) -> list:
        recs = []
        try:
            with open(self.journal) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        recs.append(json.loads(line))
                    except ValueError:
                        break  # torn tail — nothing after it is trusted
        except OSError:
            pass
        return recs

    @staticmethod
    def _apply(tenants: Dict[str, TenantSpec], rec: dict) -> None:
        op, name = rec.get("op"), rec.get("tenant")
        if op == "add" and rec.get("spec"):
            try:
                tenants[name] = TenantSpec.from_json(rec["spec"])
            except (KeyError, TypeError, ValueError):
                pass
        elif op == "remove":
            tenants.pop(name, None)
        elif op == "set-state" and name in tenants:
            st = rec.get("state")
            if st in TENANT_STATES:
                tenants[name] = replace(tenants[name], state=st)
        elif op == "bump-generation" and name in tenants:
            sp = tenants[name]
            tenants[name] = replace(sp, generation=sp.generation + 1)

    def load(self) -> Dict[str, TenantSpec]:
        """Snapshot + journal replay; torn-tail tolerant, lock-free
        (readers never block the supervisor or the CLI)."""
        seq, tenants = self._read_snapshot()
        for rec in self._read_journal():
            if int(rec.get("seq", 0)) > seq:
                self._apply(tenants, rec)
        return tenants

    def max_seq(self) -> int:
        seq, _ = self._read_snapshot()
        for rec in self._read_journal():
            seq = max(seq, int(rec.get("seq", 0)))
        return seq

    # -- mutations ------------------------------------------------------

    def _commit(self, rec: dict) -> Dict[str, TenantSpec]:
        """Journal-then-snapshot under the registry lock."""
        import fcntl
        os.makedirs(self.root, exist_ok=True)
        with open(self._lockpath, "a") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            tenants = self.load()
            seq = self.max_seq() + 1
            rec = dict(rec, seq=seq, t=time.time())
            self._apply(tenants, rec)
            with open(self.journal, "a") as jf:
                jf.write(json.dumps(rec, sort_keys=True) + "\n")
                jf.flush()
                os.fsync(jf.fileno())
            _atomic_json(self.path, {
                "seq": seq,
                "tenants": {n: s.to_json()
                            for n, s in sorted(tenants.items())},
            })
            return tenants

    def add(self, spec: TenantSpec) -> None:
        """Register a tenant.  Rejects a name collision and — the
        cross-tenant containment invariant — any explicit node that
        another tenant already owns."""
        if not spec.name or "/" in spec.name or spec.name.startswith("."):
            raise ValueError(f"bad tenant name {spec.name!r}")
        current = self.load()
        if spec.name in current:
            raise ValueError(f"tenant {spec.name!r} already registered")
        mine = set(spec.nodes)
        for other in current.values():
            shared = mine & set(other.nodes)
            if shared:
                raise ValueError(
                    f"tenant {spec.name!r} claims nodes "
                    f"{sorted(shared)} owned by {other.name!r}: "
                    f"cross-tenant nemesis targeting is forbidden")
        self._commit({"op": "add", "tenant": spec.name,
                      "spec": spec.to_json()})
        telemetry.count("fleet.tenants-added")

    def remove(self, name: str) -> None:
        self._commit({"op": "remove", "tenant": name})
        telemetry.count("fleet.tenants-removed")

    def set_state(self, name: str, state: str) -> None:
        if state not in TENANT_STATES:
            raise ValueError(f"bad tenant state {state!r}")
        if name not in self.load():
            raise ValueError(f"unknown tenant {name!r}")
        self._commit({"op": "set-state", "tenant": name, "state": state})

    def bump_generation(self, name: str) -> None:
        if name not in self.load():
            raise ValueError(f"unknown tenant {name!r}")
        self._commit({"op": "bump-generation", "tenant": name})


# ---------------------------------------------------------------------------
# Supervision


def default_spawn(spec: TenantSpec, store: str,
                  endpoint: Optional[str]) -> subprocess.Popen:
    """Spawn one tenant's live monitor as `python -m
    jepsen_tpu.suites.<suite> monitor ...` — the same child the live
    smoke drives, plus tenant identity for the checkerd tee."""
    argv = [
        sys.executable, "-m", f"jepsen_tpu.suites.{spec.suite}",
        "monitor", "--suite", spec.suite, "--store-dir", store,
        "--search-dir", os.path.join(store, "search"),
        "--rate", str(spec.rate), "--duration", str(spec.duration_s),
        "--keys", str(spec.keys),
        "--procs-per-key", str(spec.procs_per_key),
        "--cadence", str(spec.cadence_s),
        "--tenant", spec.name, "--tee-deadline", str(spec.deadline_s),
        "--tee-window", str(spec.tee_window_ops),
    ]
    if spec.live_faults:
        argv += ["--live-faults", ",".join(spec.live_faults)]
    ep = spec.endpoint or endpoint
    if ep:
        argv += ["--endpoint", ep]
    for n in spec.nodes:
        argv += ["--node", n]
    for s in spec.sinks:
        argv += ["--sink", s]
    return subprocess.Popen(argv)


class _Child:
    """Runtime state for one tenant's monitor process."""

    def __init__(self, spec: TenantSpec, clock: Callable[[], float],
                 rng: Callable[[], float], breaker_base_s: float,
                 breaker_max_s: float, park_after: int) -> None:
        self.spec = spec
        self.proc: Optional[subprocess.Popen] = None
        self.started_at: Optional[float] = None
        self.generation = spec.generation
        self.restarts = 0
        self.crash_loops = 0
        self.park_after = park_after
        self.last_exit: Optional[int] = None
        self.draining_until: Optional[float] = None
        self.restart_after_drain = False
        self.last_sweep: dict = {}
        self.breaker = CircuitBreaker(
            failure_threshold=max(1, park_after - 1) or 1,
            base_backoff_s=breaker_base_s, max_backoff_s=breaker_max_s,
            clock=clock, rng=rng)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class FleetSupervisor:
    """The reconcile loop: registry is the desired state, children are
    the actual state, every tick converges one toward the other."""

    def __init__(self, root: str, *, endpoint: Optional[str] = None,
                 tick_s: float = 1.0, park_after: int = 3,
                 min_uptime_s: float = 5.0, drain_timeout_s: float = 20.0,
                 retention_interval_s: float = 30.0,
                 breaker_base_s: float = 0.5, breaker_max_s: float = 30.0,
                 spawn: Optional[Callable[..., subprocess.Popen]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[Callable[[], float]] = None) -> None:
        self.root = os.path.abspath(root)
        self.registry = FleetRegistry(self.root)
        self.endpoint = endpoint
        self.tick_s = tick_s
        self.park_after = max(1, park_after)
        self.min_uptime_s = min_uptime_s
        self.drain_timeout_s = drain_timeout_s
        self.retention_interval_s = retention_interval_s
        self.breaker_base_s = breaker_base_s
        self.breaker_max_s = breaker_max_s
        self.spawn = spawn or default_spawn
        self.clock = clock
        self.rng = rng or __import__("random").random
        self.children: Dict[str, _Child] = {}
        self._last_retention = 0.0
        self.status_path = os.path.join(self.root, FLEET_STATUS)

    # -- child lifecycle ------------------------------------------------

    def _start(self, ch: _Child) -> None:
        store = tenant_store_dir(self.root, ch.spec.name)
        os.makedirs(store, exist_ok=True)
        try:
            ch.proc = self.spawn(ch.spec, store, self.endpoint)
        except OSError as e:
            log.warning("fleet: spawn %s failed: %r", ch.spec.name, e)
            ch.breaker.record_failure()
            telemetry.count("fleet.spawn-errors")
            return
        ch.started_at = self.clock()
        ch.generation = ch.spec.generation
        telemetry.count("fleet.tenant-starts")
        log.info("fleet: started tenant %s (pid %s, gen %d)",
                 ch.spec.name, ch.proc.pid, ch.generation)

    def _begin_drain(self, ch: _Child, *, restart_after: bool) -> None:
        """Graceful stop via the monitor's SIGTERM drain path; SIGKILL
        only after drain_timeout_s (handled in _reap_drain)."""
        if not ch.alive() or ch.draining_until is not None:
            ch.restart_after_drain = ch.restart_after_drain or restart_after
            return
        try:
            ch.proc.send_signal(signal.SIGTERM)
        except OSError:
            pass
        ch.draining_until = self.clock() + self.drain_timeout_s
        ch.restart_after_drain = restart_after
        telemetry.count("fleet.drains")

    def _reap(self, ch: _Child) -> None:
        """Handle an exited child: crash-loop accounting, parking."""
        rc = ch.proc.poll()
        ch.last_exit = rc
        uptime = (self.clock() - ch.started_at
                  if ch.started_at is not None else 0.0)
        drained = ch.draining_until is not None
        ch.proc = None
        ch.started_at = None
        ch.draining_until = None
        if drained:
            return  # deliberate stop, not a crash
        if uptime >= self.min_uptime_s:
            # A long-lived child that exits (epoch end, clean rc) is
            # healthy: reset the loop counter, restart next tick.
            ch.crash_loops = 0
            ch.breaker.record_success()
            return
        ch.crash_loops += 1
        ch.breaker.record_failure()
        telemetry.count("fleet.crash-loops")
        log.warning("fleet: tenant %s crash-loop %d/%d (rc=%s, "
                    "uptime %.1fs)", ch.spec.name, ch.crash_loops,
                    self.park_after, rc, uptime)
        if ch.crash_loops >= self.park_after:
            self._park(ch, rc, uptime)

    def _park(self, ch: _Child, rc: Optional[int], uptime: float) -> None:
        telemetry.count("fleet.tenants-parked")
        log.error("fleet: parking tenant %s after %d crash-loops",
                  ch.spec.name, ch.crash_loops)
        try:
            self.registry.set_state(ch.spec.name, "parked")
        except ValueError:
            pass  # tenant was removed out from under us
        store = tenant_store_dir(self.root, ch.spec.name)
        _write_dossier(store, f"fleet-parked-{int(time.time())}", {
            "kind": "fleet-parked", "tenant": ch.spec.name,
            "crash-loops": ch.crash_loops, "last-exit": rc,
            "last-uptime-s": round(uptime, 3),
            "generation": ch.generation, "t": time.time(),
        })

    # -- reconcile ------------------------------------------------------

    def _tick(self) -> None:
        telemetry.count("fleet.reconciles")
        specs = self.registry.load()
        now = self.clock()

        # Forget removed tenants (drain first).
        for name in list(self.children):
            if name not in specs:
                ch = self.children[name]
                if ch.alive():
                    self._begin_drain(ch, restart_after=False)
                    if ch.draining_until is not None and \
                            now < ch.draining_until:
                        continue
                    self._force_kill(ch)
                if ch.proc is not None:
                    self._reap(ch)
                del self.children[name]

        for name, spec in specs.items():
            ch = self.children.get(name)
            if ch is None:
                ch = self.children[name] = _Child(
                    spec, self.clock, self.rng, self.breaker_base_s,
                    self.breaker_max_s, self.park_after)
            prev_state = ch.spec.state
            ch.spec = spec
            if spec.state != "parked" and prev_state == "parked":
                # Operator resumed a parked tenant: clean slate.
                ch.crash_loops = 0
                ch.breaker = CircuitBreaker(
                    failure_threshold=max(1, self.park_after - 1),
                    base_backoff_s=self.breaker_base_s,
                    max_backoff_s=self.breaker_max_s,
                    clock=self.clock, rng=self.rng)

            # Drain-deadline escalation is state-independent.
            if ch.alive() and ch.draining_until is not None \
                    and now >= ch.draining_until:
                self._force_kill(ch)

            if ch.proc is not None and not ch.alive():
                self._reap(ch)

            if spec.state in ("drained", "parked"):
                if ch.alive():
                    self._begin_drain(ch, restart_after=False)
                continue

            # state == running
            if ch.alive():
                if ch.generation != spec.generation:
                    # Rolling restart: drain, then relaunch.
                    self._begin_drain(ch, restart_after=True)
                continue
            want_start = (ch.restart_after_drain
                          or ch.last_exit is None
                          or ch.crash_loops < self.park_after)
            if want_start and ch.breaker.allow():
                was_restart = ch.last_exit is not None \
                    or ch.restart_after_drain
                ch.restart_after_drain = False
                self._start(ch)
                if was_restart and ch.proc is not None:
                    ch.restarts += 1
                    telemetry.count("fleet.tenant-restarts")

    def _force_kill(self, ch: _Child) -> None:
        try:
            ch.proc.kill()
        except OSError:
            pass
        try:
            ch.proc.wait(timeout=10)
        except Exception:  # noqa: BLE001 — already escalating
            pass
        telemetry.count("fleet.drain-kills")

    # -- retention ------------------------------------------------------

    def _retention_pass(self) -> None:
        now = self.clock()
        if now - self._last_retention < self.retention_interval_s:
            return
        self._last_retention = now
        for name, ch in self.children.items():
            store = tenant_store_dir(self.root, name)
            if not os.path.isdir(store):
                continue
            try:
                ch.last_sweep = sweep(store, ch.spec.retention_policy())
            except OSError as e:
                telemetry.count("fleet.retention.errors")
                log.warning("fleet: retention sweep %s failed: %r",
                            name, e)

    # -- status ---------------------------------------------------------

    def status(self) -> dict:
        tenants = {}
        for name, ch in sorted(self.children.items()):
            store = tenant_store_dir(self.root, name)
            tenants[name] = {
                "state": ch.spec.state,
                "suite": ch.spec.suite,
                "alive": ch.alive(),
                "pid": ch.proc.pid if ch.alive() else None,
                "generation": ch.generation,
                "target-generation": ch.spec.generation,
                "restarts": ch.restarts,
                "crash-loops": ch.crash_loops,
                "last-exit": ch.last_exit,
                "draining": ch.draining_until is not None,
                "breaker": ch.breaker.stats(),
                "weight": ch.spec.weight,
                "deadline-s": ch.spec.deadline_s,
                "disk-bytes": disk_bytes(store)
                if os.path.isdir(store) else 0,
                "retention": ch.last_sweep,
                "store-dir": store,
            }
        return {"t": time.time(), "root": self.root,
                "endpoint": self.endpoint, "tenants": tenants}

    def _write_status(self) -> None:
        _atomic_json(self.status_path, self.status())

    # -- main loop ------------------------------------------------------

    def run(self, stop: Optional[threading.Event] = None) -> int:
        """Supervise until ``stop`` is set (or signals arrive when the
        caller installed none).  Children are drained on exit."""
        stop = stop or threading.Event()
        os.makedirs(self.root, exist_ok=True)
        try:
            while not stop.is_set():
                self._tick()
                self._retention_pass()
                self._write_status()
                stop.wait(self.tick_s)
        finally:
            self.shutdown()
        return 0

    def shutdown(self) -> None:
        """Drain every child through SIGTERM, escalate at the drain
        deadline, and leave a final status snapshot."""
        deadline = self.clock() + self.drain_timeout_s
        for ch in self.children.values():
            if ch.alive():
                self._begin_drain(ch, restart_after=False)
        while self.clock() < deadline and \
                any(ch.alive() for ch in self.children.values()):
            time.sleep(0.1)
        for ch in self.children.values():
            if ch.alive():
                self._force_kill(ch)
            if ch.proc is not None:
                self._reap(ch)
        self._write_status()
        log.info("fleet: shut down")


def read_status(root: str) -> dict:
    """fleet-status.json, torn-tolerant (atomic writes make a torn
    read impossible; missing file yields {})."""
    try:
        with open(os.path.join(root, FLEET_STATUS)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}
