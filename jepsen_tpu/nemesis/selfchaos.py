"""Jepsen turned on its own checker fleet: self-chaos for checkerd.

The nemesis-search machinery (nemesis/search.py) fuzzes *databases
under test*; this module points the same discipline at the verification
infrastructure itself.  A **chaos schedule** — a seeded, timed sequence
of fault events against a router + N-daemon fleet — is compiled
deterministically (`compile_schedule`), injected against live child
processes while multi-tenant load runs (`run_chaos`), and the fleet's
own behavior is recorded as a Jepsen history (`ChaosHistory`) whose
invariants `check_invariants` verifies:

  * **exactly-one-verdict** — every acked TICKET eventually yields a
    verdict, and every verdict observed for a ticket is byte-identical
    (journal replays and router failovers may recompute it, but per-key
    verdicts are deterministic, so the digests must agree);
  * **honest sheds** — an admission refusal is a structured F_SHED with
    a positive retry-after, never a hang and never an ERROR-shaped
    silent drop;
  * **fairness** — a whale tenant saturating its queue must not push a
    light tenant's queue-wait p95 beyond the DRR starvation bound.

Fault families (each deterministic given the schedule seed):

  * ``daemon-kill``   — SIGKILL a daemon; restart on its --queue
                        journal after `duration` (replay must cover
                        every acked ticket).
  * ``daemon-pause``  — SIGSTOP / SIGCONT (a slow, not dead, peer).
  * ``router-kill``   — SIGKILL the router; restart on its journal.
  * ``partition``     — the daemon's FlakyProxy drops connections.
  * ``slow-peer``     — the proxy delays every forwarded chunk.
  * ``journal-tear``  — garbage appended to a (killed) daemon's queue
                        file; reopen must truncate the torn tail.
  * ``disk-full``     — journal appends fail with ENOSPC via the
                        ``JEPSEN_QUEUE_FAULT`` file: indirection
                        (checkerd/journal.py) — degraded durability,
                        never a crash.
  * ``brownout``      — a forced brownout level via the
                        ``JEPSEN_BROWNOUT_FORCE`` file: indirection
                        (checkerd/overload.py) — optional plan passes
                        drop, verdicts stay sound.

Chaos telemetry lives in the ``chaos.*`` namespace (declared in
analysis/rules/protocol.py).  ``tools/chaos_smoke.py`` wires a small
schedule into CI.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import random
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time
from typing import Any, Optional, Sequence

from .. import telemetry
from .ledger import FaultLedger

log = logging.getLogger(__name__)

#: Every injectable fault family.  `daemon-*`, `partition`, `slow-peer`
#: and `journal-tear`/`disk-full` target one daemon; `router-kill`
#: targets the router; `brownout` targets one daemon's controller.
FAMILIES = (
    "daemon-kill",
    "daemon-pause",
    "router-kill",
    "partition",
    "slow-peer",
    "journal-tear",
    "disk-full",
    "brownout",
)

#: Faults that require the target daemon to be down while they apply
#: (tearing a live daemon's journal races its own appends).
_NEEDS_DOWN = frozenset({"journal-tear"})


# ---------------------------------------------------------------------------
# Schedules
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChaosFault:
    """One timed fault: inject at `t`, heal at `t + duration_s`.
    `target` is a daemon index, or -1 for the router.  `salt` seeds the
    event's private RNG (Random(schedule.seed ^ salt)), the same
    determinism contract as nemesis-search events."""

    family: str
    t: float
    duration_s: float
    target: int
    salt: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    """A seeded fault timeline against an n-daemon fleet."""

    seed: int
    duration_s: float
    faults: tuple

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "duration-s": self.duration_s,
            "faults": [f.to_dict() for f in self.faults],
        }


def compile_schedule(
    seed: int,
    *,
    n_daemons: int,
    duration_s: float = 20.0,
    n_faults: int = 4,
    families: Sequence[str] = FAMILIES,
) -> ChaosSchedule:
    """Compiles a deterministic schedule: same seed, same timeline.
    Fault times land in the middle 70% of the window so load exists on
    both sides of every injection; durations are bounded so every fault
    heals before the run ends."""
    rng = random.Random(seed)
    faults = []
    for _ in range(n_faults):
        family = rng.choice(list(families))
        t = rng.uniform(0.15, 0.7) * duration_s
        dur = rng.uniform(0.1, 0.25) * duration_s
        if family == "router-kill":
            target = -1
        else:
            target = rng.randrange(max(1, n_daemons))
        faults.append(ChaosFault(
            family=family, t=round(t, 3), duration_s=round(dur, 3),
            target=target, salt=rng.getrandbits(32),
        ))
    faults.sort(key=lambda f: (f.t, f.salt))
    return ChaosSchedule(seed=seed, duration_s=float(duration_s),
                         faults=tuple(faults))


# ---------------------------------------------------------------------------
# The fleet history + invariants
# ---------------------------------------------------------------------------


def verdict_digest(result: dict) -> str:
    """Canonical digest of a verdict's observable content.  Meta
    (spans, pids, addresses) varies across replays by design; validity
    and per-key results must not."""
    krs = result.get("key-results")
    core = {"valid": result.get("valid"), "key-results": krs}
    blob = json.dumps(core, sort_keys=True, separators=(",", ":"),
                      default=repr)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class ChaosHistory:
    """Thread-safe record of the fleet's observable behavior: acks,
    verdicts, sheds, errors, fault injections — the Jepsen history the
    invariant checker runs over."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ops: list[dict] = []
        self._t0 = time.monotonic()

    def record(self, type: str, **fields: Any) -> None:  # noqa: A002
        op = {"t": round(time.monotonic() - self._t0, 4), "type": type}
        op.update(fields)
        with self._lock:
            self._ops.append(op)
        telemetry.count(f"chaos.op.{type}")

    def ops(self, type: Optional[str] = None) -> list[dict]:  # noqa: A002
        with self._lock:
            if type is None:
                return list(self._ops)
            return [o for o in self._ops if o["type"] == type]

    def stats(self) -> dict:
        with self._lock:
            kinds: dict[str, int] = {}
            for o in self._ops:
                kinds[o["type"]] = kinds.get(o["type"], 0) + 1
            return {"ops": len(self._ops), "kinds": kinds}


def check_invariants(
    history: ChaosHistory,
    *,
    fairness_bound_s: Optional[float] = None,
    light_tenant: Optional[str] = None,
) -> list[str]:
    """Verifies the fleet invariants over a chaos history; returns a
    list of violation strings (empty = the fleet held).  Counted under
    ``chaos.invariant-violation``."""
    violations: list[str] = []

    acked: dict[str, dict] = {}
    verdicts: dict[str, list[dict]] = {}
    for op in history.ops("ack"):
        t = op.get("ticket")
        if t:
            acked[t] = op
    for op in history.ops("verdict"):
        t = op.get("ticket")
        if t:
            verdicts.setdefault(t, []).append(op)

    # 1. Exactly-one-verdict: every acked ticket produced a verdict...
    for t, op in sorted(acked.items()):
        if t not in verdicts:
            violations.append(
                f"lost-verdict: ticket {t} (tenant "
                f"{op.get('tenant')!r}) was acked at t={op['t']} but "
                f"never yielded a verdict"
            )
    # ...and every verdict observed for a ticket is byte-identical.
    for t, vs in sorted(verdicts.items()):
        digests = {v.get("digest") for v in vs}
        if len(digests) > 1:
            violations.append(
                f"replay-divergence: ticket {t} yielded "
                f"{len(digests)} distinct verdict digests {sorted(digests)}"
            )

    # 2. Honest sheds: structured retry-after, always positive.
    for op in history.ops("shed"):
        ra = op.get("retry_after_s")
        if not isinstance(ra, (int, float)) or ra <= 0:
            violations.append(
                f"dishonest-shed: shed at t={op['t']} (tenant "
                f"{op.get('tenant')!r}) carried retry-after {ra!r}"
            )

    # 3. Fairness: the light tenant's queue-wait p95 under the bound.
    if fairness_bound_s is not None and light_tenant is not None:
        waits = sorted(
            op["wait_s"] for op in history.ops("verdict")
            if op.get("tenant") == light_tenant
            and isinstance(op.get("wait_s"), (int, float))
        )
        if waits:
            import math

            p95 = waits[min(len(waits) - 1,
                            int(math.ceil(0.95 * len(waits))) - 1)]
            if p95 > fairness_bound_s:
                violations.append(
                    f"unfair: light tenant {light_tenant!r} queue-wait "
                    f"p95 {p95:.3f}s exceeds the fairness bound "
                    f"{fairness_bound_s:.3f}s"
                )
    for v in violations:
        telemetry.count("chaos.invariant-violation")
        log.warning("chaos invariant violation: %s", v)
    return violations


# ---------------------------------------------------------------------------
# The socket shim: partitions and slow peers without netns privileges
# ---------------------------------------------------------------------------


class FlakyProxy:
    """A TCP forwarder in front of one daemon.  Modes: ``ok`` forwards
    transparently; ``drop`` refuses new connections and severs live
    ones (a partition); ``slow`` delays every forwarded chunk (a slow
    peer).  The router is pointed at proxy addresses, so flipping a
    mode partitions exactly one router->daemon edge."""

    def __init__(self, backend: str, host: str = "127.0.0.1"):
        self.backend = backend
        self.mode = "ok"
        self.delay_s = 0.0
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                outer._handle(self.request)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Server((host, 0), _Handler)
        self.addr = "%s:%d" % self._srv.server_address[:2]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="flaky-proxy",
            daemon=True,
        )
        self._thread.start()

    def set_mode(self, mode: str, delay_s: float = 0.0) -> None:
        self.mode = mode
        self.delay_s = delay_s
        telemetry.count(f"chaos.proxy.{mode}")
        if mode == "drop":
            with self._lock:
                conns, self._conns = self._conns, []
            for s in conns:
                try:
                    s.close()
                except OSError:
                    pass

    def _handle(self, client: socket.socket) -> None:
        if self.mode == "drop":
            client.close()
            return
        from ..checkerd.protocol import parse_addr

        try:
            up = socket.create_connection(parse_addr(self.backend),
                                          timeout=5.0)
        except OSError:
            client.close()
            return
        with self._lock:
            self._conns += [client, up]

        def pump(src: socket.socket, dst: socket.socket) -> None:
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    if self.mode == "drop":
                        break
                    if self.mode == "slow" and self.delay_s > 0:
                        time.sleep(self.delay_s)
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        s.close()
                    except OSError:
                        pass

        t = threading.Thread(target=pump, args=(up, client), daemon=True)
        t.start()
        pump(client, up)
        t.join(timeout=5.0)

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self.set_mode("drop")


# ---------------------------------------------------------------------------
# The fleet under test: router + N daemons as child processes
# ---------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_listening(addr: str, timeout_s: float = 20.0) -> None:
    from ..checkerd.protocol import parse_addr

    host, port = parse_addr(addr)
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        try:
            with socket.create_connection((host, port), timeout=1.0):
                return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"{addr} not listening after {timeout_s}s")


class ChaosFleet:
    """A router + N checkerd daemons, each a child process on its own
    --queue journal, each daemon fronted by a FlakyProxy the router
    dials through.  All fault injectors live here so a schedule event
    maps to one method call."""

    def __init__(self, n_daemons: int, workdir: str, *,
                 tenant_weights: Optional[dict[str, float]] = None,
                 batch_window_s: float = 0.02,
                 metrics: bool = False):
        self.workdir = workdir
        self.n = n_daemons
        self.batch_window_s = batch_window_s
        self.tenant_weights = dict(tenant_weights or {})
        os.makedirs(workdir, exist_ok=True)
        self.daemon_ports = [_free_port() for _ in range(n_daemons)]
        self.metrics_ports = [_free_port() if metrics else -1
                              for _ in range(n_daemons)]
        self.router_port = _free_port()
        self.daemons: list[Optional[subprocess.Popen]] = [None] * n_daemons
        self.paused = [False] * n_daemons
        self.router: Optional[subprocess.Popen] = None
        self.proxies: list[FlakyProxy] = []
        for i in range(n_daemons):
            self.proxies.append(
                FlakyProxy(f"127.0.0.1:{self.daemon_ports[i]}"))
        # Same intent/healed ledger discipline as the real nemesis: a
        # crashed chaos driver leaves an auditable record of which
        # faults are still outstanding (a SIGSTOPped daemon, a dropped
        # proxy edge) instead of a mystery-wedged fleet.
        self.ledger = FaultLedger(os.path.join(workdir, "chaos.ledger"))
        self._ledger_ids: dict[tuple[str, int, int], int] = {}

    # -- paths & env ---------------------------------------------------------

    def daemon_addr(self, i: int) -> str:
        return f"127.0.0.1:{self.daemon_ports[i]}"

    @property
    def router_addr(self) -> str:
        return f"127.0.0.1:{self.router_port}"

    def _queue_path(self, i: int) -> str:
        return os.path.join(self.workdir, f"d{i}.queue")

    def _diskfull_path(self, i: int) -> str:
        return os.path.join(self.workdir, f"d{i}.diskfull")

    def _brownout_path(self, i: int) -> str:
        return os.path.join(self.workdir, f"d{i}.brownout")

    def _daemon_env(self, i: int) -> dict:
        from ..checkerd import journal, overload

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env[journal.FAULT_ENV] = "file:" + self._diskfull_path(i)
        env[overload.FORCE_ENV] = "file:" + self._brownout_path(i)
        return env

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for i in range(self.n):
            self.start_daemon(i)
        self.start_router()

    def start_daemon(self, i: int) -> None:
        args = [
            sys.executable, "-m", "jepsen_tpu.checkerd.server",
            "--host", "127.0.0.1", "--port", str(self.daemon_ports[i]),
            "--platform", "cpu",
            "--batch-window", str(self.batch_window_s),
            "--metrics-port", str(self.metrics_ports[i]),
            "--queue", self._queue_path(i),
        ]
        for t, w in sorted(self.tenant_weights.items()):
            args += ["--tenant-weight", f"{t}={w}"]
        self.daemons[i] = subprocess.Popen(
            args, env=self._daemon_env(i),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        self.paused[i] = False
        _wait_listening(self.daemon_addr(i))

    def start_router(self) -> None:
        args = [
            sys.executable, "-m", "jepsen_tpu.checkerd.router",
            "--host", "127.0.0.1", "--port", str(self.router_port),
            "--metrics-port", "-1",
            "--probe-interval", "0.5",
            "--queue", os.path.join(self.workdir, "router.queue"),
        ]
        for p in self.proxies:
            args += ["--daemon", p.addr]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        self.router = subprocess.Popen(
            args, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        _wait_listening(self.router_addr)

    def stop(self) -> None:
        procs = [p for p in self.daemons if p is not None]
        if self.router is not None:
            procs.append(self.router)
        for i, p in enumerate(self.daemons):
            if p is not None and self.paused[i]:
                try:
                    p.send_signal(signal.SIGCONT)
                except OSError as e:
                    log.debug("SIGCONT to daemon %d failed: %r", i, e)
        for p in procs:
            try:
                p.terminate()
            except OSError as e:
                log.debug("terminate of pid %s failed: %r", p.pid, e)
        for p in procs:
            try:
                p.wait(timeout=10)
            except (subprocess.TimeoutExpired, OSError):
                try:
                    p.kill()
                except OSError as e:
                    log.debug("kill of pid %s failed: %r", p.pid, e)
        for px in self.proxies:
            px.close()
        # Teardown heals everything by construction (every child is
        # dead, every proxy closed) — mark any outstanding intents so a
        # post-run ledger audit shows a clean fleet.
        self.ledger.heal_matching(by="fleet-stop")

    # -- fault injectors -----------------------------------------------------

    def kill_daemon(self, i: int) -> None:
        p = self.daemons[i]
        if p is None:
            return
        telemetry.count("chaos.inject.daemon-kill")
        p.kill()
        p.wait(timeout=10)
        self.daemons[i] = None

    def restart_daemon(self, i: int) -> None:
        if self.daemons[i] is None:
            telemetry.count("chaos.heal.daemon-restart")
            self.start_daemon(i)

    def pause_daemon(self, i: int) -> None:
        p = self.daemons[i]
        if p is None or self.paused[i]:
            return
        telemetry.count("chaos.inject.daemon-pause")
        p.send_signal(signal.SIGSTOP)
        self.paused[i] = True

    def resume_daemon(self, i: int) -> None:
        p = self.daemons[i]
        if p is None or not self.paused[i]:
            return
        telemetry.count("chaos.heal.daemon-resume")
        p.send_signal(signal.SIGCONT)
        self.paused[i] = False

    def kill_router(self) -> None:
        if self.router is None:
            return
        telemetry.count("chaos.inject.router-kill")
        self.router.kill()
        self.router.wait(timeout=10)
        self.router = None

    def restart_router(self) -> None:
        if self.router is None:
            telemetry.count("chaos.heal.router-restart")
            self.start_router()

    def partition(self, i: int) -> None:
        telemetry.count("chaos.inject.partition")
        self.proxies[i].set_mode("drop")

    def slow_peer(self, i: int, delay_s: float = 0.05) -> None:
        telemetry.count("chaos.inject.slow-peer")
        self.proxies[i].set_mode("slow", delay_s=delay_s)

    def heal_proxy(self, i: int) -> None:
        telemetry.count("chaos.heal.proxy")
        self.proxies[i].set_mode("ok")

    def tear_journal(self, i: int) -> None:
        """Appends a torn frame to the daemon's queue journal.  Only
        meaningful while the daemon is down (its reopen must truncate);
        a live daemon is killed first — the schedule compiler pairs
        this family with a restart heal."""
        if self.daemons[i] is not None:
            self.kill_daemon(i)
        telemetry.count("chaos.inject.journal-tear")
        try:
            with open(self._queue_path(i), "ab") as f:
                f.write(b"\x13\x00\x00\x00torn-by-selfchaos")
        except OSError as e:
            log.warning("journal tear on daemon %d failed: %r", i, e)

    def set_disk_full(self, i: int, on: bool) -> None:
        telemetry.count("chaos.inject.disk-full" if on
                        else "chaos.heal.disk-full")
        path = self._diskfull_path(i)
        if on:
            with open(path, "w", encoding="utf-8") as f:
                f.write("enospc")
        else:
            try:
                os.unlink(path)
            except OSError:
                pass

    def set_brownout(self, i: int, level: int) -> None:
        telemetry.count("chaos.inject.brownout" if level
                        else "chaos.heal.brownout")
        path = self._brownout_path(i)
        if level:
            with open(path, "w", encoding="utf-8") as f:
                f.write(str(int(level)))
        else:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- schedule application ------------------------------------------------

    def inject(self, fault: ChaosFault,
               rng: Optional[random.Random] = None) -> None:
        if fault.family not in FAMILIES:
            raise ValueError(f"unknown chaos family {fault.family!r}")
        rng = rng or random.Random(fault.salt)
        # Journal intent BEFORE touching the fleet: the append+fsync
        # must land first so a crash between journal and injection errs
        # toward a spurious (idempotent) heal replay, never a stranded
        # fault — the same contract as nemesis/faults.py.
        eid = self.ledger.intent(
            fault.family,
            nodes=["router" if fault.target < 0 else f"d{fault.target}"],
            params={"t": round(fault.t, 3),
                    "duration-s": round(fault.duration_s, 3)},
            compensator={"type": f"chaos-heal:{fault.family}",
                         "target": fault.target},
            tag=f"salt-{fault.salt}",
        )
        self._ledger_ids[(fault.family, fault.target, fault.salt)] = eid
        i = fault.target
        if fault.family == "daemon-kill":
            self.kill_daemon(i)
        elif fault.family == "daemon-pause":
            self.pause_daemon(i)
        elif fault.family == "router-kill":
            self.kill_router()
        elif fault.family == "partition":
            self.partition(i)
        elif fault.family == "slow-peer":
            self.slow_peer(i, delay_s=rng.uniform(0.02, 0.1))
        elif fault.family == "journal-tear":
            self.tear_journal(i)
        elif fault.family == "disk-full":
            self.set_disk_full(i, True)
        elif fault.family == "brownout":
            self.set_brownout(i, 1 + rng.randrange(2))
        else:
            raise ValueError(f"unknown chaos family {fault.family!r}")

    def heal(self, fault: ChaosFault) -> None:
        i = fault.target
        if fault.family in ("daemon-kill", "journal-tear"):
            self.restart_daemon(i)
        elif fault.family == "daemon-pause":
            self.resume_daemon(i)
        elif fault.family == "router-kill":
            self.restart_router()
        elif fault.family in ("partition", "slow-peer"):
            self.heal_proxy(i)
        elif fault.family == "disk-full":
            self.set_disk_full(i, False)
        elif fault.family == "brownout":
            self.set_brownout(i, 0)
        # Healed lands AFTER the compensator succeeds, never before.
        eid = self._ledger_ids.pop(
            (fault.family, fault.target, fault.salt), None)
        if eid is not None:
            self.ledger.healed(eid, by="chaos-heal")


# ---------------------------------------------------------------------------
# Multi-tenant load
# ---------------------------------------------------------------------------


def _register_ops(rng: random.Random, n_pairs: int) -> list[dict]:
    """A valid single-key register history as op dicts (write x, read
    x) — always linearizable, so every verdict is deterministic-valid
    and replay digests are comparable."""
    ops = []
    i = 0
    for _ in range(n_pairs):
        v = rng.randrange(1000)
        for f, typ, val in (("write", "invoke", v), ("write", "ok", v),
                            ("read", "invoke", None), ("read", "ok", v)):
            ops.append({"index": i, "time": i, "type": typ,
                        "process": 0, "f": f, "value": val})
            i += 1
    return ops


class TenantLoad(threading.Thread):
    """One tenant's closed-loop submit/poll worker against the router.
    Each iteration submits a small register history with a deadline,
    records ack/shed, polls to the verdict, and records its digest and
    queue wait into the shared ChaosHistory."""

    def __init__(self, tenant: str, router_addr: str,
                 history: ChaosHistory, stop: threading.Event, *,
                 seed: int, n_keys: int = 2, pairs_per_key: int = 4,
                 deadline_s: float = 30.0, think_s: float = 0.05):
        super().__init__(name=f"load-{tenant}", daemon=True)
        self.tenant = tenant
        self.router_addr = router_addr
        self.history = history
        self.stop_evt = stop
        self.rng = random.Random(seed)
        self.n_keys = n_keys
        self.pairs_per_key = pairs_per_key
        self.deadline_s = deadline_s
        self.think_s = think_s
        self.submitted = 0

    def run(self) -> None:
        from ..checkerd.client import (
            CheckerdClient,
            RemoteUnavailable,
            ShedByServer,
        )

        spec = {"type": "register", "value": None}
        while not self.stop_evt.is_set():
            subs = [_register_ops(self.rng, self.pairs_per_key)
                    for _ in range(self.n_keys)]
            run = f"{self.tenant}-{self.submitted}"
            self.submitted += 1
            t_submit = time.monotonic()
            try:
                with CheckerdClient(self.router_addr,
                                    connect_timeout=2.0,
                                    io_timeout=30.0) as c:
                    try:
                        ticket = c.submit_ops(
                            run, spec, subs, tenant=self.tenant,
                            deadline_s=self.deadline_s,
                        )
                    except ShedByServer as e:
                        self.history.record(
                            "shed", tenant=self.tenant, run=run,
                            retry_after_s=e.retry_after_s,
                            reason=e.shed.reason,
                        )
                        self.stop_evt.wait(
                            min(e.retry_after_s, 0.5))
                        continue
                    self.history.record("ack", tenant=self.tenant,
                                        run=run, ticket=ticket)
                    self._poll(c, ticket, t_submit)
            except RemoteUnavailable as e:
                self.history.record("error", tenant=self.tenant,
                                    run=run, error=str(e))
                self.stop_evt.wait(0.2)
            self.stop_evt.wait(self.think_s)

    def _poll(self, c: Any, ticket: str, t_submit: float) -> None:
        """Polls on the submitting connection until RESULT; on a dead
        connection, re-polls the router on fresh connections — an acked
        ticket is chased until the harness stops, because losing it IS
        the bug we're hunting."""
        from ..checkerd.client import CheckerdClient, RemoteUnavailable
        from ..checkerd.protocol import F_PENDING, F_RESULT

        own: Optional[Any] = None  # replacement client we must close
        try:
            while not self.stop_evt.is_set():
                try:
                    ftype, payload = c.poll(ticket)
                except RemoteUnavailable:
                    if own is not None:
                        own.close()
                        own = None
                    try:
                        c = own = CheckerdClient(self.router_addr,
                                                 connect_timeout=2.0,
                                                 io_timeout=30.0)
                    except RemoteUnavailable:
                        self.stop_evt.wait(0.3)
                    continue
                if ftype == F_RESULT:
                    self.history.record(
                        "verdict", tenant=self.tenant, ticket=ticket,
                        digest=verdict_digest(payload),
                        valid=payload.get("valid"),
                        wait_s=round(time.monotonic() - t_submit, 4),
                    )
                    return
                if ftype != F_PENDING:
                    self.history.record("error", tenant=self.tenant,
                                        ticket=ticket,
                                        error=f"frame {ftype}")
                    return
                self.stop_evt.wait(0.05)
        finally:
            if own is not None:
                own.close()


def chase_outstanding(history: ChaosHistory, router_addr: str,
                      timeout_s: float = 30.0) -> None:
    """After the load stops, polls every acked-but-unverdicted ticket
    until it resolves or the timeout expires — the exactly-one-verdict
    invariant is about *eventual* delivery through faults, so the
    harness gives the healed fleet a bounded grace window."""
    from ..checkerd.client import CheckerdClient, RemoteUnavailable
    from ..checkerd.protocol import F_PENDING, F_RESULT

    outstanding = {
        op["ticket"]: op for op in history.ops("ack")
        if op.get("ticket")
    }
    for op in history.ops("verdict"):
        outstanding.pop(op.get("ticket"), None)
    t0 = time.monotonic()
    while outstanding and time.monotonic() - t0 < timeout_s:
        for ticket, op in list(outstanding.items()):
            try:
                with CheckerdClient(router_addr, connect_timeout=2.0,
                                    io_timeout=10.0) as c:
                    ftype, payload = c.poll(ticket)
            except RemoteUnavailable:
                time.sleep(0.3)
                continue
            if ftype == F_RESULT:
                history.record(
                    "verdict", tenant=op.get("tenant"), ticket=ticket,
                    digest=verdict_digest(payload),
                    valid=payload.get("valid"), wait_s=None,
                )
                del outstanding[ticket]
            elif ftype != F_PENDING:
                # A hard ERROR for an acked ticket is a loss; leave it
                # outstanding so check_invariants flags it.
                time.sleep(0.2)
        time.sleep(0.1)


def replay_check(history: ChaosHistory, router_addr: str,
                 n: int = 3) -> list[str]:
    """Re-polls the last n verdicts on fresh connections and compares
    digests — replayed results must be byte-identical to what clients
    first observed (router journal + result TTL make this answerable)."""
    from ..checkerd.client import CheckerdClient, RemoteUnavailable
    from ..checkerd.protocol import F_RESULT

    divergent: list[str] = []
    seen = history.ops("verdict")[-n:]
    for op in seen:
        ticket = op.get("ticket")
        if not ticket:
            continue
        try:
            with CheckerdClient(router_addr, connect_timeout=2.0,
                                io_timeout=10.0) as c:
                ftype, payload = c.poll(ticket)
        except RemoteUnavailable:
            continue
        if ftype != F_RESULT:
            continue
        d = verdict_digest(payload)
        history.record("verdict", tenant=op.get("tenant"),
                       ticket=ticket, digest=d,
                       valid=payload.get("valid"), wait_s=None)
        if d != op.get("digest"):
            divergent.append(ticket)
    return divergent


# ---------------------------------------------------------------------------
# The harness
# ---------------------------------------------------------------------------


def run_chaos(
    schedule: ChaosSchedule,
    *,
    n_daemons: int = 2,
    workdir: str,
    tenants: Sequence[str] = ("alpha", "beta", "gamma"),
    tenant_weights: Optional[dict[str, float]] = None,
    whale: Optional[str] = None,
    light: Optional[str] = None,
    fairness_bound_s: Optional[float] = None,
    settle_s: float = 10.0,
) -> dict:
    """Runs one chaos schedule against a fresh fleet under multi-tenant
    load; returns the outcome dict (history stats, violations, fault
    log).  The whale tenant (when named) submits bigger histories with
    no think time — the saturation source the fairness invariant
    measures against."""
    telemetry.count("chaos.run")
    fleet = ChaosFleet(n_daemons, workdir,
                       tenant_weights=tenant_weights)
    history = ChaosHistory()
    stop = threading.Event()
    loads: list[TenantLoad] = []
    fault_log: list[dict] = []
    try:
        fleet.start()
        for k, tenant in enumerate(tenants):
            is_whale = tenant == whale
            loads.append(TenantLoad(
                tenant, fleet.router_addr, history, stop,
                seed=schedule.seed ^ (0x9E3779B9 * (k + 1)),
                n_keys=6 if is_whale else 2,
                pairs_per_key=24 if is_whale else 4,
                think_s=0.0 if is_whale else 0.05,
            ))
        for ld in loads:
            ld.start()

        t0 = time.monotonic()
        pending_heals: list[tuple[float, ChaosFault]] = []
        events = list(schedule.faults)
        while time.monotonic() - t0 < schedule.duration_s:
            now = time.monotonic() - t0
            while events and events[0].t <= now:
                f = events.pop(0)
                rng = random.Random(schedule.seed ^ f.salt)
                log.info("chaos inject: %s target=%d t=%.2f",
                         f.family, f.target, now)
                history.record("inject", family=f.family,
                               target=f.target)
                fault_log.append({"family": f.family,
                                  "target": f.target,
                                  "t": round(now, 3)})
                try:
                    fleet.inject(f, rng)
                except Exception as e:  # noqa: BLE001 — keep running
                    log.warning("inject %s failed: %r", f.family, e)
                pending_heals.append((f.t + f.duration_s, f))
                pending_heals.sort(key=lambda e: e[0])
            while pending_heals and pending_heals[0][0] <= now:
                _, f = pending_heals.pop(0)
                log.info("chaos heal: %s target=%d t=%.2f",
                         f.family, f.target, now)
                history.record("heal", family=f.family,
                               target=f.target)
                try:
                    fleet.heal(f)
                except Exception as e:  # noqa: BLE001
                    log.warning("heal %s failed: %r", f.family, e)
            time.sleep(0.02)

        # Heal everything still open, stop the load, then chase every
        # acked ticket to its verdict through the healed fleet.
        for _, f in pending_heals:
            history.record("heal", family=f.family, target=f.target)
            try:
                fleet.heal(f)
            except Exception as e:  # noqa: BLE001
                log.warning("final heal %s failed: %r", f.family, e)
        stop.set()
        for ld in loads:
            ld.join(timeout=30.0)
        stop.clear()
        chase_outstanding(history, fleet.router_addr,
                          timeout_s=settle_s)
        divergent = replay_check(history, fleet.router_addr)
    finally:
        stop.set()
        fleet.stop()

    violations = check_invariants(
        history, fairness_bound_s=fairness_bound_s, light_tenant=light,
    )
    for t in divergent:
        violations.append(f"replay-divergence: ticket {t} re-polled to "
                          f"a different digest")
    st = history.stats()
    return {
        "schedule": schedule.to_dict(),
        "faults-injected": fault_log,
        "history": st,
        "submitted": sum(ld.submitted for ld in loads),
        "violations": violations,
        "valid": not violations,
    }
