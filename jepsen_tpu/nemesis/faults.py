"""Faults that act on nodes through the control plane.

Equivalents of the process/clock/file faults in
/root/reference/jepsen/src/jepsen/nemesis.clj and nemesis/time.clj:
DB kill/pause via the DB protocol (nemesis/combined.clj:72-100),
`node_start_stopper` (nemesis.clj:453-496), `hammer_time`
SIGSTOP/SIGCONT (nemesis.clj:498-512), clock bump/strobe/reset with a
C helper compiled on the node (nemesis/time.clj:21-40, :104-167),
`truncate_file` (nemesis.clj:514-548), and `bitflip` (nemesis.clj:550-597,
reimplemented with dd+xxd instead of a downloaded Go binary).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Optional, Sequence

from .. import telemetry
from ..control import Session, health, on_nodes
from ..history import Op
from ..utils import with_retry
from . import ledger as fault_ledger
from .core import Nemesis, _rng

log = logging.getLogger(__name__)

RESOURCE_DIR = os.path.join(os.path.dirname(__file__), "..", "resources")


def _pick_nodes(test: dict, spec: Any) -> list:
    """Node selection spec: None = all, int = that many random, list =
    exactly those, callable = filter (nemesis.clj:453-467).  Quarantined
    nodes are out of the draw — faulting a corpse is wasted fault budget
    and would muddy the health timeline — and the ledger records the
    skip so a post-mortem reader knows why the fault's footprint
    shrank."""
    all_nodes = list(test.get("nodes") or [])
    nodes = [n for n in all_nodes if not health.is_quarantined(test, n)]
    skipped = [n for n in all_nodes if n not in nodes]
    if skipped:
        telemetry.count("nemesis.skip.quarantined", len(skipped))
        fault_ledger.note(
            test, why="quarantined-skip", nodes=list(skipped)
        )
    if spec is None:
        return nodes
    if isinstance(spec, int):
        _rng().shuffle(nodes)
        return nodes[:spec]
    if callable(spec):
        return [n for n in nodes if spec(n)]
    return [n for n in spec if n in nodes]


class DBNemesis(Nemesis):
    """Kills/pauses the DB via its Kill/Pause capabilities
    (nemesis/combined.clj:72-100).  fs: kill/start/pause/resume; op
    value selects nodes (see _pick_nodes)."""

    def invoke(self, test: dict, op: Op) -> Op:
        db = test["db"]
        nodes = _pick_nodes(test, op.value)
        method = {
            "kill": "kill",
            "start": "start",
            "pause": "pause",
            "resume": "resume",
        }[op.f]

        if method == "kill":
            fault_ledger.intent(
                test, "process", nodes=[str(n) for n in nodes],
                params={"f": "kill"},
                compensator={"type": "db-start",
                             "nodes": [str(n) for n in nodes]},
                tag="db-kill",
            )
        elif method == "pause":
            fault_ledger.intent(
                test, "process", nodes=[str(n) for n in nodes],
                params={"f": "pause"},
                compensator={"type": "db-resume",
                             "nodes": [str(n) for n in nodes]},
                tag="db-pause",
            )
        elif fault_ledger.heal_guard():
            return op.replace(value="heal abandoned")

        def act(sess: Session, node: str):
            getattr(db, method)(test, sess, node)
            return "done"

        res = on_nodes(test, act, nodes)
        if method == "start":
            fault_ledger.healed(test, tag="db-kill")
        elif method == "resume":
            fault_ledger.healed(test, tag="db-pause")
        return op.replace(value=res)

    def fs(self) -> set:
        return {"kill", "start", "pause", "resume"}


class HammerTime(Nemesis):
    """SIGSTOP/SIGCONT a process by name (nemesis.clj:498-512)."""

    def __init__(self, process_name: str):
        self.process_name = process_name

    def invoke(self, test: dict, op: Op) -> Op:
        sig = {"start": "STOP", "stop": "CONT"}[op.f]
        nodes = _pick_nodes(test, op.value)
        if sig == "STOP":
            fault_ledger.intent(
                test, "process", nodes=[str(n) for n in nodes],
                params={"process": self.process_name, "signal": "STOP"},
                compensator={"type": "sigcont",
                             "process": self.process_name,
                             "nodes": [str(n) for n in nodes]},
                tag="hammer",
            )
        elif fault_ledger.heal_guard():
            return op.replace(value="heal abandoned")

        def act(sess: Session, node: str):
            with sess.su():
                sess.exec_star("pkill", f"-{sig}", "-f", self.process_name)
            return f"SIG{sig}"

        res = on_nodes(test, act, nodes)
        if sig == "CONT":
            fault_ledger.healed(test, tag="hammer")
        return op.replace(value=res)

    def fs(self) -> set:
        return {"start", "stop"}


def node_start_stopper(
    targeter: Callable[[dict, list], Sequence[str]],
    start: Callable[[dict, Session, str], Any],
    stop: Callable[[dict, Session, str], Any],
) -> Nemesis:
    """Generic start/stop fault over targeted nodes
    (nemesis.clj:453-496): `start` breaks a node, `stop` heals it; the
    nemesis remembers which nodes it broke."""

    class StartStopper(Nemesis):
        def __init__(self) -> None:
            self.affected: list = []

        def invoke(self, test: dict, op: Op) -> Op:
            if op.f == "start":
                nodes = list(targeter(test, health.eligible_nodes(test)))
                # The heal is an arbitrary closure — not data-describable,
                # so repair can only report it, not replay it.
                fault_ledger.intent(
                    test, "process", nodes=[str(n) for n in nodes],
                    params={"f": "start"},
                    compensator={
                        "type": "unreplayable",
                        "note": "node_start_stopper closure; re-run its "
                                "stop by hand",
                    },
                    tag="start-stopper",
                )
                res = on_nodes(
                    test, lambda s, n: start(test, s, n), nodes
                )
                self.affected = nodes
                return op.replace(value=res)
            elif op.f == "stop":
                if fault_ledger.heal_guard():
                    return op.replace(value="heal abandoned")
                nodes = self.affected or list(test.get("nodes") or [])
                res = on_nodes(test, lambda s, n: stop(test, s, n), nodes)
                self.affected = []
                fault_ledger.healed(test, tag="start-stopper")
                return op.replace(value=res)
            raise ValueError(f"unknown f {op.f!r}")

        def fs(self) -> set:
            return {"start", "stop"}

    return StartStopper()


# ---------------------------------------------------------------------------
# Clock faults (nemesis/time.clj)
# ---------------------------------------------------------------------------

BUILD_DIR = "/opt/jepsen-tpu"


class ClockNemesis(Nemesis):
    """Bumps, strobes, and resets node wall clocks.  At setup, uploads
    and gcc-compiles the C helpers on every node (nemesis/time.clj:21-67)
    and stops NTP.  fs: bump/strobe/reset/check-offsets.

    Op values: bump {node: delta_ms} or delta_ms for all; strobe
    {"delta": ms, "period": ms, "duration": ms} (+optional "nodes").

    Like the reference (nemesis/time.clj:104-167), every bump/strobe/
    reset completion carries a {"clock-offsets": {node: secs}} map of
    node-clock-minus-control-clock offsets, which ClockPlot graphs."""

    def setup(self, test: dict) -> "ClockNemesis":
        def install(sess: Session, node: str):
            with sess.su():
                sess.exec("mkdir", "-p", BUILD_DIR)
                for src in ("bump-time.c", "strobe-time.c"):
                    local = os.path.join(RESOURCE_DIR, src)
                    sess.upload(local, f"{BUILD_DIR}/{src}")
                    binary = src[:-2]
                    sess.exec(
                        "gcc", "-O2", "-o", f"{BUILD_DIR}/{binary}",
                        f"{BUILD_DIR}/{src}",
                    )
                # Stop time daemons fighting us (time.clj:69-102).
                sess.exec_star("systemctl", "stop", "ntp", "chronyd",
                               "systemd-timesyncd")
            return "ok"

        on_nodes(test, install)
        return self

    def _offsets(self, test: dict, nodes=None) -> dict:
        """Node wall-clock minus control wall-clock, in seconds, per node
        (the reference's current-offset, nemesis/time.clj:104-130)."""
        import time as _time

        def offset(sess: Session, node: str):
            remote = sess.exec("date", "+%s.%N")
            try:
                return float(remote) - _time.time()
            except (TypeError, ValueError):
                return None  # dummy remotes return empty output

        return on_nodes(test, offset, nodes)

    def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "bump":
            spec = op.value
            if not isinstance(spec, dict):
                spec = {n: spec for n in test.get("nodes") or []}

            def bump(sess: Session, node: str):
                # Single positional arg: bump-time parses argv[1] with
                # atoll, so a "--" separator would silently read as 0
                # (exec() passes argv directly — no option parsing, so
                # negative deltas are safe without it).
                delta = spec[node]
                with sess.su():
                    sess.exec(f"{BUILD_DIR}/bump-time", str(delta))
                return delta

            nodes = list(spec.keys())
            fault_ledger.intent(
                test, "clock", nodes=[str(n) for n in nodes],
                params={"f": "bump",
                        "deltas_ms": {str(n): spec[n] for n in nodes}},
                compensator={"type": "clock-reset",
                             "nodes": [str(n) for n in nodes]},
            )
            res = on_nodes(test, bump, nodes)
            return op.replace(value={
                "bumped": res,
                "clock-offsets": self._offsets(test, nodes),
            })
        if op.f == "strobe":
            v = op.value or {}
            nodes = _pick_nodes(test, v.get("nodes"))

            def strobe(sess: Session, node: str):
                with sess.su():
                    sess.exec(
                        f"{BUILD_DIR}/strobe-time",
                        str(v.get("delta", 200)),
                        str(v.get("period", 10)),
                        str(v.get("duration", 1000)),
                    )
                return "strobed"

            fault_ledger.intent(
                test, "clock", nodes=[str(n) for n in nodes],
                params={"f": "strobe", "delta": v.get("delta", 200),
                        "period": v.get("period", 10),
                        "duration": v.get("duration", 1000)},
                compensator={"type": "clock-reset",
                             "nodes": [str(n) for n in nodes]},
            )
            res = on_nodes(test, strobe, nodes)
            return op.replace(value={
                "strobed": res,
                "clock-offsets": self._offsets(test, nodes),
            })
        if op.f == "reset":
            if fault_ledger.heal_guard():
                return op.replace(value="heal abandoned")
            nodes = _pick_nodes(test, op.value)

            def reset(sess: Session, node: str):
                with sess.su():
                    sess.exec("ntpdate", "-b", "pool.ntp.org")
                return "reset"

            res = on_nodes(test, reset, nodes)
            fault_ledger.healed(test, fault="clock")
            return op.replace(value={
                "reset": res,
                "clock-offsets": self._offsets(test, nodes),
            })
        if op.f == "check-offsets":
            return op.replace(
                value={"clock-offsets": self._offsets(test)}
            )
        raise ValueError(f"unknown clock f {op.f!r}")

    def teardown(self, test: dict) -> None:
        # Per-node, best-effort, retried: one unreachable node cannot
        # abort resetting the rest, and a failed reset is stranded clock
        # skew — warn loudly and leave its ledger entries outstanding
        # for `jepsen repair` / the residue sweep.
        if fault_ledger.heal_guard():
            return

        def reset_node(sess: Session) -> None:
            with sess.su():
                sess.exec_star("ntpdate", "-b", "pool.ntp.org")
                # Restart the time daemons setup stopped.
                sess.exec_star("systemctl", "start", "ntp", "chronyd",
                               "systemd-timesyncd")

        failed: list = []
        for node, sess in (test.get("sessions") or {}).items():
            try:
                with_retry(
                    lambda s=sess: reset_node(s),
                    retries=2, backoff_ms=100.0,
                )
            except Exception as e:  # noqa: BLE001 — continue to siblings
                log.warning(
                    "clock reset failed on %s during teardown: %r", node, e
                )
                failed.append(node)
        if failed:
            log.warning(
                "clock skew may be stranded on %s — ledger entries left "
                "outstanding for `jepsen repair`", failed,
            )
        else:
            fault_ledger.healed(test, fault="clock", by="teardown")

    def fs(self) -> set:
        return {"bump", "strobe", "reset", "check-offsets"}


class ClockScrambler(ClockNemesis):
    """The classic coarse clock fault (nemesis.clj:436-451): on
    f="start", bumps every node's clock by an independent uniformly
    random offset within ±dt seconds; f="stop" resets clocks via NTP.
    Inherits ClockNemesis's helper compilation, offset reporting, and
    teardown."""

    def __init__(self, dt_secs: float):
        self.dt_secs = dt_secs

    def invoke(self, test: dict, op: Op) -> Op:
        from .core import _rng

        if op.f == "start":
            dt_ms = int(self.dt_secs * 1000)
            spec = {
                n: _rng().randint(-dt_ms, dt_ms)
                for n in test.get("nodes") or []
            }
            return super().invoke(test, op.replace(f="bump", value=spec)
                                  ).replace(f="start")
        if op.f == "stop":
            return super().invoke(test, op.replace(f="reset", value=None)
                                  ).replace(f="stop")
        raise ValueError(f"unknown clock-scrambler f {op.f!r}")

    def fs(self) -> set:
        return {"start", "stop"}


def clock_scrambler(dt_secs: float) -> ClockScrambler:
    return ClockScrambler(dt_secs)


# ---------------------------------------------------------------------------
# Disk faults
# ---------------------------------------------------------------------------


class TruncateFile(Nemesis):
    """Chops bytes off the end of a file (nemesis.clj:514-548).  Op value:
    {node: {"file": path, "drop": bytes}} or a single spec for all."""

    def invoke(self, test: dict, op: Op) -> Op:
        spec = op.value
        if not isinstance(spec, dict) or "file" in spec:
            spec = {n: spec for n in test.get("nodes") or []}
        fault_ledger.intent(
            test, "file", nodes=[str(n) for n in spec],
            params={"f": "truncate",
                    "files": sorted({str(s.get("file")) for s in
                                     spec.values() if isinstance(s, dict)})},
            compensator={
                "type": "unreplayable",
                "note": "file truncation is unrecoverable — restore the "
                        "file from backup or reprovision the node",
            },
            tag="truncate",
        )

        def trunc(sess: Session, node: str):
            s = spec[node]
            drop = int(s.get("drop", 1))
            path = s["file"]
            with sess.su():
                sess.exec(
                    "truncate", "-c", "-s", f"-{drop}", path
                )
            return {"truncated": path, "drop": drop}

        return op.replace(value=on_nodes(test, trunc, list(spec.keys())))

    def fs(self) -> set:
        return {"truncate"}


class Bitflip(Nemesis):
    """Flips a bit in a file (nemesis.clj:550-597; the reference
    downloads a Go binary — here: dd read, flip in shell, dd write).
    Op value: {node: {"file": path, "probability": p}} or one spec."""

    def invoke(self, test: dict, op: Op) -> Op:
        spec = op.value
        if not isinstance(spec, dict) or "file" in spec:
            spec = {n: spec for n in test.get("nodes") or []}
        fault_ledger.intent(
            test, "file", nodes=[str(n) for n in spec],
            params={"f": "bitflip",
                    "files": sorted({str(s.get("file")) for s in
                                     spec.values() if isinstance(s, dict)})},
            compensator={
                "type": "unreplayable",
                "note": "bitflip corruption is unrecoverable — restore the "
                        "file from backup or reprovision the node",
            },
            tag="bitflip",
        )

        def flip(sess: Session, node: str):
            s = spec[node]
            path = s["file"]
            with sess.su():
                size = int(sess.exec("stat", "-c", "%s", path) or "0")
                if size == 0:
                    return {"flipped": 0}
                offset = _rng().randrange(size)
                bit = 1 << _rng().randrange(8)
                script = (
                    f"b=$(dd if={path} bs=1 skip={offset} count=1 "
                    f"2>/dev/null | od -An -tu1 | tr -d ' '); "
                    f"printf \"\\\\$(printf '%03o' $((b ^ {bit})))\" | "
                    f"dd of={path} bs=1 seek={offset} count=1 "
                    f"conv=notrunc 2>/dev/null"
                )
                sess.exec("bash", "-c", script)
                return {"flipped": 1, "offset": offset, "bit": bit}

        return op.replace(value=on_nodes(test, flip, list(spec.keys())))

    def fs(self) -> set:
        return {"bitflip"}
