"""Nemesis protocol and stock fault injectors.

Equivalent of /root/reference/jepsen/src/jepsen/nemesis.clj: the
`Nemesis` protocol (:12-17) and `Reflection` (:19-22), `noop`,
partition grudges — `complete-grudge` :121, `bridge` :145,
`majorities-ring` :203-276 — the `partitioner` nemesis :158-184, node
isolation helpers :27-107, `compose` :385-429, and `f-map` :303-328.

Faults that shell into nodes (clock scrambling, kill/pause, file
corruption) live in `jepsen_tpu.nemesis.faults` since they need the
control plane; this module is pure protocol + graph math over the
network-manipulation `Net` interface carried in ``test["net"]``.
"""

from __future__ import annotations

import logging
import random
from typing import Any, Callable, Iterable, Mapping, Optional, Sequence

from ..control import health
from ..history import INFO, Op
from ..utils import JepsenTimeout, majority, timeout as run_timeout
from . import ledger as fault_ledger

log = logging.getLogger(__name__)


class Nemesis:
    """A special process that injects faults (nemesis.clj:12-17)."""

    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: Op) -> Op:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass

    def fs(self) -> set:
        """The :f values this nemesis handles (Reflection, :19-22)."""
        return set()


class NoopNemesis(Nemesis):
    """Does nothing (nemesis.clj:24-30)."""

    def invoke(self, test: dict, op: Op) -> Op:
        return op

    def fs(self) -> set:
        return set()


noop = NoopNemesis()


# ---------------------------------------------------------------------------
# Grudges: maps of node -> collection of nodes to cut links FROM
# ---------------------------------------------------------------------------


def complete_grudge(components: Sequence[Sequence[Any]]) -> dict:
    """Takes a collection of components (collections of nodes) and
    returns a grudge cutting every node off from all nodes in the other
    components (nemesis.clj:121-130)."""
    all_nodes = [n for comp in components for n in comp]
    grudge = {}
    for comp in components:
        comp_set = set(comp)
        others = [n for n in all_nodes if n not in comp_set]
        for n in comp:
            grudge[n] = set(others)
    return grudge


def bisect(coll: Sequence[Any]) -> tuple[list, list]:
    """Splits a collection into [first-half, second-half]; the first half
    is smaller for odd sizes (nemesis.clj:109-113)."""
    coll = list(coll)
    mid = len(coll) // 2
    return coll[:mid], coll[mid:]


def _rng() -> random.Random:
    """Nemesis randomness rides the generator module's seedable RNG so
    set_rng_seed reproduces partition choices along with schedules."""
    from ..generator.core import get_rng

    return get_rng()


def split_one(coll: Sequence[Any], rng: Optional[random.Random] = None) -> tuple[list, list]:
    """Splits a collection into one random node and the rest
    (nemesis.clj:115-119)."""
    coll = list(coll)
    r = rng or _rng()
    i = r.randrange(len(coll))
    return [coll[i]], coll[:i] + coll[i + 1 :]


def bridge(nodes: Sequence[Any]) -> dict:
    """A grudge cutting the network in half, preserving a middle node
    with uninterrupted connectivity to both components
    (nemesis.clj:145-156)."""
    nodes = list(nodes)
    mid = len(nodes) // 2
    bridge_node = nodes[mid]
    a = [n for n in nodes[:mid]]
    b = [n for n in nodes[mid + 1 :]]
    grudge = {n: set(b) for n in a}
    grudge.update({n: set(a) for n in b})
    grudge[bridge_node] = set()
    return grudge


def majorities_ring(nodes: Sequence[Any]) -> dict:
    """Grudge in which every node can see a majority including itself,
    but no two nodes see the *same* majority: overlapping majorities
    arranged in a ring (nemesis.clj:203-276).  Node i's view is the
    window of the ring *centered* on i — centering makes visibility
    symmetric, so every node keeps a BIDIRECTIONAL majority (itself
    plus its k nearest neighbors each way).  A window keyed at i
    instead of centered on it would isolate every node: i could hear
    nodes that cannot hear it back.  Even majority sizes round up to
    the next odd window to stay symmetric.

    The ring order is shuffled per call, like the reference's
    majorities-ring-perfect (nemesis.clj:203-217): repeated partitions
    in one test then cut different edges each time."""
    nodes = list(nodes)
    _rng().shuffle(nodes)
    n = len(nodes)
    k = majority(n) // 2
    grudge = {}
    for i, node in enumerate(nodes):
        visible = {nodes[(i + d) % n] for d in range(-k, k + 1)}
        grudge[node] = set(nodes) - visible
    return grudge


def invert_grudge(grudge: Mapping[Any, Iterable[Any]]) -> dict:
    """Symmetrizes a grudge: if a is cut from b, b is cut from a."""
    out: dict[Any, set] = {k: set(v) for k, v in grudge.items()}
    for a, bs in grudge.items():
        for b in bs:
            out.setdefault(b, set()).add(a)
    return out


# ---------------------------------------------------------------------------
# Partitioner nemesis
# ---------------------------------------------------------------------------


class Partitioner(Nemesis):
    """Responds to {:f "start"} by cutting links per a grudge and
    {:f "stop"} by healing (nemesis.clj:158-184).  `grudge_fn` maps the
    test's node list to a grudge; a start op whose value is already a
    grudge mapping takes precedence."""

    def __init__(self, grudge_fn: Optional[Callable[[Sequence[Any]], dict]] = None):
        self.grudge_fn = grudge_fn

    def setup(self, test: dict) -> "Partitioner":
        net = test.get("net")
        if net is not None:
            net.heal(test)
        return self

    def invoke(self, test: dict, op: Op) -> Op:
        net = test["net"]
        if op.f == "start":
            if isinstance(op.value, Mapping):
                grudge = {k: set(v) for k, v in op.value.items()}
            elif self.grudge_fn is not None:
                # Grudges form over the nodes still in rotation: cutting
                # links to a quarantined corpse wastes the fault budget.
                grudge = self.grudge_fn(health.eligible_nodes(test))
            else:
                raise ValueError(
                    "partition start op needs a grudge value or grudge_fn"
                )
            fault_ledger.intent(
                test,
                "partition",
                nodes=sorted(str(n) for n in grudge),
                params={"grudge": {str(k): sorted(v) for k, v in
                                   grudge.items()}},
                compensator={
                    "type": "net-heal",
                    "mech": fault_ledger.net_mech(net),
                },
            )
            net.drop_all(test, grudge)
            return op.replace(
                value={k: sorted(v) for k, v in grudge.items()}
            )
        elif op.f == "stop":
            if fault_ledger.heal_guard():
                return op.replace(value="network heal abandoned")
            net.heal(test)
            fault_ledger.healed(test, fault="partition")
            return op.replace(value="network healed")
        raise ValueError(f"partitioner got unknown f {op.f!r}")

    def teardown(self, test: dict) -> None:
        net = test.get("net")
        if net is None:
            return
        if fault_ledger.heal_guard():
            return
        net.heal(test)
        fault_ledger.healed(test, fault="partition", by="teardown")

    def fs(self) -> set:
        return {"start", "stop"}


def partitioner(grudge_fn: Optional[Callable] = None) -> Partitioner:
    return Partitioner(grudge_fn)


def partition_halves() -> Partitioner:
    """Cuts the network into two halves at start (nemesis.clj:186-192)."""
    return Partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves() -> Partitioner:
    """Two randomly-chosen halves (nemesis.clj:194-201)."""

    def grudge(nodes: Sequence[Any]) -> dict:
        shuffled = list(nodes)
        _rng().shuffle(shuffled)
        return complete_grudge(bisect(shuffled))

    return Partitioner(grudge)


def partition_random_node() -> Partitioner:
    """Isolates a single random node (nemesis.clj:132-143)."""
    return Partitioner(lambda nodes: complete_grudge(split_one(nodes)))


def partition_majorities_ring() -> Partitioner:
    """Overlapping-majorities ring partition (nemesis.clj:278-282)."""
    return Partitioner(majorities_ring)


# ---------------------------------------------------------------------------
# Combinators
# ---------------------------------------------------------------------------


class FMap(Nemesis):
    """Remaps the :f values a nemesis sees: `fmap` is {outer-f: inner-f};
    ops are translated on the way in and back on the way out
    (nemesis.clj:303-328)."""

    def __init__(self, fmap: Mapping[Any, Any], nem: Nemesis):
        self.fmap = dict(fmap)
        self.inv = {v: k for k, v in self.fmap.items()}
        self.nem = nem

    def setup(self, test: dict) -> "FMap":
        return FMap(self.fmap, self.nem.setup(test))

    def invoke(self, test: dict, op: Op) -> Op:
        inner = op.replace(f=self.fmap[op.f])
        out = self.nem.invoke(test, inner)
        return out.replace(f=self.inv[out.f])

    def teardown(self, test: dict) -> None:
        self.nem.teardown(test)

    def fs(self) -> set:
        return set(self.fmap.keys())


def f_map(fmap: Mapping[Any, Any], nem: Nemesis) -> FMap:
    return FMap(fmap, nem)


class NemesisTeardownError(Exception):
    """Aggregate of per-child Compose teardown failures: every child got
    its teardown attempt; these are the ones that failed."""

    def __init__(self, failures: list[tuple["Nemesis", BaseException]]):
        self.failures = failures
        super().__init__(
            "nemesis teardown failed for "
            + "; ".join(
                f"{type(nem).__name__}: {type(e).__name__}: {e}"
                for nem, e in failures
            )
        )


class Compose(Nemesis):
    """Routes ops to one of several nemeses by :f (nemesis.clj:385-429).
    Takes a plain list of nemeses (fs taken from Reflection) or a list
    of (fs, nemesis) pairs, where fs is a collection of f values or an
    {outer-f: inner-f} remapping (the reference's fmap-key form —
    expressed as pairs here since dicts can't key a Python dict)."""

    def __init__(self, nemeses: Any):
        entries = []
        for item in nemeses:
            if (
                isinstance(item, tuple)
                and len(item) == 2
                and not isinstance(item[0], Nemesis)
            ):
                fs, nem = item
                if isinstance(fs, Mapping):
                    entries.append((set(fs.keys()), f_map(fs, nem)))
                else:
                    entries.append((set(fs), nem))
            else:
                entries.append((set(item.fs()), item))
        seen: set = set()
        for fs, _ in entries:
            dup = seen & fs
            if dup:
                raise ValueError(f"multiple nemeses claim fs {sorted(dup)}")
            seen |= fs
        self.entries = entries

    @classmethod
    def _from_entries(cls, entries: list) -> "Compose":
        self = cls([])
        self.entries = entries
        return self

    def _route(self, f: Any) -> Nemesis:
        for fs, nem in self.entries:
            if f in fs:
                return nem
        raise ValueError(f"no nemesis handles f {f!r}")

    def setup(self, test: dict) -> "Compose":
        return Compose._from_entries(
            [(fs, nem.setup(test)) for fs, nem in self.entries]
        )

    def invoke(self, test: dict, op: Op) -> Op:
        return self._route(op.f).invoke(test, op)

    def teardown(self, test: dict) -> None:
        # One failing child must not strand its siblings' faults: every
        # child gets its teardown attempt, then the failures surface as
        # one aggregate.
        failures: list[tuple[Nemesis, BaseException]] = []
        for _, nem in self.entries:
            try:
                nem.teardown(test)
            except Exception as e:  # noqa: BLE001 — aggregated below
                log.warning(
                    "nemesis %s teardown failed: %r", type(nem).__name__, e
                )
                failures.append((nem, e))
        if failures:
            raise NemesisTeardownError(failures)

    def fs(self) -> set:
        out: set = set()
        for fs, _ in self.entries:
            out |= fs
        return out


def compose(nemeses: Any) -> Compose:
    return Compose(nemeses)


class Timeout(Nemesis):
    """Bounds nemesis invocations at `ms`; on expiry the op completes
    with an error note and the fault thread keeps running
    (nemesis.clj:430-434 analog of client/Timeout)."""

    def __init__(self, ms: float, nem: Nemesis):
        self.ms = ms
        self.nem = nem

    def setup(self, test: dict) -> "Timeout":
        return Timeout(self.ms, self.nem.setup(test))

    def invoke(self, test: dict, op: Op) -> Op:
        try:
            return run_timeout(self.ms, lambda: self.nem.invoke(test, op))
        except JepsenTimeout:
            return op.replace(value="nemesis timeout")

    def teardown(self, test: dict) -> None:
        self.nem.teardown(test)

    def fs(self) -> set:
        return self.nem.fs()


def timeout(ms: float, nem: Nemesis) -> Timeout:
    return Timeout(ms, nem)
