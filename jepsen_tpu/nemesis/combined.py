"""Declarative "fault packages": nemesis + generator bundles.

Equivalent of /root/reference/jepsen/src/jepsen/nemesis/combined.clj:
a package is {"nemesis", "generator", "final-generator", "perf"}
(:26-60); `partition_package` (:228), `db_package` kill/pause (:143),
`packet_package` tc-netem (:288), `clock_package` (:329),
`compose_packages` (:483), and the top-level `nemesis_package` (:508-568)
that turns {"faults": {...}, "interval": secs} into one bundle ready to
merge into a test map:

    pkg = nemesis_package({"faults": {"partition", "kill"}, "interval": 10})
    test["nemesis"] = pkg["nemesis"]
    test["generator"] = gen.nemesis(pkg["generator"], workload_gen)

Fault f's are namespaced ("start-partition"...) and routed by f_map +
compose, like the reference.
"""

from __future__ import annotations

import logging
import random
from typing import Any, Optional, Sequence

from ..generator.core import FnGen, cycle, mix, once, sleep as gen_sleep
from .core import (
    Compose,
    Nemesis,
    _rng,
    bridge,
    complete_grudge,
    bisect,
    compose,
    majorities_ring,
    noop,
    partitioner,
    split_one,
)
from .faults import Bitflip, ClockNemesis, DBNemesis, TruncateFile

log = logging.getLogger(__name__)

DEFAULT_INTERVAL = 10.0  # seconds between fault transitions (:22-24)


def _package(nemesis: Nemesis, generator, final_generator=None, perf=None):
    return {
        "nemesis": nemesis,
        "generator": generator,
        "final-generator": final_generator,
        "perf": perf or [],
    }


def _cycle_ops(interval: float, *templates: dict):
    """start/stop style op cycle spaced by ~interval seconds."""
    steps: list = []
    for t in templates:
        steps.append(gen_sleep(interval))
        steps.append(dict(t, type="info"))
    return cycle(steps)


def _grudge_for(kind: str):
    table = {
        "one": lambda nodes: complete_grudge(split_one(nodes)),
        "majority": lambda nodes: complete_grudge(
            bisect(sorted(nodes, key=lambda _: _rng().random()))
        ),
        "majorities-ring": majorities_ring,
        "bridge": bridge,
        "primaries": lambda nodes: complete_grudge(split_one(nodes)),
    }
    return table[kind]


def partition_package(opts: dict) -> Optional[dict]:
    """Network partitions cycling start/stop (combined.clj:228-286).
    opts["partition"]["targets"]: list of grudge kinds to mix."""
    if "partition" not in opts.get("faults", set()):
        return None
    popts = opts.get("partition", {}) or {}
    targets = popts.get("targets", ["one", "majority", "majorities-ring"])
    interval = opts.get("interval", DEFAULT_INTERVAL)

    nem = partitioner(
        lambda nodes: _grudge_for(_rng().choice(targets))(nodes)
    )
    generator = cycle(
        [
            gen_sleep(interval),
            {"type": "info", "f": "start-partition", "value": None},
            gen_sleep(interval),
            {"type": "info", "f": "stop-partition"},
        ]
    )
    return _package(
        compose([({"start-partition": "start",
                   "stop-partition": "stop"}, nem)]),
        generator,
        final_generator={"type": "info", "f": "stop-partition"},
        perf=[{"name": "partition", "start": {"start-partition"},
               "stop": {"stop-partition"}}],
    )


def db_package(opts: dict) -> Optional[dict]:
    """Kill/pause the DB on random subsets (combined.clj:143-226)."""
    faults = opts.get("faults", set())
    kills = "kill" in faults
    pauses = "pause" in faults
    if not (kills or pauses):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)

    def targets():
        return _rng().choice([1, None])  # one node or all

    cycles = []
    if kills:
        cycles.append(
            cycle([
                gen_sleep(interval),
                once(FnGen(lambda: {"type": "info", "f": "kill", "value": targets()})),
                gen_sleep(interval),
                {"type": "info", "f": "start", "value": None},
            ])
        )
    if pauses:
        cycles.append(
            cycle([
                gen_sleep(interval),
                once(FnGen(lambda: {"type": "info", "f": "pause", "value": targets()})),
                gen_sleep(interval),
                {"type": "info", "f": "resume", "value": None},
            ])
        )
    generator = mix(cycles) if len(cycles) > 1 else cycles[0]
    final = [{"type": "info", "f": "start", "value": None}] if kills else []
    if pauses:
        final.append({"type": "info", "f": "resume", "value": None})
    perf = []
    if kills:
        perf.append({"name": "kill", "start": {"kill"}, "stop": {"start"}})
    if pauses:
        perf.append({"name": "pause", "start": {"pause"}, "stop": {"resume"}})
    return _package(
        DBNemesis(),
        generator,
        final_generator=final or None,
        perf=perf,
    )


def packet_package(opts: dict) -> Optional[dict]:
    """tc/netem packet mangling (combined.clj:288-327)."""
    if "packet" not in opts.get("faults", set()):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    behaviors = (opts.get("packet", {}) or {}).get(
        "behaviors",
        [
            {"delay": {"time": 100, "jitter": 50}},
            {"loss": {"percent": 20}},
            {"duplicate": {"percent": 20}},
            {"reorder": {"percent": 20}},
        ],
    )

    class PacketNemesis(Nemesis):
        def invoke(self, test: dict, op):
            net = test["net"]
            if op.f == "start-packet":
                b = op.ext.get("behavior") or _rng().choice(behaviors)
                net.shape(test, b)
                return op.replace(value=b)
            net.shape(test, None)
            return op.replace(value="healed")

        def teardown(self, test: dict) -> None:
            net = test.get("net")
            if net is not None:
                try:
                    # shape(None) -> net.fast, which journals the heal
                    # (or leaves entries outstanding when abandoned).
                    net.shape(test, None)
                except Exception as e:  # noqa: BLE001
                    log.warning(
                        "packet shaping teardown failed — netem may be "
                        "stranded (see the fault ledger): %r", e,
                    )

        def fs(self):
            return {"start-packet", "stop-packet"}

    generator = cycle([
        gen_sleep(interval),
        {"type": "info", "f": "start-packet"},
        gen_sleep(interval),
        {"type": "info", "f": "stop-packet"},
    ])
    return _package(
        PacketNemesis(),
        generator,
        final_generator={"type": "info", "f": "stop-packet"},
        perf=[{"name": "packet", "start": {"start-packet"},
               "stop": {"stop-packet"}}],
    )


def clock_package(opts: dict) -> Optional[dict]:
    """Clock skew faults (combined.clj:329-400)."""
    if "clock" not in opts.get("faults", set()):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)

    def bump_op():
        delta = int(_rng().choice([-1, 1]) * _rng().choice(
            [100, 1000, 10_000, 60_000]
        ))
        return {"type": "info", "f": "bump", "value": delta}

    def strobe_op():
        return {
            "type": "info",
            "f": "strobe",
            "value": {
                "delta": int(_rng().choice([50, 200, 1000])),
                "period": 10,
                "duration": 1000,
            },
        }

    generator = cycle([
        gen_sleep(interval),
        once(mix([FnGen(bump_op), FnGen(strobe_op)])),
        gen_sleep(interval),
        {"type": "info", "f": "reset", "value": None},
    ])
    return _package(
        ClockNemesis(),
        generator,
        final_generator={"type": "info", "f": "reset", "value": None},
        perf=[{"name": "clock", "start": {"bump", "strobe"},
               "stop": {"reset"}}],
    )


def file_corruption_package(opts: dict) -> Optional[dict]:
    """Bitflips/truncation on DB files (combined.clj:402-481).
    opts["file-corruption"]: {"file": path, "targets": [...]}."""
    if "file-corruption" not in opts.get("faults", set()):
        return None
    fopts = opts.get("file-corruption", {}) or {}
    path = fopts.get("file")
    if path is None:
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    generator = cycle([
        gen_sleep(interval),
        once(mix([
            FnGen(lambda: {"type": "info", "f": "bitflip",
                           "value": {"file": path}}),
            FnGen(lambda: {"type": "info", "f": "truncate",
                           "value": {"file": path, "drop": 64}}),
        ])),
    ])
    return _package(
        compose([Bitflip(), TruncateFile()]),
        generator,
    )


def compose_packages(packages: Sequence[dict]) -> dict:
    """Unified package: composed nemesis, mixed generators, sequenced
    final generators (combined.clj:483-506)."""
    packages = [p for p in packages if p is not None]
    if not packages:
        return _package(noop, None)
    nem = compose([p["nemesis"] for p in packages])
    gens = [p["generator"] for p in packages if p["generator"] is not None]
    finals = [
        p["final-generator"] for p in packages
        if p.get("final-generator") is not None
    ]
    perf: list = []
    for p in packages:
        perf.extend(p.get("perf") or [])
    return _package(
        nem,
        mix(gens) if len(gens) > 1 else (gens[0] if gens else None),
        final_generator=finals or None,
        perf=perf,
    )


def _membership_package(opts: dict) -> Optional[dict]:
    from .membership import membership_package
    return membership_package(opts)


def _lazyfs_package(opts: dict) -> Optional[dict]:
    from ..lazyfs import lazyfs_package
    return lazyfs_package(opts)


def _faketime_package(opts: dict) -> Optional[dict]:
    from ..faketime import faketime_package
    return faketime_package(opts)


#: The family registry: name -> (faults served, constructor), in
#: composition order.  One constructor may serve several fault names
#: (kill and pause share one DBNemesis — building it twice would race
#: two nemeses over the same processes).  Constructors stay
#: capability-guarded: each may return None when its faults are absent
#: from opts["faults"] or a capability is missing (no corruption file
#: path, no FUSE for lazyfs, no faketime binary), and callers drop the
#: Nones.  Membership and friends import lazily to keep fault-free
#: startup cheap and cycle-free.
FAMILY_PACKAGES: dict = {
    "partition": ({"partition"}, partition_package),
    "db": ({"kill", "pause"}, db_package),
    "packet": ({"packet"}, packet_package),
    "clock": ({"clock"}, clock_package),
    "file-corruption": ({"file-corruption"}, file_corruption_package),
    "membership": ({"membership"}, _membership_package),
    "lazyfs": ({"lazyfs"}, _lazyfs_package),
    "faketime": ({"faketime"}, _faketime_package),
}


def registry_packages(opts: Optional[dict] = None) -> list:
    """Instantiates every registered package whose served faults
    intersect opts["faults"], in registry order.  Entries may be None
    (capability-guarded constructors); `compose_packages` drops them."""
    opts = opts or {}
    faults = set(opts.get("faults") or set())
    return [
        ctor(opts)
        for served, ctor in FAMILY_PACKAGES.values()
        if faults & served
    ]


def nemesis_package(opts: Optional[dict] = None) -> dict:
    """The one-stop constructor (combined.clj:508-568): opts["faults"]
    from the FAMILY_PACKAGES registry — {"partition", "kill", "pause",
    "packet", "clock", "file-corruption", "membership", "lazyfs",
    "faketime"} (membership needs opts["membership"]["state"], lazyfs
    needs FUSE, faketime needs opts["faketime"]["binary"])."""
    opts = opts or {}
    opts.setdefault("faults", {"partition"})
    return compose_packages(registry_packages(opts))
