"""Membership nemesis: standardized grow/shrink cluster faults.

Equivalent of /root/reference/jepsen/src/jepsen/nemesis/membership.clj
(design doc :1-47) + membership/state.clj: cluster membership is a
state machine over three framework-managed pieces —

  * ``node_views``: each node's own (possibly stale, possibly divergent)
    view of the cluster, refreshed by a background poller per node;
  * ``view``: the merged, authoritative-as-far-as-we-know view;
  * ``pending``: operations we applied whose effect is not yet
    confirmed — they constrain further choices (e.g. don't start a 5th
    removal while 4 are in flight) and are *resolved* against fresh
    views via a fixed-point loop.

Databases vary wildly in how membership looks, so the specifics live in
a user-supplied `MembershipState` subclass (the reference's `State`
protocol, membership/state.clj:20-57).  Python idiom: the state object
is mutable and the nemesis serializes every touch through one lock —
the reference reaches the same end with an atom + `locking`.

The package's generator asks the *state* what operation is currently
legal (`op`), so fault scheduling adapts to the cluster's actual
condition rather than a fixed script.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

from ..control import on_nodes
from ..generator.core import PENDING, Generator, fill_in_op, stagger
from ..history import Op
from . import ledger as fault_ledger
from .core import Nemesis

log = logging.getLogger(__name__)

#: Seconds between node-view refreshes (membership.clj:57-59).
NODE_VIEW_INTERVAL = 5.0


class MembershipState:
    """Cluster-specific membership logic (membership/state.clj:20-57).

    Subclasses own any fields they like; the nemesis initializes and
    maintains `node_views` (dict node -> view), `view` (merged), and
    `pending` (list of [invocation, completion] op pairs) on the
    instance, and calls every method below under its lock."""

    node_views: dict
    view: Any
    pending: list

    def setup(self, test: dict) -> "MembershipState":
        """One-time initialization (open connections etc.)."""
        return self

    def node_view(self, test: dict, session, node: str) -> Any:
        """This node's view of the cluster, via `session`; None =
        currently unknown (ignored)."""
        return None

    def merge_views(self, test: dict) -> Any:
        """Derive the authoritative view from self.node_views."""
        return self.view

    def fs(self) -> set:
        """All op :f values this state machine may generate."""
        return set()

    def op(self, test: dict) -> Any:
        """An op template we could perform now, PENDING if nothing is
        currently legal, or None to stop generating forever."""
        return None

    def invoke(self, test: dict, op: Op) -> Op:
        """Apply a generated op to the cluster; return the completed op.
        State mutation is safe here (the nemesis holds its lock)."""
        raise NotImplementedError

    def resolve(self, test: dict) -> bool:
        """Evolve toward a fixed point after view changes; return True
        if anything changed (the loop re-runs until False)."""
        return False

    def resolve_op(self, test: dict, pair: list) -> bool:
        """True if the pending [op, op'] pair is now confirmed complete
        (it is then removed from `pending`)."""
        return False

    def teardown(self, test: dict) -> None:
        pass


class MembershipNemesis(Nemesis):
    """Drives a MembershipState: background per-node view pollers, op
    application, pending-op bookkeeping (membership.clj:150-232)."""

    def __init__(self, state: MembershipState,
                 view_interval: float = NODE_VIEW_INTERVAL):
        self.state = state
        self.lock = threading.RLock()
        self.view_interval = view_interval
        self._stop = threading.Event()
        self._pollers: list[threading.Thread] = []
        # Ledger entry ids per pending pair, keyed by id(pair): user
        # code unpacks pending pairs as 2-tuples, so the id cannot ride
        # the list itself.
        self._intents: dict[int, int] = {}

    # -- resolution --------------------------------------------------------

    def _resolve(self, test: dict) -> None:
        """resolve + resolve-ops to fixed point (membership.clj:94-117),
        caller holds the lock."""
        st = self.state
        for _ in range(1000):  # fixed-point with a runaway guard
            changed = st.resolve(test)
            for pair in list(st.pending):
                if st.resolve_op(test, pair):
                    log.info("resolved membership op: %s", pair[0])
                    st.pending.remove(pair)
                    eid = self._intents.pop(id(pair), None)
                    if eid is not None:
                        fault_ledger.healed(test, entry_id=eid,
                                            by="resolve")
                    changed = True
            if not changed:
                return

    def _update_node_view(self, test: dict, node: str) -> None:
        def view(sess, n):
            return self.state.node_view(test, sess, n)

        nv = on_nodes(test, view, [node]).get(node)
        if nv is None:
            return
        with self.lock:
            st = self.state
            if st.node_views.get(node) != nv:
                log.debug("new node view from %s: %s", node, nv)
            st.node_views[node] = nv
            st.view = st.merge_views(test)
            self._resolve(test)

    def _poll(self, test: dict, node: str) -> None:
        while not self._stop.is_set():
            try:
                self._update_node_view(test, node)
            except Exception:  # noqa: BLE001 — poller must survive
                log.warning(
                    "membership view poller for %s failed; will retry",
                    node, exc_info=True,
                )
            self._stop.wait(self.view_interval)

    # -- Nemesis protocol --------------------------------------------------

    def setup(self, test: dict) -> "MembershipNemesis":
        with self.lock:
            st = self.state
            st.node_views = {}
            st.view = None
            st.pending = []
            self.state = st.setup(test)
        for node in test.get("nodes") or []:
            t = threading.Thread(
                target=self._poll, args=(test, node),
                name=f"membership-view-{node}", daemon=True,
            )
            t.start()
            self._pollers.append(t)
        return self

    def invoke(self, test: dict, op: Op) -> Op:
        with self.lock:
            # Membership changes have no mechanical inverse the framework
            # could replay (the state machine owns the cluster logic), so
            # the ledger records them for the repair report only.
            eid = fault_ledger.intent(
                test, "membership",
                params={"f": op.f, "value": op.value},
                compensator={
                    "type": "unreplayable",
                    "note": "membership change; converge via the state "
                            "machine or operator action",
                },
                tag="membership",
            )
            op2 = self.state.invoke(test, op)
            pair = [op, op2]
            self.state.pending.append(pair)
            if eid is not None:
                self._intents[id(pair)] = eid
            self._resolve(test)
            return op2

    def teardown(self, test: dict) -> None:
        self._stop.set()
        for t in self._pollers:
            t.join(timeout=2.0)
        with self.lock:
            state = self.state
        state.teardown(test)

    def fs(self) -> set:
        with self.lock:
            state = self.state
        return set(state.fs())


class MembershipGenerator(Generator):
    """Asks the state machine for the next legal operation
    (membership.clj:234-244)."""

    __slots__ = ("nemesis",)

    def __init__(self, nemesis: MembershipNemesis):
        self.nemesis = nemesis

    def op(self, test, ctx):
        with self.nemesis.lock:
            o = self.nemesis.state.op(test)
        if o is None:
            return None
        if o is PENDING or o == "pending":
            return (PENDING, self)
        filled = fill_in_op(dict(o), ctx)
        return (filled, self)


def membership_package(opts: dict) -> Optional[dict]:
    """Package constructor (membership.clj:246-270).  opts:

        {"faults": {"membership", ...},
         "membership": {"state": MembershipState instance,
                        "view-interval": secs},
         "interval": secs}

    The returned dict carries "state" so custom generators can target
    faults from the current cluster view."""
    if "membership" not in (opts.get("faults") or set()):
        return None
    mopts = opts.get("membership", {}) or {}
    state = mopts["state"]
    nem = MembershipNemesis(
        state, view_interval=mopts.get("view-interval", NODE_VIEW_INTERVAL)
    )
    gen = stagger(opts.get("interval", 10.0), MembershipGenerator(nem))
    return {
        "state": state,
        "nemesis": nem,
        "generator": gen,
        "final-generator": None,
        "perf": [],
    }
