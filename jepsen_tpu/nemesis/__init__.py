"""Fault injection: the Nemesis protocol and stock faults.

Equivalent of /root/reference/jepsen/src/jepsen/nemesis.clj plus the
nemesis/ subtree (combined packages, clock faults, membership churn).
"""

from . import ledger, search
from .core import (
    Compose,
    FMap,
    Nemesis,
    NemesisTeardownError,
    NoopNemesis,
    Partitioner,
    Timeout,
    bisect,
    bridge,
    complete_grudge,
    compose,
    f_map,
    invert_grudge,
    majorities_ring,
    noop,
    partition_halves,
    partition_majorities_ring,
    partition_random_halves,
    partition_random_node,
    partitioner,
    split_one,
    timeout,
)

__all__ = [
    "Compose",
    "FMap",
    "Nemesis",
    "NemesisTeardownError",
    "NoopNemesis",
    "ledger",
    "Partitioner",
    "Timeout",
    "bisect",
    "bridge",
    "complete_grudge",
    "compose",
    "f_map",
    "invert_grudge",
    "majorities_ring",
    "noop",
    "partition_halves",
    "partition_majorities_ring",
    "partition_random_halves",
    "partition_random_node",
    "partitioner",
    "search",
    "split_one",
    "timeout",
]
