"""Durable fault ledger: crash-safe journal of live faults + repair.

PR 2 guaranteed that a *run* always terminates with a verdict; this
module guarantees the *cluster* can always be put back the way we found
it.  Every fault-injecting nemesis action journals a declarative
**intent** record — fault family, target nodes, parameters, and a
data-described *compensator* (heal the net, ``tc qdisc del``, clock
reset + time-daemon restart, daemon restart) — into the run's store dir
**before** touching the cluster, and a **healed** record after its
compensator completes.  Records ride the store's append+fsync block
discipline (`store.format.BlockWriter`, block type `BLOCK_LEDGER`), so
a control-process crash at any instant leaves a readable ledger whose
outstanding entries are exactly the faults still live on the nodes —
the same host-side journaled-side-effect split DrJAX argues for: device
(here: cluster) mutations are described declaratively on the host and
replayable without the process that created them.

Recovery: `core.repair(test_dir)` (CLI: ``jepsen repair``) loads a
crashed run's ledger, reopens sessions, replays outstanding
compensators newest-first, appends healed records for the ones that
succeed, and finishes with `probe_residue` — a per-node sweep of
iptables/blackhole-route/tc/clock state that emits
``nemesis.residue.*`` telemetry counters (surfaced in the checker
results' ``resilience`` block).

Fault hook (mirrors ops/degrade.py's JEPSEN_WGL_FAULT): the
``JEPSEN_NEMESIS_FAULT`` env var names failure sites, comma-separated:

  * ``inject``  — raise after the intent record lands but before the
    cluster is touched (a session dropped mid-inject);
  * ``heal``    — raise at the start of any heal path (a crash
    mid-heal: the fault stays live, the entry stays outstanding);
  * ``repair``  — raise inside `run_compensator` during repair, so a
    repair pass reports that entry failed;
  * ``abandon`` — heal paths silently skip (no compensator, no healed
    record): the in-test stand-in for a control-plane SIGKILL;
  * ``all``     — every raise site above (not ``abandon``).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Optional, Sequence

from .. import telemetry
from ..store import format as store_format
from ..utils import with_retry

log = logging.getLogger(__name__)

#: Ledger file name inside a run's store dir, next to test.jtpu.
LEDGER_FILE = "nemesis.ledger"

#: The four fault families the residue probe sweeps for.
FAMILIES = ("partition", "netem", "clock", "process")

FAULT_ENV = "JEPSEN_NEMESIS_FAULT"


class InjectedNemesisFault(RuntimeError):
    """Raised by `maybe_fault` to simulate control-plane failures at
    inject/heal/repair sites."""


def fault_modes() -> set[str]:
    raw = os.environ.get(FAULT_ENV, "")
    return {m.strip() for m in raw.split(",") if m.strip()}


def maybe_fault(site: str) -> None:
    """Raises when JEPSEN_NEMESIS_FAULT names `site` (or "all").  Read
    per call so tests can toggle sites without reimporting."""
    modes = fault_modes()
    if site in modes or "all" in modes:
        raise InjectedNemesisFault(
            f"injected nemesis fault at site {site!r} "
            f"({FAULT_ENV}={os.environ.get(FAULT_ENV)!r})"
        )


def abandoned() -> bool:
    """True when heal paths should be skipped entirely — the SIGKILL
    simulation: the ledger keeps its outstanding entries and the faults
    stay live for `repair` to find."""
    return "abandon" in fault_modes()


def heal_guard() -> bool:
    """The one check every heal path runs first: raises on the "heal"
    fault site, returns True when healing is abandoned (caller returns
    without compensating or journaling)."""
    maybe_fault("heal")
    return abandoned()


# ---------------------------------------------------------------------------
# The ledger itself
# ---------------------------------------------------------------------------


class FaultLedger:
    """Append-only intent/healed journal over one `BlockWriter`.

    The file is created lazily on the first intent, so fault-free runs
    write nothing (the no-overhead contract).  Reopening a ledger with
    a torn tail (crashed writer) truncates back to the last valid block
    via the writer's `_valid_end` recovery, so repair can append fresh
    healed records to a file the dying process half-wrote."""

    def __init__(self, path: str):
        self.path = path
        self._writer: Optional[store_format.BlockWriter] = None
        self._next_id = 1
        self._lock = threading.Lock()

    # -- write side ------------------------------------------------------

    def _open(self) -> store_format.BlockWriter:
        if self._writer is None:
            for rec in read_records(self.path):
                if rec.get("id", 0) >= self._next_id:
                    self._next_id = rec["id"] + 1
            self._writer = store_format.BlockWriter(self.path)
        return self._writer

    def _append(self, rec: dict) -> None:
        w = self._open()
        w.append(store_format.BLOCK_LEDGER, rec)
        w.sync()

    def intent(
        self,
        fault: str,
        *,
        nodes: Optional[Sequence[str]] = None,
        params: Optional[dict] = None,
        compensator: Optional[dict] = None,
        tag: Optional[str] = None,
    ) -> int:
        """Journals one fault intent; returns its entry id.  Call BEFORE
        touching the cluster: the append+fsync must land first, so a
        crash between journal and injection errs toward a spurious
        compensator replay (idempotent) rather than a stranded fault."""
        with self._lock:
            # _open may bump _next_id past prior records on first use.
            self._open()
            eid = self._next_id
            self._next_id += 1
            self._append({
                "rec": "intent",
                "id": eid,
                "fault": fault,
                "tag": tag,
                "nodes": sorted(nodes) if nodes else [],
                "params": params or {},
                "comp": compensator or {"type": "unreplayable"},
                "t": time.time(),
            })
        telemetry.count("nemesis.ledger.intents")
        return eid

    def healed(self, entry_id: int, *, by: str = "run",
               note: Optional[str] = None) -> None:
        """Journals that entry_id's compensator completed.  Call AFTER
        the compensator succeeds, never before."""
        with self._lock:
            rec: dict[str, Any] = {
                "rec": "healed", "id": entry_id, "by": by, "t": time.time(),
            }
            if note:
                rec["note"] = note
            self._append(rec)
        telemetry.count("nemesis.ledger.healed")

    def note(self, why: str, **fields: Any) -> None:
        """Journals an informational record (e.g. the nemesis skipping a
        quarantined node).  Notes carry no compensator and are ignored
        by `outstanding_entries` — pure post-mortem context."""
        with self._lock:
            self._append({
                "rec": "note", "why": why, "t": time.time(), **fields,
            })
        telemetry.count("nemesis.ledger.notes")

    def heal_matching(
        self,
        *,
        fault: Optional[str] = None,
        tag: Optional[str] = None,
        ctype: Optional[str] = None,
        by: str = "run",
    ) -> list[int]:
        """Marks every outstanding entry matching the filters healed
        (a heal like ``net.heal`` or ``iptables -F`` clears the whole
        family at once, not one grudge).  Returns the ids healed."""
        ids = []
        for e in self.outstanding():
            if fault is not None and e.get("fault") != fault:
                continue
            if tag is not None and e.get("tag") != tag:
                continue
            if ctype is not None and (e.get("comp") or {}).get("type") != ctype:
                continue
            ids.append(e["id"])
        for eid in ids:
            self.healed(eid, by=by)
        return ids

    # -- read side -------------------------------------------------------

    def records(self) -> list[dict]:
        with self._lock:
            if self._writer is not None:
                self._writer.f.flush()
        return read_records(self.path)

    def outstanding(self) -> list[dict]:
        return outstanding_entries(self.records())

    def close(self) -> None:
        with self._lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None


def read_records(path: str) -> list[dict]:
    """All valid ledger records in file order.  A torn/corrupt tail is
    ignored (same `_valid_end` discipline as the test file): everything
    up to the last fsynced block survives a crash."""
    if not os.path.exists(path):
        return []
    size = os.path.getsize(path)
    out: list[dict] = []
    try:
        with open(path, "rb") as f:
            if f.read(len(store_format.MAGIC)) != store_format.MAGIC:
                return []
            while True:
                rec = store_format._read_block(f, size)
                if rec is None:
                    break
                _, btype, payload = rec
                if btype == store_format.BLOCK_LEDGER and isinstance(
                    payload, dict
                ):
                    out.append(payload)
    except OSError as e:  # pragma: no cover - unreadable file
        log.warning("fault ledger %s unreadable: %r", path, e)
    return out


def outstanding_entries(records: list[dict]) -> list[dict]:
    """Intents with no healed record, NEWEST FIRST — the replay order:
    compensate in reverse injection order, the same unwinding a
    correctly exiting run would have performed."""
    healed_ids = {r["id"] for r in records if r.get("rec") == "healed"}
    out = [
        r for r in records
        if r.get("rec") == "intent" and r["id"] not in healed_ids
    ]
    out.sort(key=lambda r: r["id"], reverse=True)
    return out


def ledger_path(test_dir: str) -> str:
    return os.path.join(test_dir, LEDGER_FILE)


def read_outstanding(path: str) -> list[dict]:
    """Unhealed intents at `path`, newest first — the one-call probe
    the monitor's resume path (and its smoke) uses to decide whether a
    crash left fault debt behind."""
    return outstanding_entries(read_records(path))


# ---------------------------------------------------------------------------
# Test-map helpers: every nemesis call site goes through these, so a
# test without a bound ledger (unit tests, library use) pays one dict
# get and nothing else.
# ---------------------------------------------------------------------------


def ledger_of(test: dict) -> Optional[FaultLedger]:
    led = test.get("fault-ledger")
    return led if isinstance(led, FaultLedger) else None


def intent(
    test: dict,
    fault: str,
    *,
    nodes: Optional[Sequence[str]] = None,
    params: Optional[dict] = None,
    compensator: Optional[dict] = None,
    tag: Optional[str] = None,
) -> Optional[int]:
    """Journal an intent (when a ledger is bound), then run the
    mid-inject fault site.  The hook fires even without a ledger so the
    injection paths can be crash-tested in isolation."""
    led = ledger_of(test)
    eid = None
    if led is not None:
        eid = led.intent(
            fault, nodes=nodes, params=params, compensator=compensator,
            tag=tag,
        )
    maybe_fault("inject")
    return eid


def healed(
    test: dict,
    *,
    fault: Optional[str] = None,
    tag: Optional[str] = None,
    ctype: Optional[str] = None,
    entry_id: Optional[int] = None,
    by: str = "run",
) -> list[int]:
    led = ledger_of(test)
    if led is None:
        return []
    if entry_id is not None:
        led.healed(entry_id, by=by)
        return [entry_id]
    return led.heal_matching(fault=fault, tag=tag, ctype=ctype, by=by)


def note(test: dict, why: str, **fields: Any) -> None:
    """Journal an informational note when a ledger is bound; silently a
    no-op otherwise (notes are context, never obligations)."""
    led = ledger_of(test)
    if led is not None:
        led.note(why, **fields)


def net_mech(net: Any) -> str:
    """Names the partition mechanism a live Net uses, so the net-heal
    compensator can be replayed without the object: "iptables",
    "ipfilter", "route", "noop", or "all" (unknown impl: try
    everything)."""
    name = type(net).__name__
    if "Ipfilter" in name:
        return "ipfilter"
    if "Iptables" in name:
        return "iptables"
    if "Route" in name:
        return "route"
    if "Noop" in name:
        return "noop"
    return "all"


# ---------------------------------------------------------------------------
# Compensator execution
# ---------------------------------------------------------------------------

#: Per-node retry policy for compensators: small and bounded — repair
#: must make progress past a dead node, not wait on it.
COMP_RETRIES = 2
COMP_BACKOFF_MS = 100.0


def _heal_net_node(sess: Any, mech: str) -> None:
    if mech in ("iptables", "all"):
        with sess.su():
            sess.exec_star("iptables", "-F", "-w")
            sess.exec_star("iptables", "-X", "-w")
    if mech in ("route", "all"):
        with sess.su():
            sess.exec_star(
                "bash", "-c", "ip route flush type blackhole || true"
            )
    if mech == "ipfilter":
        with sess.su():
            sess.exec_star("ipf", "-Fa")


def _tc_del_node(sess: Any, dev: str) -> None:
    with sess.su():
        # Deleting a nonexistent qdisc exits nonzero; that is the
        # healthy case, so exec_star (never raises on exit codes).
        sess.exec_star("tc", "qdisc", "del", "dev", dev, "root")


def _clock_reset_node(sess: Any) -> None:
    with sess.su():
        sess.exec_star("ntpdate", "-b", "pool.ntp.org")
        # ClockNemesis.setup stopped these; a healed node gets its time
        # daemons back (the "daemon restart" half of the compensator).
        sess.exec_star("systemctl", "start", "ntp", "chronyd",
                       "systemd-timesyncd")


def run_compensator(test: dict, entry: dict) -> dict:
    """Executes one entry's data-described compensator, per-node and
    best-effort: each node gets `with_retry` over transport failures,
    and one unreachable node cannot abort healing the rest.  Returns
    {"ok": bool, "nodes": {node: "ok" | "failed: ..."}}."""
    comp = entry.get("comp") or {}
    ctype = comp.get("type", "unreplayable")
    sessions = test.get("sessions") or {}
    nodes = comp.get("nodes") or entry.get("nodes") or list(sessions.keys())
    results: dict[str, str] = {}

    if ctype == "none":
        return {"ok": True, "nodes": {}}
    if ctype == "unreplayable":
        note = comp.get("note") or "compensator not data-describable"
        return {"ok": False, "nodes": {},
                "error": f"unreplayable: {note}"}

    def node_action(sess: Any, node: str) -> None:
        if ctype == "net-heal":
            _heal_net_node(sess, comp.get("mech", "all"))
        elif ctype == "tc-del":
            _tc_del_node(sess, comp.get("dev", "eth0"))
        elif ctype == "clock-reset":
            _clock_reset_node(sess)
        elif ctype == "sigcont":
            with sess.su():
                sess.exec_star(
                    "pkill", "-CONT", "-f", comp.get("process", "")
                )
        elif ctype == "db-start":
            db = test.get("db")
            if db is None:
                raise RuntimeError("no live db object; pass one to repair")
            db.start(test, sess, node)
        elif ctype == "db-resume":
            db = test.get("db")
            if db is None:
                raise RuntimeError("no live db object; pass one to repair")
            db.resume(test, sess, node)
        elif ctype == "faketime-unwrap":
            from .. import faketime
            cmd = comp.get("cmd")
            if not cmd:
                raise RuntimeError("faketime-unwrap without a cmd")
            with sess.su():
                faketime.unwrap(sess, cmd)
        else:
            raise RuntimeError(f"unknown compensator type {ctype!r}")

    ok = True
    for node in nodes:
        sess = sessions.get(node)
        if sess is None:
            results[node] = "failed: no session"
            ok = False
            continue
        try:
            maybe_fault("repair")
            with_retry(
                lambda s=sess, n=node: node_action(s, n),
                retries=COMP_RETRIES,
                backoff_ms=COMP_BACKOFF_MS,
            )
            results[node] = "ok"
        except Exception as e:  # noqa: BLE001 — continue through siblings
            log.warning(
                "compensator %s for entry %s failed on %s: %r",
                ctype, entry.get("id"), node, e,
            )
            results[node] = f"failed: {type(e).__name__}: {e}"
            ok = False
    if comp.get("mech") == "noop" and ctype == "net-heal":
        # Nothing to undo on a noop net; the loop above was a no-op too.
        ok = True
    return {"ok": ok, "nodes": results}


# ---------------------------------------------------------------------------
# Residue probe sweep
# ---------------------------------------------------------------------------


def _probe_int(sess: Any, script: str) -> int:
    res = sess.exec_star("bash", "-c", script)
    try:
        return int((res.get("out") or "").strip().splitlines()[-1])
    except (ValueError, IndexError):
        return 0


def _probe_node(sess: Any) -> dict:
    """One node's fault residue: leftover iptables DROP rules, blackhole
    routes, tc qdiscs, and wall-clock skew vs the control node.  Every
    probe is best-effort (missing binaries read as clean)."""
    out: dict[str, Any] = {}
    with sess.su():
        out["iptables"] = _probe_int(
            sess,
            "command -v iptables >/dev/null 2>&1 && "
            "iptables -S 2>/dev/null | grep -c -- '-j DROP' || echo 0",
        )
        out["route"] = _probe_int(
            sess,
            "ip route show type blackhole 2>/dev/null | wc -l",
        )
        out["tc"] = _probe_int(
            sess,
            "tc qdisc show 2>/dev/null | grep -cE 'netem|tbf' || echo 0",
        )
    skew = 0.0
    res = sess.exec_star("date", "+%s.%N")
    raw = (res.get("out") or "").strip()
    if raw:
        try:
            skew = abs(float(raw) - time.time())
        except ValueError:
            skew = 0.0
    # Sub-5s offsets are indistinguishable from exec latency + honest
    # drift; the clock faults injected here are >= 100 ms bumps on top
    # of synchronized clocks, and stranded skew is typically seconds+.
    out["clock_skew_s"] = round(skew, 3) if skew >= 5.0 else 0.0
    return out


def probe_residue(
    test: dict, *, ledger: Optional[FaultLedger] = None,
    path: Optional[str] = None,
) -> dict:
    """Sweeps every session-reachable node for fault residue and counts
    what it finds as ``nemesis.residue.<kind>`` telemetry counters
    (which `core.analyze` surfaces in the results' ``resilience``
    block).  Also counts the ledger's outstanding entries.  Returns
    {"clean": bool, "outstanding": n, "nodes": {node: probe}}."""
    sessions = test.get("sessions") or {}
    nodes: dict[str, dict] = {}
    residue_totals: dict[str, float] = {}
    for node, sess in sessions.items():
        try:
            probe = _probe_node(sess)
        except Exception as e:  # noqa: BLE001 — sweep must finish
            log.warning("residue probe on %s failed: %r", node, e)
            nodes[node] = {"error": f"{type(e).__name__}: {e}"}
            telemetry.count("nemesis.residue.unprobed")
            continue
        nodes[node] = probe
        for kind, val in (
            ("iptables", probe["iptables"]),
            ("route", probe["route"]),
            ("tc", probe["tc"]),
            ("clock", 1 if probe["clock_skew_s"] else 0),
        ):
            if val:
                residue_totals[kind] = residue_totals.get(kind, 0) + val
    for kind, val in residue_totals.items():
        telemetry.count(f"nemesis.residue.{kind}", val)

    if ledger is None and path is None:
        led_test = ledger_of(test)
        outstanding = led_test.outstanding() if led_test else []
    elif ledger is not None:
        outstanding = ledger.outstanding()
    else:
        outstanding = outstanding_entries(read_records(path))
    if outstanding:
        telemetry.count("nemesis.residue.outstanding", len(outstanding))

    clean = not residue_totals and not outstanding and not any(
        "error" in p for p in nodes.values()
    )
    return {
        "clean": clean,
        "outstanding": len(outstanding),
        "nodes": nodes,
    }
