"""Coverage-guided nemesis schedule search: the fault matrix as a fuzzer.

`tools/fault_matrix.py` enumerates fault scenarios by hand; this module
*searches* the schedule space the way TVM searches kernel-schedule
space: a **schedule genome** — a timed sequence of fault events — is
compiled into a runnable nemesis + generator pair (through the same
family packages `nemesis_package` composes), executed as a full
`core.run` in its own store dir, and scored by a **coverage map** keyed
on the run's observable behavior:

  * resilience counters (``node.*``, ``net.*``, ``nemesis.*``,
    ``wgl.degrade.*``, ``client.open.*``), log2-bucketed;
  * checker verdict/anomaly signatures per composed checker;
  * fault-ledger outcomes per family (healed-by-run vs healed-by-
    teardown vs healed-by-repair vs left outstanding).

Schedules that surface new feature combinations enter a **corpus**
persisted to the search dir; mutation and crossover operators (perturb
timing, swap families, widen/narrow target overlap, splice two
schedules) breed the next candidates from it.  Any schedule producing
a *hang*, *residue*, *unhealed ledger entry*, or *checker anomaly* is
handed to a **shrinker** that minimizes it to a smallest reproducing
schedule, emitted as a fault-matrix cell JSON under
``<search-dir>/cells/`` (replayable via
``tools/fault_matrix.py --cell <file>``).

Crash-safety contract: every searched event runs through the ordinary
nemeses, so it is born on the PR 4 fault ledger (intent-before-inject
+ data-described compensator) in its *iteration's own store dir* — a
search process SIGKILLed mid-iteration leaves a normal crashed run
that ``jepsen repair <run-dir>`` heals, and `run_search` begins by
sweeping its runs dir for exactly those leftovers
(`heal_crashed_iterations`).  Targeting is floor-checked against the
``--node-loss-policy`` minimum *before* a schedule runs
(`respects_floor` / `enforce_floor`), and every node-targeting op goes
through `faults._pick_nodes`, which drops quarantined nodes at invoke
time — so the search can never fault the cluster below its survivable
minimum.

Determinism: a schedule carries its own seed, and every event carries
a `salt`; `materialize` draws all randomness (grudge choice, node
picks, netem behaviors, clock deltas) from ``Random(seed ^ salt)`` per
event — so the same genome always compiles to the same op timeline
(replays are deterministic), and the shrinker can drop events without
perturbing how the survivors materialize.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import os
import random
import time
from typing import Any, Callable, Optional, Sequence

from .. import telemetry
from ..control import health
from ..generator.core import sleep as gen_sleep, time_limit
from ..utils import JepsenTimeout, timeout as run_timeout
from . import ledger as fault_ledger
from .core import (
    bisect,
    bridge,
    complete_grudge,
    compose,
    split_one,
)

log = logging.getLogger(__name__)

#: File names inside a search dir.
STATE_FILE = "search.json"
CORPUS_DIR = "corpus"
CELLS_DIR = "cells"
RUNS_DIR = "runs"

#: Seconds of workload tail after the last heal op, so the checker sees
#: post-fault recovery behavior too.
TAIL_S = 0.3

#: Families whose active window takes nodes out of service, counted
#: against the --node-loss-policy floor.  Partition/packet/clock degrade
#: links or clocks but leave processes serving; kill/pause (and the
#: unrecoverable file corruptions) take the node down outright.
NODE_DOWN_FAMILIES = frozenset({"kill", "pause", "bitflip", "truncate"})

#: The default search pool: every family whose compensator is
#: data-replayable, so a crashed iteration is always fully healable by
#: `jepsen repair` (bitflip/truncate journal an *unreplayable*
#: "restore from backup" compensator and are opt-in via
#: opts["search-families"]).
DEFAULT_FAMILIES = ("partition", "kill", "pause", "packet", "clock")

#: Grudge kinds the partition family draws from.
PARTITION_KINDS = ("one", "majority", "majorities-ring", "bridge")

#: netem behaviors the packet family draws from (mirrors
#: combined.packet_package's defaults).
PACKET_BEHAVIORS = (
    {"delay": {"time": 100, "jitter": 50}},
    {"loss": {"percent": 20}},
    {"duplicate": {"percent": 20}},
    {"reorder": {"percent": 20}},
)

CLOCK_DELTAS_MS = (100, 1000, 10_000, 60_000)


# ---------------------------------------------------------------------------
# Genome
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Event:
    """One timed fault: inject at `t`, heal at `t + duration`.

    `targets`: None = all nodes, int = that many (materialized to an
    explicit node list, which `_pick_nodes` still filters against the
    quarantine set at invoke time), list = exactly those nodes.
    `salt` isolates this event's randomness from its neighbors'."""

    family: str
    t: float
    duration: float
    targets: Any = None
    params: dict = dataclasses.field(default_factory=dict)
    salt: int = 0

    def to_json(self) -> dict:
        return {
            "family": self.family,
            "t": round(self.t, 4),
            "duration": round(self.duration, 4),
            "targets": list(self.targets)
            if isinstance(self.targets, (list, tuple)) else self.targets,
            "params": self.params,
            "salt": self.salt,
        }

    @staticmethod
    def from_json(d: dict) -> "Event":
        return Event(
            family=d["family"],
            t=float(d["t"]),
            duration=float(d["duration"]),
            targets=d.get("targets"),
            params=dict(d.get("params") or {}),
            salt=int(d.get("salt", 0)),
        )


@dataclasses.dataclass(frozen=True)
class Schedule:
    """A timed sequence of fault events plus the seed that pins every
    random choice made while materializing them."""

    seed: int
    events: tuple = ()

    @property
    def horizon(self) -> float:
        """When the last heal lands."""
        return max((e.t + e.duration for e in self.events), default=0.0)

    @property
    def families(self) -> set:
        return {e.family for e in self.events}

    def to_json(self) -> dict:
        return {
            "seed": self.seed,
            "events": [e.to_json() for e in sorted(
                self.events, key=lambda e: (e.t, e.salt))],
        }

    @staticmethod
    def from_json(d: dict) -> "Schedule":
        return Schedule(
            seed=int(d["seed"]),
            events=tuple(Event.from_json(e) for e in d.get("events") or []),
        )


def _event_rng(sched: Schedule, event: Event) -> random.Random:
    # Independent of event *position*: the shrinker can drop neighbors
    # without changing how this event materializes.
    return random.Random((sched.seed << 17) ^ (event.salt * 2654435761))


# ---------------------------------------------------------------------------
# Materialization: genome -> concrete op timeline
# ---------------------------------------------------------------------------


def _grudge(kind: str, nodes: list, rng: random.Random,
            isolate: Optional[str] = None) -> dict:
    nodes = sorted(str(n) for n in nodes)
    if kind == "one":
        if isolate is not None and isolate in nodes:
            rest = [n for n in nodes if n != isolate]
            comp = ([isolate], rest)
        else:
            comp = split_one(nodes, rng)
        return complete_grudge(comp)
    if kind == "majority":
        shuffled = list(nodes)
        rng.shuffle(shuffled)
        return complete_grudge(bisect(shuffled))
    if kind == "majorities-ring":
        # majorities_ring shuffles via the generator RNG; pre-shuffle
        # here with the event RNG and accept its internal reshuffle —
        # determinism comes from passing the *explicit* grudge into the
        # op, computed once here.
        shuffled = list(nodes)
        rng.shuffle(shuffled)
        n = len(shuffled)
        from ..utils import majority as _maj

        k = _maj(n) // 2
        grudge = {}
        for i, node in enumerate(shuffled):
            visible = {shuffled[(i + d) % n] for d in range(-k, k + 1)}
            grudge[node] = set(shuffled) - visible
        return grudge
    if kind == "bridge":
        shuffled = list(nodes)
        rng.shuffle(shuffled)
        return bridge(shuffled)
    raise ValueError(f"unknown partition kind {kind!r}")


def _target_list(event: Event, nodes: list, rng: random.Random) -> list:
    if isinstance(event.targets, (list, tuple)):
        return [str(n) for n in event.targets]
    if isinstance(event.targets, int):
        picked = sorted(str(n) for n in nodes)
        rng.shuffle(picked)
        return sorted(picked[:max(1, event.targets)])
    return sorted(str(n) for n in nodes)


def target_width(event: Event, n_nodes: int) -> int:
    """How many nodes this event can take down at once."""
    if isinstance(event.targets, (list, tuple)):
        return len(event.targets)
    if isinstance(event.targets, int):
        return min(max(1, event.targets), n_nodes)
    return n_nodes


def materialize(sched: Schedule, nodes: Sequence[Any]) -> list:
    """The concrete op timeline: [(t, op_dict), ...] sorted by time.
    Deterministic in (schedule, nodes): same genome, same ops."""
    nodes = [str(n) for n in nodes]
    timeline: list[tuple[float, dict]] = []
    for e in sorted(sched.events, key=lambda e: (e.t, e.salt)):
        rng = _event_rng(sched, e)
        heal_t = e.t + e.duration
        if e.family == "partition":
            kind = e.params.get("kind") or rng.choice(PARTITION_KINDS)
            g = _grudge(kind, nodes, rng, isolate=e.params.get("isolate"))
            timeline.append((e.t, {
                "type": "info", "f": "start-partition",
                "value": {k: sorted(v) for k, v in g.items()},
            }))
            timeline.append((heal_t, {
                "type": "info", "f": "stop-partition", "value": None,
            }))
        elif e.family in ("kill", "pause"):
            picked = _target_list(e, nodes, rng)
            start_f, stop_f = (
                ("kill", "start") if e.family == "kill"
                else ("pause", "resume")
            )
            timeline.append((e.t, {
                "type": "info", "f": start_f, "value": picked,
            }))
            timeline.append((heal_t, {
                "type": "info", "f": stop_f, "value": None,
            }))
        elif e.family == "packet":
            behavior = e.params.get("behavior") or rng.choice(
                list(PACKET_BEHAVIORS)
            )
            timeline.append((e.t, {
                "type": "info", "f": "start-packet", "value": None,
                "behavior": behavior,
            }))
            timeline.append((heal_t, {
                "type": "info", "f": "stop-packet", "value": None,
            }))
        elif e.family == "clock":
            delta = e.params.get("delta_ms") or int(
                rng.choice([-1, 1]) * rng.choice(list(CLOCK_DELTAS_MS))
            )
            picked = _target_list(e, nodes, rng)
            timeline.append((e.t, {
                "type": "info", "f": "bump",
                "value": {n: delta for n in picked},
            }))
            timeline.append((heal_t, {
                "type": "info", "f": "reset", "value": None,
            }))
        elif e.family == "bitflip":
            spec = {"file": e.params.get("file")}
            timeline.append((e.t, {
                "type": "info", "f": "bitflip", "value": spec,
            }))
        elif e.family == "truncate":
            spec = {"file": e.params.get("file"),
                    "drop": int(e.params.get("drop", 64))}
            timeline.append((e.t, {
                "type": "info", "f": "truncate", "value": spec,
            }))
        elif e.family == "lazyfs":
            timeline.append((e.t, {
                "type": "info", "f": "lose-unfsynced-writes",
                "value": None,
            }))
        elif e.family == "faketime":
            picked = _target_list(e, nodes, rng)
            rate = e.params.get("rate") or round(
                0.5 + rng.random(), 3
            )
            timeline.append((e.t, {
                "type": "info", "f": "start-faketime",
                "value": {"nodes": picked, "rate": rate},
            }))
            timeline.append((heal_t, {
                "type": "info", "f": "stop-faketime", "value": None,
            }))
        else:
            raise ValueError(f"unknown fault family {e.family!r}")
    timeline.sort(key=lambda pair: pair[0])
    return timeline


#: family -> the nemesis_package faults key that provides its nemesis.
_PKG_FAULT = {
    "partition": "partition",
    "kill": "kill",
    "pause": "pause",
    "packet": "packet",
    "clock": "clock",
    "bitflip": "file-corruption",
    "truncate": "file-corruption",
    "lazyfs": "lazyfs",
    "faketime": "faketime",
}

#: family -> idempotent final heal op appended after the horizon.
_FINAL_HEAL = {
    "partition": {"type": "info", "f": "stop-partition", "value": None},
    "kill": {"type": "info", "f": "start", "value": None},
    "pause": {"type": "info", "f": "resume", "value": None},
    "packet": {"type": "info", "f": "stop-packet", "value": None},
    "clock": {"type": "info", "f": "reset", "value": None},
    "faketime": {"type": "info", "f": "stop-faketime", "value": None},
}


def compile_schedule(sched: Schedule, opts: Optional[dict] = None,
                     *, nodes: Sequence[Any]) -> dict:
    """Compiles a genome into a package dict {"nemesis", "generator",
    "timeline", "horizon"}: the nemesis is composed from the same
    family packages `nemesis_package` uses (via the FAMILY_PACKAGES
    registry), and the generator is the schedule's materialized op
    timeline as a sleep-sequenced script, ending with one idempotent
    heal op per family.  Route it with
    ``gen.nemesis(pkg["generator"], client_gen)``."""
    from .combined import registry_packages

    opts = dict(opts or {})
    fams = sched.families
    opts["faults"] = {_PKG_FAULT[f] for f in fams}
    pkgs = [p for p in registry_packages(opts) if p is not None]
    nem = compose([p["nemesis"] for p in pkgs]) if pkgs else None

    timeline = materialize(sched, nodes)
    steps: list = []
    now = 0.0
    for t, op in timeline:
        if t > now:
            steps.append(gen_sleep(t - now))
            now = t
        steps.append(op)
    horizon = sched.horizon
    if horizon > now:
        steps.append(gen_sleep(horizon - now))
    for fam in sorted(fams):
        heal = _FINAL_HEAL.get(fam)
        if heal is not None:
            steps.append(dict(heal))
    return {
        "nemesis": nem,
        "generator": steps or None,
        "timeline": timeline,
        "horizon": horizon,
    }


# ---------------------------------------------------------------------------
# Floor enforcement
# ---------------------------------------------------------------------------


def max_concurrent_down(sched: Schedule, n_nodes: int) -> int:
    """The worst-case number of nodes simultaneously taken down by
    overlapping NODE_DOWN_FAMILIES windows."""
    edges: list[tuple[float, int]] = []
    for e in sched.events:
        if e.family not in NODE_DOWN_FAMILIES:
            continue
        w = target_width(e, n_nodes)
        edges.append((e.t, w))
        edges.append((e.t + e.duration, -w))
    # Heals sort before injections at the same instant: a back-to-back
    # heal/inject pair is sequential, not overlapping.
    edges.sort(key=lambda p: (p[0], p[1]))
    worst = cur = 0
    for _, delta in edges:
        cur += delta
        worst = max(worst, cur)
    return min(worst, n_nodes)


def respects_floor(sched: Schedule, n_nodes: int, min_nodes: int) -> bool:
    """True when the schedule can never fault the cluster below
    `min_nodes` live nodes."""
    return n_nodes - max_concurrent_down(sched, n_nodes) >= min_nodes


def enforce_floor(sched: Schedule, n_nodes: int, min_nodes: int,
                  rng: random.Random) -> Schedule:
    """Repairs a floor-violating schedule by narrowing targets, then by
    dropping node-down events, until it respects the floor."""
    budget = n_nodes - min_nodes
    if budget <= 0:
        # No fault budget at all: strip every node-down event.
        return dataclasses.replace(sched, events=tuple(
            e for e in sched.events if e.family not in NODE_DOWN_FAMILIES
        ))
    for _ in range(8):
        if respects_floor(sched, n_nodes, min_nodes):
            return sched
        events = list(sched.events)
        wide = [
            i for i, e in enumerate(events)
            if e.family in NODE_DOWN_FAMILIES
            and target_width(e, n_nodes) > 1
        ]
        if wide:
            i = rng.choice(wide)
            e = events[i]
            w = target_width(e, n_nodes)
            events[i] = dataclasses.replace(e, targets=max(1, w - 1))
        else:
            down = [
                i for i, e in enumerate(events)
                if e.family in NODE_DOWN_FAMILIES
            ]
            if not down:
                return sched
            events.pop(rng.choice(down))
        sched = dataclasses.replace(sched, events=tuple(events))
    # Last resort: serial faults only.
    return dataclasses.replace(sched, events=tuple(
        e for e in sched.events if e.family not in NODE_DOWN_FAMILIES
    ))


# ---------------------------------------------------------------------------
# Mutation / crossover
# ---------------------------------------------------------------------------

#: Bounds for randomly drawn events.
MAX_T = 1.5
MIN_DURATION = 0.05
MAX_DURATION = 0.8
MAX_EVENTS = 6


def _fresh_event(families: Sequence[str], n_nodes: int,
                 rng: random.Random) -> Event:
    fam = rng.choice(list(families))
    targets: Any = None
    if fam in NODE_DOWN_FAMILIES:
        targets = rng.randint(1, max(1, n_nodes - 1))
    elif rng.random() < 0.5:
        targets = rng.randint(1, n_nodes)
    return Event(
        family=fam,
        t=round(rng.uniform(0.0, MAX_T), 3),
        duration=round(rng.uniform(MIN_DURATION, MAX_DURATION), 3),
        targets=targets,
        params={},
        salt=rng.randrange(1 << 30),
    )


def seed_schedule(family: str, seed: int) -> Schedule:
    """The deterministic single-event schedule the seed round runs for
    each family: one fault at 0.1 s, healed 0.4 s later."""
    targets = 1 if family in NODE_DOWN_FAMILIES else None
    return Schedule(seed=seed, events=(
        Event(family=family, t=0.1, duration=0.4, targets=targets,
              params={}, salt=1),
    ))


def mutate(sched: Schedule, families: Sequence[str], n_nodes: int,
           min_nodes: int, rng: random.Random) -> Schedule:
    """One mutation step: perturb timing, swap family, widen/narrow
    targets, add or drop an event — then floor-repair the result."""
    events = list(sched.events)
    ops = ["perturb_t", "perturb_dur", "retarget", "swap_family", "add",
           "overlap"]
    if len(events) > 1:
        ops.append("drop")
    op = rng.choice(ops)
    if op == "overlap" and events:
        # The composition operator: overlap an event of a DIFFERENT
        # family with an existing one — either the fresh fault fires
        # inside the anchor's window, or the anchor fires inside the
        # fresh one's.  Fault interactions live in exactly these
        # overlaps, and undirected time draws almost never hit them.
        anchor = rng.choice(events)
        others = [f for f in families if f != anchor.family] \
            or list(families)
        fresh = _fresh_event(others, n_nodes, rng)
        if rng.random() < 0.5:
            t = rng.uniform(anchor.t, anchor.t + anchor.duration)
        else:
            t = max(0.0, anchor.t
                    - fresh.duration * rng.uniform(0.05, 0.95))
        events.append(dataclasses.replace(fresh, t=round(t, 3)))
    elif op == "add" or not events:
        # Composition pressure: half the time draw the new event from a
        # family the schedule lacks, and half the time drop it inside an
        # existing event's window — overlapping multi-family schedules
        # are where the interesting bugs live, and unbiased uniform
        # draws almost never produce them.
        missing = [f for f in families
                   if f not in {e.family for e in events}]
        pool = missing if missing and rng.random() < 0.5 else families
        fresh = _fresh_event(pool, n_nodes, rng)
        if events and rng.random() < 0.5:
            anchor = rng.choice(events)
            fresh = dataclasses.replace(fresh, t=round(
                rng.uniform(anchor.t, anchor.t + anchor.duration), 3
            ))
        events.append(fresh)
    elif op == "drop":
        events.pop(rng.randrange(len(events)))
    else:
        i = rng.randrange(len(events))
        e = events[i]
        if op == "perturb_t":
            events[i] = dataclasses.replace(
                e, t=round(max(0.0, e.t + rng.uniform(-0.3, 0.3)), 3)
            )
        elif op == "perturb_dur":
            events[i] = dataclasses.replace(
                e, duration=round(min(MAX_DURATION, max(
                    MIN_DURATION, e.duration * rng.choice([0.5, 2.0])
                )), 3)
            )
        elif op == "retarget":
            w = target_width(e, n_nodes)
            w2 = max(1, min(n_nodes, w + rng.choice([-1, 1])))
            events[i] = dataclasses.replace(e, targets=w2)
        elif op == "swap_family":
            fam = rng.choice(list(families))
            targets = e.targets
            if fam in NODE_DOWN_FAMILIES and targets is None:
                targets = 1
            events[i] = dataclasses.replace(
                e, family=fam, targets=targets, params={},
                salt=rng.randrange(1 << 30),
            )
    events = events[:MAX_EVENTS]
    out = Schedule(seed=rng.randrange(1 << 32), events=tuple(events))
    return enforce_floor(out, n_nodes, min_nodes, rng)


def crossover(a: Schedule, b: Schedule, n_nodes: int, min_nodes: int,
              rng: random.Random) -> Schedule:
    """Splice: a's events before a random cut time + b's events after."""
    cut = rng.uniform(0.0, max(a.horizon, b.horizon, MIN_DURATION))
    events = tuple(e for e in a.events if e.t < cut) + tuple(
        e for e in b.events if e.t >= cut
    )
    if not events:
        events = a.events or b.events
    out = Schedule(seed=rng.randrange(1 << 32),
                   events=tuple(events)[:MAX_EVENTS])
    return enforce_floor(out, n_nodes, min_nodes, rng)


def evolve(
    frontier: Sequence[Schedule],
    families: Sequence[str],
    n_nodes: int,
    min_nodes: int,
    rng: random.Random,
    *,
    window: int = 0,
    seed_duration: float = 0.4,
) -> Schedule:
    """The standing monitor's per-window schedule chooser: the search
    loop of `run_search` unrolled to one step, so a live run can evolve
    between windows instead of between subprocess iterations.

    The first ``len(families)`` windows are the deterministic
    per-family seeds (with ``seed_duration`` substituted — a live run
    wants windows long enough that an op stream actually overlaps the
    fault), after which parents come from the novelty frontier:
    crossover when two parents exist (30%), otherwise mutation of a
    random frontier member, falling back to a mutated fresh seed when
    the frontier is empty (nothing novel yet)."""
    families = list(families)
    if not families:
        raise ValueError("evolve needs at least one fault family")
    if window < len(families):
        s = seed_schedule(families[window], seed=rng.randrange(1 << 32))
        if seed_duration != 0.4:
            s = dataclasses.replace(s, events=tuple(
                dataclasses.replace(e, duration=round(seed_duration, 3))
                for e in s.events
            ))
        return enforce_floor(s, n_nodes, min_nodes, rng)
    pool = list(frontier)
    if len(pool) >= 2 and rng.random() < 0.3:
        a, b = rng.sample(pool, 2)
        return crossover(a, b, n_nodes, min_nodes, rng)
    parent = (rng.choice(pool) if pool
              else seed_schedule(rng.choice(families),
                                 seed=rng.randrange(1 << 32)))
    return mutate(parent, families, n_nodes, min_nodes, rng)


# ---------------------------------------------------------------------------
# Coverage
# ---------------------------------------------------------------------------


def _bucket(v: float) -> int:
    return int(math.log2(v)) if v >= 1 else 0


def signature(outcome: dict) -> frozenset:
    """The feature set a run contributes to the coverage map.  `outcome`
    is what a runner returns: {"resilience": counters, "results":
    checker results, "ledger": ledger records, "hang": bool}."""
    feats: set[str] = set()
    for k, v in (outcome.get("resilience") or {}).items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if v > 0:
            feats.add(f"c:{k}:{_bucket(v)}")
    results = outcome.get("results") or {}
    if isinstance(results, dict):
        feats.add(f"v:test:{results.get('valid')}")
        for name, sub in results.items():
            if isinstance(sub, dict) and "valid" in sub:
                feats.add(f"v:{name}:{sub.get('valid')}")
                if sub.get("error"):
                    feats.add(f"a:{name}:error")
                for anom in (sub.get("anomaly-types") or []):
                    feats.add(f"a:{name}:{anom}")
        # Anomaly-forensics signatures (jepsen_tpu/forensics.py): each
        # dossier's fingerprint of WHY a verdict went bad is its own
        # fitness dimension, so the search distinguishes schedules that
        # produce *different* anomalies, not just "an anomaly".
        forens = results.get("forensics")
        if isinstance(forens, dict):
            for d in forens.get("dossiers") or []:
                if isinstance(d, dict) and d.get("signature"):
                    feats.add(f"x:{d['signature']}")
    records = outcome.get("ledger") or []
    healed_by = {
        r["id"]: r.get("by", "run")
        for r in records if r.get("rec") == "healed"
    }
    for r in records:
        if r.get("rec") != "intent":
            continue
        by = healed_by.get(r["id"])
        feats.add(
            f"l:{r.get('fault')}:{by if by else 'outstanding'}"
        )
    if outcome.get("hang"):
        feats.add("hang")
    if outcome.get("error"):
        feats.add("e:" + str(outcome["error"]).split(":", 1)[0])
    return frozenset(feats)


class CoverageMap:
    """The set of features ever observed; `add` returns the novel ones."""

    def __init__(self) -> None:
        self.features: set[str] = set()

    def add(self, sig: frozenset) -> frozenset:
        novel = frozenset(sig - self.features)
        self.features |= sig
        return novel

    def __len__(self) -> int:
        return len(self.features)


def reasons(outcome: dict) -> list[str]:
    """Why a run is worth shrinking: hang, residue, unhealed ledger
    entry, or checker anomaly.  Empty = boring."""
    out = []
    if outcome.get("hang"):
        out.append("hang")
    if outcome.get("error"):
        out.append("crash")
    resil = outcome.get("resilience") or {}
    if any(
        k.startswith("nemesis.residue.") and k != "nemesis.residue.outstanding"
        and v for k, v in resil.items()
    ):
        out.append("residue")
    records = outcome.get("ledger") or []
    if fault_ledger.outstanding_entries(list(records)):
        out.append("unhealed")
    valid = (outcome.get("results") or {}).get("valid")
    if valid is False:
        out.append("anomaly")
    elif valid not in (True, None):
        out.append("unknown")
    return out


# ---------------------------------------------------------------------------
# Corpus persistence
# ---------------------------------------------------------------------------


def _write_json_atomic(path: str, obj: Any) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True, default=repr)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class Corpus:
    """Schedules that contributed novel coverage, one JSON file each
    under <search-dir>/corpus/, written atomically so a crash never
    leaves a half-entry."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.entries: list[dict] = []
        for fn in sorted(os.listdir(directory)):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(directory, fn)) as f:
                    self.entries.append(json.load(f))
            except (OSError, ValueError):
                log.warning("corpus entry %s unreadable; skipped", fn)

    def add(self, sched: Schedule, sig: frozenset, novel: frozenset,
            iteration: int, valid: Any, interesting: list) -> dict:
        entry = {
            "id": len(self.entries),
            "iteration": iteration,
            "schedule": sched.to_json(),
            "signature": sorted(sig),
            "novel": sorted(novel),
            "valid": valid,
            "interesting": interesting,
        }
        self.entries.append(entry)
        _write_json_atomic(
            os.path.join(self.dir, f"{entry['id']:04d}.json"), entry
        )
        return entry

    def schedules(self) -> list[Schedule]:
        return [Schedule.from_json(e["schedule"]) for e in self.entries]


# ---------------------------------------------------------------------------
# Shrinker
# ---------------------------------------------------------------------------


def greedy_shrink(
    items: Sequence[Any],
    rebuild: Callable[[tuple], Any],
    is_interesting: Callable[[Any], bool],
    *,
    simplify: Optional[Callable[[Any], Any]] = None,
    max_attempts: int = 24,
    min_items: int = 1,
) -> tuple[tuple, int]:
    """The two-pass greedy delta-debugger, generic over the unit being
    minimized.  `items` is the sequence of droppable units; `rebuild`
    turns a kept subsequence back into the candidate object that
    `is_interesting` judges; `simplify` (optional) maps one unit to a
    simpler form tried in pass 2.  Pass 1 drops units largest-index
    first (never below `min_items`); pass 2 swaps each survivor for its
    simplified form; both repeat while anything sticks and the attempt
    budget holds.  Deterministic: same inputs + deterministic oracle =
    same minimum.  Returns (kept units, attempts spent).

    Shared by the nemesis schedule shrinker below and the anomaly
    forensics counterexample minimizer (jepsen_tpu/forensics.py), so
    both shrink with the same discipline."""
    attempts = 0
    cur = tuple(items)
    progressed = True
    while progressed and attempts < max_attempts:
        progressed = False
        # Pass 1: drop whole units.
        i = len(cur) - 1
        while i >= 0 and attempts < max_attempts:
            if len(cur) <= min_items:
                break
            cand = cur[:i] + cur[i + 1:]
            attempts += 1
            if is_interesting(rebuild(cand)):
                cur = cand
                progressed = True
            i -= 1
        if simplify is None:
            continue
        # Pass 2: simplify the survivors.
        for i, it in enumerate(cur):
            if attempts >= max_attempts:
                break
            simpler = simplify(it)
            if simpler is None or simpler == it:
                continue
            cand = cur[:i] + (simpler,) + cur[i + 1:]
            attempts += 1
            if is_interesting(rebuild(cand)):
                cur = cand
                progressed = True
    return cur, attempts


def shrink(sched: Schedule, is_interesting: Callable[[Schedule], bool],
           *, max_attempts: int = 24) -> tuple[Schedule, int]:
    """Greedy minimization: drop events (largest index first), then
    shorten durations and narrow targets, keeping any candidate that
    still reproduces.  Event salts pin each survivor's materialization,
    so dropping a neighbor never changes what the rest do.  Returns
    (smallest reproducer, attempts spent)."""

    def rebuild(events: tuple) -> Schedule:
        return dataclasses.replace(sched, events=events)

    def simplify(e: Event) -> Event:
        simpler = e
        if e.duration > 0.2:
            simpler = dataclasses.replace(simpler, duration=0.2)
        if isinstance(e.targets, int) and e.targets > 1:
            simpler = dataclasses.replace(simpler, targets=1)
        return simpler

    kept, attempts = greedy_shrink(
        sched.events, rebuild, is_interesting,
        simplify=simplify, max_attempts=max_attempts,
    )
    return rebuild(kept), attempts


# ---------------------------------------------------------------------------
# Running one schedule through core.run
# ---------------------------------------------------------------------------


class CoreRunner:
    """Runs a schedule as a full core.run in its own store dir under
    <search-dir>/runs/.  `factory` returns a fresh base test map whose
    "generator" key (if any) is the *client* generator; the runner
    installs the compiled nemesis + scripted nemesis generator around
    it."""

    def __init__(self, factory: Callable[[], dict], search_dir: str,
                 opts: Optional[dict] = None):
        self.factory = factory
        self.runs_dir = os.path.join(search_dir, RUNS_DIR)
        self.opts = dict(opts or {})
        self.deadline_s = float(self.opts.get("iteration-deadline", 60.0))

    def __call__(self, sched: Schedule, name: str) -> dict:
        from .. import core, generator as gen, store

        test = self.factory()
        pkg = compile_schedule(sched, self.opts, nodes=test["nodes"])
        test["name"] = name
        test["store-dir"] = self.runs_dir
        test["nemesis"] = pkg["nemesis"]
        client_gen = test.get("generator")
        test["generator"] = time_limit(
            pkg["horizon"] + TAIL_S,
            gen.nemesis(pkg["generator"], client_gen),
        )
        test.setdefault(
            "node-loss-policy",
            self.opts.get("node-loss-policy") or "tolerate:1",
        )

        was_enabled = telemetry.enabled()
        telemetry.enable(True)
        # The child run resets telemetry (core.run scoped_reset) but
        # re-seeds from this: every iteration's spans share the search
        # process's trace_id, so trace_merge can stitch a whole search
        # into one timeline.
        test["trace-parent"] = telemetry.trace_context()
        hang = False
        error = None
        run_dir = None
        try:
            res = run_timeout(
                self.deadline_s * 1000.0, lambda: core.run(test)
            )
            if res is JepsenTimeout:
                hang = True
            else:
                test = res
        except Exception as e:  # noqa: BLE001 — a crashed iteration is
            # data (its ledger shows what stayed live), not a search
            # abort.
            log.warning("search iteration %s crashed: %r", name, e)
            error = f"{type(e).__name__}: {e}"
        finally:
            telemetry.enable(was_enabled)
        resilience = dict(telemetry.resilience_counters())
        results = test.get("results") if not (hang or error) else None
        try:
            run_dir = store.test_dir(test)
        except (KeyError, ValueError):
            run_dir = None
        records: list = []
        if run_dir:
            records = fault_ledger.read_records(
                fault_ledger.ledger_path(run_dir)
            )
        return {
            "resilience": resilience,
            "results": results,
            "ledger": records,
            "hang": hang,
            "error": error,
            "run_dir": run_dir,
        }


def heal_crashed_iterations(search_dir: str,
                            template: Optional[dict] = None) -> dict:
    """Sweeps <search-dir>/runs/ for run dirs whose ledger still holds
    outstanding entries — iterations a crashed/SIGKILLed search process
    left mid-fault — and replays their compensators via `core.repair`.
    Returns {run_dir: repair_report}."""
    from .. import core

    runs_root = os.path.join(search_dir, RUNS_DIR)
    healed: dict[str, dict] = {}
    if not os.path.isdir(runs_root):
        return healed
    for name in sorted(os.listdir(runs_root)):
        name_dir = os.path.join(runs_root, name)
        if not os.path.isdir(name_dir):
            continue
        for ts in sorted(os.listdir(name_dir)):
            d = os.path.join(runs_root, name, ts)
            led = fault_ledger.ledger_path(d)
            if not os.path.exists(led):
                continue
            outstanding = fault_ledger.outstanding_entries(
                fault_ledger.read_records(led)
            )
            if not outstanding:
                continue
            log.info("healing crashed search iteration %s "
                     "(%d outstanding)", d, len(outstanding))
            healed[d] = core.repair(d, dict(template or {}))
            telemetry.count("nemesis.search.healed-iterations")
    return healed


# ---------------------------------------------------------------------------
# The search loop
# ---------------------------------------------------------------------------


def _count_preserving(stats: dict) -> None:
    """Re-emits the search's cumulative counters into the (run-reset)
    telemetry registry so `resilience_counters()` reflects the search
    regardless of how many core.run resets happened since.

    core.run's telemetry.scoped_reset() already preserves
    `nemesis.search.*` counters across iterations (see
    telemetry.FLEET_COUNTER_PREFIXES), so under normal flow this is a
    no-op; it remains as a backstop for externally-driven runners that
    reset telemetry wholesale between iterations."""
    if not telemetry.enabled():
        return
    current = telemetry.resilience_counters()
    for k, v in stats.items():
        name = f"nemesis.search.{k}"
        have = current.get(name, 0)
        if v > have:
            telemetry.count(name, v - have)


def run_search(
    runner: Callable[[Schedule, str], dict],
    *,
    search_dir: str,
    n_nodes: int,
    budget_s: float = 60.0,
    seed: int = 0,
    families: Sequence[str] = DEFAULT_FAMILIES,
    min_nodes: int = 1,
    max_iterations: Optional[int] = None,
    shrink_attempts: int = 12,
    repair_template: Optional[dict] = None,
) -> dict:
    """The coverage-guided loop: heal leftovers, seed one schedule per
    family (guaranteed early coverage growth), then breed from the
    corpus under the wall-clock budget.  Interesting outcomes (see
    `reasons`) are shrunk and emitted as fault-matrix cells.  State is
    checkpointed atomically to <search-dir>/search.json after every
    iteration, so a SIGKILL loses at most the in-flight run — which the
    next invocation's heal sweep repairs."""
    os.makedirs(search_dir, exist_ok=True)
    heal_crashed_iterations(search_dir, repair_template)

    rng = random.Random(seed)
    coverage = CoverageMap()
    corpus = Corpus(os.path.join(search_dir, CORPUS_DIR))
    cells_dir = os.path.join(search_dir, CELLS_DIR)
    os.makedirs(cells_dir, exist_ok=True)
    # Re-grow coverage from a resumed corpus so replays aren't "novel".
    for entry in corpus.entries:
        coverage.add(frozenset(entry.get("signature") or []))

    deadline = time.monotonic() + budget_s
    stats = {
        "iterations": 0, "novel": 0, "interesting": 0, "shrunk": 0,
        "shrink-attempts": 0,
    }
    history: list[dict] = []
    cells: list[dict] = []
    state_path = os.path.join(search_dir, STATE_FILE)

    def checkpoint() -> None:
        _write_json_atomic(state_path, {
            "seed": seed,
            "families": list(families),
            "n_nodes": n_nodes,
            "min_nodes": min_nodes,
            "budget_s": budget_s,
            "coverage": len(coverage),
            "features": sorted(coverage.features),
            "counters": {f"nemesis.search.{k}": v
                         for k, v in stats.items()},
            "iterations": history,
            "corpus": [
                {k: e[k] for k in ("id", "iteration", "valid",
                                   "interesting", "novel")}
                for e in corpus.entries
            ],
            "cells": cells,
        })

    def spend(sched: Schedule, label: str) -> dict:
        outcome = runner(sched, label)
        stats["iterations"] += 1
        return outcome

    def primary_reason_reproduces(want: str):
        def check(cand: Schedule) -> bool:
            if not respects_floor(cand, n_nodes, min_nodes):
                return False
            out = spend(cand, f"shrink-{stats['iterations']:04d}")
            stats["shrink-attempts"] += 1
            return want in reasons(out)
        return check

    def record(sched: Schedule, outcome: dict, label: str) -> None:
        sig = signature(outcome)
        novel = coverage.add(sig)
        why = reasons(outcome)
        valid = (outcome.get("results") or {}).get("valid")
        if novel:
            stats["novel"] += 1
            corpus.add(sched, sig, novel, stats["iterations"], valid, why)
        history.append({
            "i": stats["iterations"],
            "label": label,
            "events": len(sched.events),
            "families": sorted(sched.families),
            "new_features": len(novel),
            "coverage": len(coverage),
            "interesting": why,
        })
        if why:
            stats["interesting"] += 1
            already = any(
                c["reason"] == why[0] and
                Schedule.from_json(c["schedule"]).families
                == sched.families
                for c in cells
            )
            # The budget bounds exploration, not minimization: a found
            # reproducer is the search's whole point, so shrink it even
            # at the budget edge (bounded overrun — `shrink_attempts`
            # runs at most).
            if not already:
                small, spent = shrink(
                    sched, primary_reason_reproduces(why[0]),
                    max_attempts=shrink_attempts,
                )
                stats["shrunk"] += 1
                cell = {
                    "name": f"searched-{why[0]}-{len(cells)}",
                    "reason": why[0],
                    "schedule": small.to_json(),
                    "events": len(small.events),
                    "shrink_runs": spent,
                    "from_events": len(sched.events),
                }
                cells.append(cell)
                _write_json_atomic(
                    os.path.join(cells_dir, cell["name"] + ".json"), cell
                )
                log.info("shrunk %s reproducer to %d event(s) "
                         "(%d shrink runs)", why[0], len(small.events),
                         spent)
        _count_preserving(stats)
        checkpoint()

    # Seed round: one deterministic single-event schedule per family —
    # each contributes family-distinct ledger/verdict features, so
    # coverage strictly grows across the round.
    for i, fam in enumerate(families):
        if time.monotonic() >= deadline:
            break
        if max_iterations and stats["iterations"] >= max_iterations:
            break
        sched = seed_schedule(fam, seed=seed + i + 1)
        outcome = spend(sched, f"seed-{fam}")
        record(sched, outcome, f"seed-{fam}")

    # Evolution: mutate/crossover corpus parents until the budget runs
    # out.  With an empty corpus (everything crashed?) fall back to
    # fresh random schedules.
    while time.monotonic() < deadline:
        if max_iterations and stats["iterations"] >= max_iterations:
            break
        parents = corpus.schedules()
        if parents and len(parents) >= 2 and rng.random() < 0.3:
            sched = crossover(
                rng.choice(parents), rng.choice(parents),
                n_nodes, min_nodes, rng,
            )
        elif parents:
            sched = mutate(
                rng.choice(parents), families, n_nodes, min_nodes, rng,
            )
        else:
            sched = enforce_floor(
                Schedule(seed=rng.randrange(1 << 32), events=(
                    _fresh_event(families, n_nodes, rng),
                    _fresh_event(families, n_nodes, rng),
                )), n_nodes, min_nodes, rng,
            )
        if not sched.events:
            continue
        label = f"iter-{stats['iterations']:04d}"
        outcome = spend(sched, label)
        record(sched, outcome, label)

    _count_preserving(stats)
    checkpoint()
    return {
        "search_dir": search_dir,
        "coverage": len(coverage),
        "stats": stats,
        "corpus": len(corpus.entries),
        "cells": cells,
        "history": history,
    }


def replay(entry_or_schedule: Any, runner: Callable[[Schedule, str], dict],
           label: str = "replay") -> dict:
    """Re-runs a corpus entry (or Schedule) and returns its outcome.
    Determinism contract: the materialized op timeline is identical to
    the original run's (same genome -> same ops); observed counters may
    bucket differently under real thread timing, but verdict validity
    and interestingness class are expected to match."""
    sched = (
        entry_or_schedule
        if isinstance(entry_or_schedule, Schedule)
        else Schedule.from_json(entry_or_schedule["schedule"])
    )
    out = runner(sched, label)
    telemetry.count("nemesis.search.replays")
    return out


def load_state(search_dir: str) -> Optional[dict]:
    """The last checkpoint a search wrote, or None."""
    path = os.path.join(search_dir, STATE_FILE)
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def floor_from_test(test: dict) -> int:
    """The min-nodes floor the search must honor, from the test map's
    --node-loss-policy.  Under "tolerate[:<min>]" the floor is that
    minimum; under "abort" (the node-loss-averse default) the search
    stays maximally conservative and never takes more than one node
    down at a time."""
    policy, min_nodes = health.node_loss_policy(test)
    if policy == "abort":
        return max(1, len(test.get("nodes") or []) - 1)
    return min_nodes
