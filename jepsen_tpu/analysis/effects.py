"""Interprocedural effect summaries over the resolved intra-repo call
graph — the shared substrate under jepsenlint's rule families.

Per-function ``FnSummary`` records what a function *does*, in program
order: locks acquired (with the held-lock stack at each acquisition),
journal appends (``X.append(BLOCK_*, ...)``), ``.flush()`` calls,
fsyncs (``os.fsync`` / ``.sync()``), frame/socket sends
(``write_frame`` / ``.sendall``), every ``self.<attr>`` read/write with
the locks held at the site, swallowed exceptions, and the outgoing
calls themselves.  ``Program`` resolves those calls against the repo
(``self.m`` → the enclosing class's method, bare names → same module or
the imported definition, ``alias.f`` → the imported module, with a
unique-method-name fallback for dynamic dispatch), then offers two
interprocedural views:

  * ``trans_acquires`` / ``trans_kinds`` — flow-insensitive transitive
    effect sets, computed as a bounded fixpoint that is safe under
    recursion and call-graph cycles (a cycle simply reaches its own
    fixpoint; no unrolling).
  * ``trace(key)`` — a flow-*sensitive* inlined event list: callee
    events are spliced into the caller's event order at the call site
    (bounded depth, cycles cut), which is what lets durability rules
    ask "is there an fsync *between* this append and that reply?"
    across function boundaries.

The lock-identity machinery (``LockScope``) and import-alias resolution
(``import_map``) moved here from rules/concurrency.py so every family
shares one notion of what a lock is and where a name points; the
concurrency module re-exports them for its older callers.

Everything is pure ``ast`` — no imports of analyzed code — and the
whole-repo build stays well inside the analyzer's 10 s budget.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .core import Module

Key = tuple[str, str]  # (dotted module name, "Class.method" symbol)

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_REENTRANT_CTORS = {"RLock", "Condition"}

#: Method names too generic for the unique-name dynamic-dispatch
#: fallback — resolving `x.append(...)` to *some* repo method named
#: `append` by uniqueness alone would be wrong far more often than
#: right.
_AMBIENT_METHODS = {
    "append", "flush", "close", "sync", "write", "read", "get", "put",
    "pop", "add", "send", "recv", "run", "start", "stop", "join",
    "acquire", "release", "update", "clear", "items", "keys", "values",
}

#: ``# guarded-by: self._lock`` — the annotation the concurrency
#: family's checked contract is declared with; parsed here because the
#: effect walk already visits every attribute assignment line.
GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")


def _lockish_text(seg: str) -> bool:
    low = seg.lower()
    return ("lock" in low or "cond" in low or "sem" in low) and \
        "clock" not in low


class LockScope:
    """Lock creations and usages for one module (lock identity is
    scoped to where the lock object lives: ``module.NAME``,
    ``module.Class.attr``, ``module.func.NAME``)."""

    def __init__(self, m: Module):
        self.m = m
        # (scope-symbol or "", name) -> reentrant?
        self.created: dict[tuple[str, str], bool] = {}
        self._collect()

    def _collect(self) -> None:
        for node in ast.walk(self.m.tree):
            if not isinstance(node, ast.Assign):
                continue
            ctor = self._ctor_of(node.value)
            if ctor is None:
                continue
            reentrant = ctor in _REENTRANT_CTORS
            fn = self.m.enclosing_function(node)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    scope = self.m.symbol(node) if fn is not None else ""
                    self.created[(scope, tgt.id)] = reentrant
                elif (isinstance(tgt, ast.Attribute)
                      and isinstance(tgt.value, ast.Name)
                      and tgt.value.id == "self"):
                    cls = self.m.enclosing_class(node)
                    if cls is not None:
                        self.created[(cls.name, tgt.attr)] = reentrant

    def _ctor_of(self, value: ast.AST) -> Optional[str]:
        # `threading.Lock()`, `Lock()`, and the `x or threading.Lock()`
        # defaulting idiom all count as creations.
        if isinstance(value, ast.BoolOp):
            for v in value.values:
                c = self._ctor_of(v)
                if c:
                    return c
            return None
        if not isinstance(value, ast.Call):
            return None
        f = value.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        return name if name in _LOCK_CTORS else None

    def resolve(self, node: ast.AST,
                expr: ast.AST) -> Optional[tuple[str, bool]]:
        """(lock-id, reentrant) for a with-item / acquire target, or
        None when the expression isn't a lock."""
        # Unwrap `self._lock.read()` / `.write()` style wrappers.
        if isinstance(expr, ast.Call):
            expr = expr.func
            if isinstance(expr, ast.Attribute):
                expr = expr.value
        m = self.m
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            cls = m.enclosing_class(node)
            cname = cls.name if cls is not None else "?"
            key = (cname, expr.attr)
            if key in self.created:
                return (f"{m.name}.{cname}.{expr.attr}",
                        self.created[key])
            if _lockish_text(expr.attr):
                return (f"{m.name}.{cname}.{expr.attr}", False)
            return None
        if isinstance(expr, ast.Name):
            # Innermost creating scope wins: function-local locks are
            # distinct per function, closures see their definer.
            fn = m.enclosing_function(node)
            while fn is not None:
                key = (m.symbol(fn), expr.id)
                if key in self.created:
                    return (f"{m.name}.{key[0]}.{expr.id}",
                            self.created[key])
                fn = m.enclosing_function(fn)
            if ("", expr.id) in self.created:
                return (f"{m.name}.{expr.id}",
                        self.created[("", expr.id)])
            if _lockish_text(expr.id):
                sym = m.symbol(node)
                scoped = sym if sym != "<module>" else ""
                return (f"{m.name}{'.' + scoped if scoped else ''}"
                        f".{expr.id}", False)
            return None
        seg = m.seg(expr)
        if _lockish_text(seg.split("(")[0].split("[")[0]):
            return (f"{m.name}.{seg.split('(')[0]}", False)
        return None


def import_map(m: Module) -> dict[str, str]:
    """alias -> dotted target ("telemetry" -> "jepsen_tpu.telemetry",
    "_count" -> "jepsen_tpu.telemetry.count", ...).  Cached on the
    Module instance — the tree walk is paid once even though device
    and the Program both ask."""
    cached = getattr(m, "_jl_imports", None)
    if cached is not None:
        return cached
    out: dict[str, str] = {}
    pkg_parts = m.name.split(".")
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[: len(pkg_parts) - node.level]
            else:
                base = []
            mod = ".".join(base + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = (
                    f"{mod}.{a.name}" if mod else a.name
                )
    m._jl_imports = out  # type: ignore[attr-defined]
    return out


@dataclass
class Event:
    """One ordered effect inside a function body.

    kind ∈ {"acquire", "append", "flush", "fsync", "send", "call"};
    detail is the lock id (acquire), receiver/callee text (append,
    call), or the marker matched (flush/fsync/send); held is the
    with-statement lock stack at the site.
    """
    kind: str
    detail: str
    line: int
    held: tuple[str, ...] = ()


@dataclass
class AttrSite:
    """One ``self.<attr>`` access in a method body."""
    attr: str
    kind: str                    # "read" | "write"
    line: int
    held: tuple[str, ...] = ()


@dataclass
class FnSummary:
    key: Key
    module: Module
    node: ast.AST
    cls: Optional[str] = None            # enclosing class name, if any
    events: list[Event] = field(default_factory=list)
    acquires: set[str] = field(default_factory=set)   # direct lock ids
    attr_sites: list[AttrSite] = field(default_factory=list)
    swallows: list[int] = field(default_factory=list)  # bare-pass lines
    # local name -> the call text it was assigned from (`hw =
    # self._ensure_history_writer()` → "self._ensure_history_writer"),
    # the raw material for typed-local dispatch.
    local_calls: dict[str, str] = field(default_factory=dict)
    # local name -> annotated class name (`hw: HistoryWriter = ...`)
    local_anns: dict[str, str] = field(default_factory=dict)
    returns_cls: Optional[str] = None    # return-annotation class name

    @property
    def calls(self) -> list[Event]:
        return [e for e in self.events if e.kind == "call"]


_EFFECT_KINDS = ("append", "flush", "fsync", "send")

#: Annotation names that are containers/builtins, not the class we're
#: after when unwrapping `Optional["HistoryWriter"]` and friends.
_ANN_NOISE = {
    "Optional", "Union", "Any", "None", "list", "dict", "tuple", "set",
    "List", "Dict", "Tuple", "Set", "Iterable", "Iterator", "Callable",
    "str", "int", "float", "bool", "bytes", "object",
}


def _ann_name(ann: Optional[ast.AST]) -> Optional[str]:
    """The class name inside a (possibly Optional/quoted) annotation,
    or None when it's a builtin/container/absent."""
    if ann is None:
        return None
    for sub in ast.walk(ann):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            name = sub.value.rsplit(".", 1)[-1].strip("'\" []")
        if name and name not in _ANN_NOISE:
            return name
    return None


def _is_block_const(expr: ast.AST) -> bool:
    name = None
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    return bool(name) and name.startswith("BLOCK_")


class Program:
    """The whole scan set, summarized: per-function effect summaries,
    a resolved call graph, lock scopes, and the interprocedural
    fixpoints the rule families query."""

    def __init__(self, modules: Iterable[Module]):
        self.modules = list(modules)
        self.by_name: dict[str, Module] = {m.name: m for m in self.modules}
        self.scopes: dict[str, LockScope] = {}
        self.imports: dict[str, dict[str, str]] = {}
        self.fns: dict[Key, FnSummary] = {}
        # (module, class) -> {method name -> Key}
        self.classes: dict[tuple[str, str], dict[str, Key]] = {}
        # method name -> [Key] (dynamic-dispatch fallback index)
        self.by_method: dict[str, list[Key]] = {}
        self.reentrant: set[str] = set()
        # (cls-scoped) guarded-by annotations: (module, class) ->
        # {attr: lock-id}
        self.guards: dict[tuple[str, str], dict[str, str]] = {}
        for m in self.modules:
            self._index_module(m)
        self._resolved: dict[tuple[str, str, Optional[str]],
                             Optional[Key]] = {}
        self._edges: Optional[dict[Key, list[Key]]] = None
        self._rev: Optional[dict[Key, list[tuple[Key, Event]]]] = None
        self._trans_acquires: Optional[dict[Key, set[str]]] = None
        self._trans_kinds: Optional[dict[Key, set[str]]] = None
        self._traces: dict[tuple[Key, int], list[Event]] = {}

    # -- construction -----------------------------------------------------

    def _index_module(self, m: Module) -> None:
        scope = LockScope(m)
        self.scopes[m.name] = scope
        self.imports[m.name] = import_map(m)
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sym = m.symbol(node)
                key: Key = (m.name, sym)
                cls = m.enclosing_class(node)
                fi = FnSummary(key=key, module=m, node=node,
                               cls=cls.name if cls else None)
                fi.returns_cls = _ann_name(node.returns)
                self.fns[key] = fi
                self._walk_function(m, scope, node, fi)
                if cls is not None and sym == f"{cls.name}.{node.name}":
                    self.classes.setdefault(
                        (m.name, cls.name), {})[node.name] = key
                self.by_method.setdefault(node.name, []).append(key)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._maybe_guard(m, node)

    def _maybe_guard(self, m: Module, node: ast.AST) -> None:
        """`self.x = ...  # guarded-by: self._lock` declares the
        contract checked by concurrency.guarded-by."""
        cls = m.enclosing_class(node)
        if cls is None:
            return
        try:
            line = m.lines[node.lineno - 1]
        except IndexError:
            return
        gm = GUARDED_BY_RE.search(line)
        if not gm:
            return
        lock = gm.group(1)
        lock_attr = lock[5:] if lock.startswith("self.") else lock
        if lock.startswith("self."):
            lock_id = f"{m.name}.{cls.name}.{lock_attr}"
        else:
            lock_id = f"{m.name}.{lock_attr}"
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                self.guards.setdefault(
                    (m.name, cls.name), {})[tgt.attr] = lock_id

    def _walk_function(self, m: Module, scope: LockScope,
                       fn: ast.AST, fi: FnSummary) -> None:
        """Single in-order pass tracking the held-lock stack.  Nested
        function bodies are skipped — they get their own summaries and
        run later, not under the caller's locks."""

        def attr_site(node: ast.Attribute, kind: str,
                      held: tuple[str, ...]) -> None:
            if (isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                fi.attr_sites.append(AttrSite(
                    attr=node.attr, kind=kind, line=node.lineno,
                    held=held))

        def visit(node: ast.AST, held: tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return
            if isinstance(node, ast.With):
                acquired: list[str] = []
                for item in node.items:
                    r = scope.resolve(node, item.context_expr)
                    if r is not None:
                        lock, re_ok = r
                        if re_ok:
                            self.reentrant.add(lock)
                        fi.acquires.add(lock)
                        fi.events.append(Event(
                            "acquire", lock, node.lineno, held))
                        acquired.append(lock)
                    # Effects inside the context expression itself
                    # (e.g. open(...) calls) still happen.
                    visit(item.context_expr, held)
                inner = held + tuple(acquired)
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.ExceptHandler):
                body = [s for s in node.body
                        if not isinstance(s, ast.Expr)
                        or not isinstance(s.value, ast.Constant)]
                if all(isinstance(s, (ast.Pass, ast.Continue))
                       for s in body):
                    fi.swallows.append(node.lineno)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Attribute) and isinstance(
                                sub.ctx, ast.Store):
                            attr_site(sub, "write", held)
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)):
                    fi.local_calls[node.targets[0].id] = m.seg(
                        node.value.func)
            elif isinstance(node, ast.AnnAssign):
                if isinstance(node.target, ast.Name):
                    ann = _ann_name(node.annotation)
                    if ann:
                        fi.local_anns[node.target.id] = ann
                elif isinstance(node.target, ast.Attribute):
                    attr_site(node.target, "write", held)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Attribute):
                    attr_site(node.target, "write", held)
                    # aug-assign reads the old value too
                    attr_site(node.target, "read", held)
            elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load):
                attr_site(node, "read", held)
            elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Del):
                attr_site(node, "write", held)
            if isinstance(node, ast.Call):
                self._classify_call(m, scope, node, fi, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        body = getattr(fn, "body", [])
        for stmt in body:
            visit(stmt, ())

    def _classify_call(self, m: Module, scope: LockScope,
                       node: ast.Call, fi: FnSummary,
                       held: tuple[str, ...]) -> None:
        func = node.func
        line = node.lineno
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr == "acquire":
                r = scope.resolve(node, func.value)
                if r is not None:
                    lock, re_ok = r
                    if re_ok:
                        self.reentrant.add(lock)
                    fi.acquires.add(lock)
                    fi.events.append(Event("acquire", lock, line, held))
                return
            if attr == "append" and node.args and _is_block_const(
                    node.args[0]):
                fi.events.append(Event(
                    "append", m.seg(func.value), line, held))
                return
            if attr == "flush" and not node.args:
                fi.events.append(Event(
                    "flush", m.seg(func.value), line, held))
                return
            if attr == "sync" and not node.args:
                fi.events.append(Event(
                    "fsync", m.seg(func.value), line, held))
                return
            if attr == "fsync":
                fi.events.append(Event("fsync", "os.fsync", line, held))
                return
            if attr == "sendall":
                fi.events.append(Event(
                    "send", m.seg(func.value), line, held))
                return
        name = func.id if isinstance(func, ast.Name) else None
        if name == "write_frame" or (
                isinstance(func, ast.Attribute)
                and func.attr == "write_frame"):
            fi.events.append(Event("send", "write_frame", line, held))
            return
        fi.events.append(Event("call", m.seg(func), line, held))

    # -- call resolution --------------------------------------------------

    def resolve(self, text: str, module: Module,
                cls: Optional[str] = None,
                caller: Optional[FnSummary] = None) -> Optional[Key]:
        """Best-effort callee resolution: ``self.m`` → the enclosing
        class's method (exact, not suffix-matched), bare name → same
        module or the imported definition, ``alias.f`` → the imported
        module's f.  A method call on a local receiver is resolved
        through the local's *type* when it is knowable — assigned from
        a constructor, an annotated local, or the return annotation of
        a resolved call (``hw = self._ensure_history_writer()`` →
        ``hw.checkpoint`` → ``HistoryWriter.checkpoint``).  Failing
        that, the unique repo-wide definition of the method name
        (dynamic dispatch fallback) when the name isn't ambient."""
        ck = (text, module.name, cls,
              caller.key if caller is not None else None)
        if ck in self._resolved:
            return self._resolved[ck]
        out = self._resolve_uncached(text, module, cls, caller)
        self._resolved[ck] = out
        return out

    def _resolve_uncached(self, text: str, module: Module,
                          cls: Optional[str],
                          caller: Optional[FnSummary]) -> Optional[Key]:
        text = text.strip()
        head = text.split("(")[0]
        imports = self.imports.get(module.name, {})
        if head.startswith("self."):
            parts0 = head[5:].split(".")
            meth = parts0[0]
            if len(parts0) > 1:
                # self.attr.meth(...): a call *through* an attribute —
                # the invoked method is the last segment, and the
                # attribute's type isn't tracked, so only the unique-
                # name fallback applies.  Resolving on the first
                # segment here would alias every `self._writer.close()`
                # in the repo onto some class's `_writer()` method.
                return self._dispatch_fallback(parts0[-1])
            if cls is not None:
                key = self.classes.get((module.name, cls), {}).get(meth)
                if key is not None:
                    return key
            # Older suffix-match behavior as a fallback when the
            # enclosing class isn't known.
            for (mod, sym), fi in self.fns.items():
                if mod == module.name and sym.endswith(f".{meth}"):
                    return (mod, sym)
            return self._dispatch_fallback(meth)
        if "." not in head:
            target = imports.get(head, head)
            if "." in target:           # from x import f
                mod, _, f = target.rpartition(".")
                hit = self.fns.get((mod, f))
                if hit is not None:
                    return hit.key
                return None
            fi = self.fns.get((module.name, head))
            return fi.key if fi is not None else None
        alias, _, rest = head.partition(".")
        base = imports.get(alias)
        if base is None:
            # Typed-local dispatch: the receiver is a local whose class
            # we can infer from how it was produced.
            meth = rest.split(".")[0]
            if caller is not None:
                cls_key = self._local_type(caller, alias)
                if cls_key is not None:
                    hit3 = self.classes.get(cls_key, {}).get(meth)
                    if hit3 is not None:
                        return hit3
            return self._dispatch_fallback(head.rsplit(".", 1)[-1])
        parts = rest.split(".")
        # alias may be a module (alias.f) or a package (alias.sub.f),
        # or a class imported from a module (alias.method on an
        # instance is not resolvable here).
        for split in range(len(parts), 0, -1):
            mod = ".".join([base] + parts[: split - 1])
            f = parts[split - 1]
            hit = self.fns.get((mod, f))
            if hit is not None:
                return hit.key
            # from pkg import cls; cls.method / Cls(...).method
            hit2 = self.fns.get((mod.rpartition(".")[0],
                                 f"{mod.rpartition('.')[2]}.{f}"))
            if hit2 is not None:
                return hit2.key
        return self._dispatch_fallback(parts[-1])

    def _dispatch_fallback(self, meth: str) -> Optional[Key]:
        if meth in _AMBIENT_METHODS:
            return None
        keys = self.by_method.get(meth) or []
        return keys[0] if len(keys) == 1 else None

    def _resolve_class(self, name: str,
                       module: Module) -> Optional[tuple[str, str]]:
        """(module, class) for a bare class name: same module first,
        then through the import map, then unique repo-wide."""
        if (module.name, name) in self.classes:
            return (module.name, name)
        target = self.imports.get(module.name, {}).get(name)
        if target and "." in target:
            mod, _, cname = target.rpartition(".")
            if (mod, cname) in self.classes:
                return (mod, cname)
        hits = [ck for ck in self.classes if ck[1] == name]
        return hits[0] if len(hits) == 1 else None

    def _local_type(self, caller: FnSummary,
                    local: str) -> Optional[tuple[str, str]]:
        """The class of a local variable, when knowable: an annotated
        local, a constructor assignment, or the return annotation of a
        resolved call."""
        ann = caller.local_anns.get(local)
        if ann is not None:
            return self._resolve_class(ann, caller.module)
        src = caller.local_calls.get(local)
        if src is None:
            return None
        head = src.strip().split("(")[0]
        ctor = head.rsplit(".", 1)[-1]
        if ctor and ctor[0].isupper():
            ck = self._resolve_class(ctor, caller.module)
            if ck is not None:
                return ck
        # Not a constructor: resolve the producing call (WITHOUT local
        # context — one level of indirection is where this stops) and
        # use its return annotation.
        prod = self.resolve(src, caller.module, caller.cls)
        if prod is None:
            return None
        pfi = self.fns.get(prod)
        if pfi is None or pfi.returns_cls is None:
            return None
        return self._resolve_class(pfi.returns_cls, pfi.module)

    # -- interprocedural views --------------------------------------------

    def edges(self) -> dict[Key, list[Key]]:
        """Resolved call graph: caller key -> callee keys."""
        if self._edges is None:
            self._edges = {}
            for key, fi in self.fns.items():
                outs = []
                for ev in fi.calls:
                    callee = self.resolve(ev.detail, fi.module,
                                          fi.cls, fi)
                    if callee is not None:
                        outs.append(callee)
                self._edges[key] = outs
        return self._edges

    def callers(self) -> dict[Key, list[tuple[Key, Event]]]:
        """Reverse call graph: callee key -> [(caller key, call
        event)] — the event carries line and held locks at the site."""
        if self._rev is None:
            self._rev = {}
            for key, fi in self.fns.items():
                for ev in fi.calls:
                    callee = self.resolve(ev.detail, fi.module,
                                          fi.cls, fi)
                    if callee is not None:
                        self._rev.setdefault(callee, []).append(
                            (key, ev))
        return self._rev

    def _fixpoint(self) -> None:
        acq = {k: set(fi.acquires) for k, fi in self.fns.items()}
        kinds = {
            k: {e.kind for e in fi.events if e.kind in _EFFECT_KINDS}
            for k, fi in self.fns.items()
        }
        edges = self.edges()
        # Bounded fixpoint: sets only grow, so this terminates; the
        # bound just caps pathological graphs.  Recursive and mutually
        # recursive functions are handled by the fixpoint itself.
        for _ in range(12):
            changed = False
            for key in self.fns:
                for callee in edges.get(key, ()):
                    a = acq[callee] - acq[key]
                    if a:
                        acq[key].update(a)
                        changed = True
                    kd = kinds[callee] - kinds[key]
                    if kd:
                        kinds[key].update(kd)
                        changed = True
            if not changed:
                break
        self._trans_acquires = acq
        self._trans_kinds = kinds

    def trans_acquires(self, key: Key) -> set[str]:
        """Every lock id acquired by `key` or anything it (transitively)
        calls."""
        if self._trans_acquires is None:
            self._fixpoint()
        return self._trans_acquires.get(key, set())  # type: ignore

    def trans_kinds(self, key: Key) -> set[str]:
        """Transitive effect kinds ({"append","flush","fsync","send"})
        reachable from `key`."""
        if self._trans_kinds is None:
            self._fixpoint()
        return self._trans_kinds.get(key, set())  # type: ignore

    def trace(self, key: Key, depth: int = 3) -> list[Event]:
        """Flow-sensitive inlined event list for `key`: each resolved
        call event is replaced by the callee's trace (down to `depth`
        levels; cycles and over-deep chains keep the bare call event
        with the callee's unordered transitive kinds appended, so an
        fsync buried deep still registers — just without ordering)."""
        memo_key = (key, depth)
        if memo_key in self._traces:
            return self._traces[memo_key]
        out = self._trace(key, depth, frozenset())
        self._traces[memo_key] = out
        return out

    def _trace(self, key: Key, depth: int,
               active: frozenset) -> list[Event]:
        fi = self.fns.get(key)
        if fi is None:
            return []
        out: list[Event] = []
        for ev in fi.events:
            if ev.kind != "call":
                out.append(ev)
                continue
            callee = self.resolve(ev.detail, fi.module, fi.cls, fi)
            if callee is None or callee == key:
                out.append(ev)
                continue
            if depth <= 0 or callee in active:
                # Cut — keep ordering-free knowledge of what's below.
                out.append(ev)
                for kind in sorted(self.trans_kinds(callee)):
                    out.append(Event(kind, f"<via {ev.detail}>",
                                     ev.line, ev.held))
                continue
            out.append(ev)
            out.extend(self._trace(callee, depth - 1,
                                   active | {key}))
        return out


#: One-slot build cache: concurrency and durability run over the same
#: module batch in one analyze pass — summarize it once.
_cache: Optional[tuple[tuple[int, ...], Program]] = None


def build(modules: Iterable[Module]) -> Program:
    """The one-call entry: summarize a scan set (cached for the batch
    so multiple rule families share one Program)."""
    global _cache
    mods = list(modules)
    key = tuple(id(m) for m in mods)
    if _cache is not None and _cache[0] == key:
        return _cache[1]
    prog = Program(mods)
    _cache = (key, prog)
    return prog
