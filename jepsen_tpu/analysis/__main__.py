"""``python -m jepsen_tpu.analysis`` — the standalone lint entry.
Same driver as ``jepsen lint`` and ``tools/lint.py``."""

import argparse
import sys

from .core import add_lint_args, main

if __name__ == "__main__":
    p = argparse.ArgumentParser(
        prog="jepsenlint",
        description="AST-based invariant analysis for this repo "
        "(device hygiene, lock discipline, framework protocols)",
    )
    add_lint_args(p)
    sys.exit(main(p.parse_args()))
