"""jepsenlint: AST-based invariant analysis for this repo's protocols.

Nine PRs of checker infrastructure rest on conventions no tool
enforced: every nemesis journals ledger intent before injecting (PR 4),
every checker routes through ``check_safe`` budgets (PR 2), every WGL
pass runs under ``profile.capture`` (PR 9), every narrowing
``.astype(np.int32)`` needs a dominating range guard (the
``wgl_witness._plan_blocks`` bug class), and ~30 modules spawn threads
whose lock discipline nobody checks.  This package makes those
properties *declared and machine-checkable* — the TVM lesson
(PAPERS.md): passes compose safely because their invariants are
checked, not remembered.

Since v2 the analysis is **interprocedural**: ``effects.py`` builds
per-function effect summaries (locks acquired, journal appends,
flush/fsync, frame sends, ``self`` attribute reads/writes with the
held-lock stack, swallowed exceptions) over a resolved intra-repo call
graph (self-methods, imports, class-qualified calls, typed locals via
constructor/annotation, and a unique-method dynamic-dispatch
fallback), with a bounded fixpoint for transitive effects — so an
append and its fsync, or a lock and the helper it protects, may live
in different functions or modules and still be matched up.

Four rule families (``rules/``):

  * ``device``      — JAX/device hygiene: unguarded narrowing casts,
    host syncs inside jit-traced code, ``np``/``jnp`` mixing in traced
    functions, device passes outside ``profile.capture``;
  * ``concurrency`` — a cross-module lock-order graph from the effect
    summaries (cycles are errors), checked ``# guarded-by:`` contracts
    (annotated at the attribute's birth or inferred for
    thread-spawning classes, verified through the call graph), plus
    the unsynced-thread-attr advice fallback;
  * ``durability``  — the crash-durability protocol at every journal
    site: appends that no function or caller path ever fsyncs,
    replies/sends reachable before the append's fsync, ``_read_block``
    results never None-checked (None IS the torn tail), read-back
    ``.json`` state written without tmp+``os.replace``, and
    ``BLOCK_*``/``F_*`` wire-id collisions;
  * ``protocol``    — framework contracts: ledger intent before
    session mutation, compensator ctypes that exist in the ledger
    registry, telemetry counter names inside the declared namespaces
    (cross-checked against ``FLEET_COUNTER_PREFIXES``), no
    ``check_safe`` bypasses, no swallowed exceptions in teardown.

Infrastructure (``core.py``): a ``Finding`` model with severity,
``# jepsenlint: ignore[rule] -- reason`` suppressions (a reason is
mandatory, and a pragma matching nothing is itself an error), a
committed ``lint_baseline.json`` of accepted findings with written
justifications, JSON + human + SARIF 2.1.0 output (``--sarif``), and
a <10 s full-repo runtime contract.  Run it as ``jepsen lint``, via
``tools/lint.py``, or ``python -m jepsen_tpu.analysis``.
"""

from .core import (  # noqa: F401
    Finding,
    LintReport,
    Module,
    baseline_path,
    lint_source,
    load_baseline,
    main,
    render_human,
    render_json,
    run_lint,
    save_baseline,
)
