"""jepsenlint: AST-based invariant analysis for this repo's protocols.

Nine PRs of checker infrastructure rest on conventions no tool
enforced: every nemesis journals ledger intent before injecting (PR 4),
every checker routes through ``check_safe`` budgets (PR 2), every WGL
pass runs under ``profile.capture`` (PR 9), every narrowing
``.astype(np.int32)`` needs a dominating range guard (the
``wgl_witness._plan_blocks`` bug class), and ~30 modules spawn threads
whose lock discipline nobody checks.  This package makes those
properties *declared and machine-checkable* — the TVM lesson
(PAPERS.md): passes compose safely because their invariants are
checked, not remembered.

Three rule families (``rules/``):

  * ``device``      — JAX/device hygiene: unguarded narrowing casts,
    host syncs inside jit-traced code, ``np``/``jnp`` mixing in traced
    functions, device passes outside ``profile.capture``;
  * ``concurrency`` — a module-level lock-order graph built from
    ``with lock:`` / ``acquire()`` nesting (cycles are errors), plus
    attributes written from a thread entry point and read elsewhere
    with no common lock;
  * ``protocol``    — framework contracts: ledger intent before
    session mutation, compensator ctypes that exist in the ledger
    registry, telemetry counter names inside the declared namespaces
    (cross-checked against ``FLEET_COUNTER_PREFIXES``), no
    ``check_safe`` bypasses, no swallowed exceptions in teardown.

Infrastructure (``core.py``): a ``Finding`` model with severity,
``# jepsenlint: ignore[rule] -- reason`` suppressions (a reason is
mandatory), a committed ``lint_baseline.json`` of accepted findings
with written justifications, JSON + human output, and a <30 s
full-repo runtime contract.  Run it as ``jepsen lint``, via
``tools/lint.py``, or ``python -m jepsen_tpu.analysis``.
"""

from .core import (  # noqa: F401
    Finding,
    LintReport,
    Module,
    baseline_path,
    lint_source,
    load_baseline,
    main,
    render_human,
    render_json,
    run_lint,
    save_baseline,
)
