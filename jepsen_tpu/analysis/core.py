"""jepsenlint core: module loading, findings, suppressions, baseline.

The analyzer is a pure-``ast`` pass over the repo's own sources — no
imports of the analyzed code, no third-party dependencies — so it runs
identically in CI, under pytest, and on a laptop with no JAX installed.
The whole-repo contract is <10 s (including the interprocedural
effect-summary build in effects.py); in practice a full parse+analyze
of ~200 files is well under 3 s.

Three moving parts:

  * **Finding** — one violation: rule id, severity, location, the
    enclosing symbol, and a *fingerprint* that is stable under line
    motion (hash of rule/path/symbol/message plus an occurrence index,
    never the line number), so baselines survive unrelated edits.
  * **Suppressions** — ``# jepsenlint: ignore[rule] -- reason`` on the
    flagged line or the line above.  The reason is mandatory: a bare
    ignore is itself an ``error`` finding, so every silenced rule has a
    written why next to the code it silences.
  * **Baseline** — ``lint_baseline.json`` at the repo root: accepted
    findings with justifications.  ``jepsen lint`` exits nonzero on any
    finding that is neither suppressed nor baselined; stale baseline
    entries (fixed code) are reported but never fail the gate, so
    fixing debt cannot break CI.
"""

from __future__ import annotations

import ast
import gc
import hashlib
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

#: Severity order, most severe first.  Every unbaselined, unsuppressed
#: finding fails the gate regardless of severity; the tiers order the
#: report and feed the jepsen_lint_findings{severity=...} gauges.
SEVERITIES = ("error", "warning", "advice")

#: Whole-repo runtime contract (seconds); run_lint records its own
#: duration and test_analysis asserts against this.  Tightened from
#: 30 s when the interprocedural analyzer landed: the tier-1 gate cost
#: must stay negligible even with effect summaries in the loop.
RUNTIME_BUDGET_S = 10.0

BASELINE_FILE = "lint_baseline.json"

#: Suppression pragma — the whole comment, nothing before it:
#: ``jepsenlint: ignore[rule, family] -- reason`` (``:`` also accepted
#: before the reason).  Applies to its own line and the line below, so
#: it can sit above a long expression.  Anchored at the comment start
#: so prose *about* the pragma syntax (like this very comment) never
#: parses as a suppression.
_PRAGMA_RE = re.compile(
    r"^#+:?\s*jepsenlint:\s*ignore\[([^\]]*)\]\s*"
    r"(?:(?:--|:)\s*(\S.*))?\s*$"
)

#: Directories never scanned (generated, vendored, or test fixtures
#: that violate rules on purpose).  Note jepsen_tpu/store/ — the
#: framed-file format module — IS scanned: the durability family's
#: block-id collision rule needs its BLOCK_* constants.  The repo-root
#: store/ data directory never enters the walk (the default roots are
#: jepsen_tpu/, tools/, bench.py) and holds no .py files anyway.
_SKIP_DIRS = {"__pycache__", ".git", "tests"}


@dataclass(frozen=True)
class Finding:
    rule: str          # "family.rule-name"
    severity: str      # one of SEVERITIES
    path: str          # repo-relative, "/" separated
    line: int
    symbol: str        # enclosing "Class.method" / "func" / "<module>"
    message: str
    fingerprint: str = ""

    @property
    def family(self) -> str:
        return self.rule.split(".", 1)[0]

    def sort_key(self) -> tuple:
        return (SEVERITIES.index(self.severity), self.path, self.line,
                self.rule)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


def _fingerprint(rule: str, path: str, symbol: str, message: str,
                 occurrence: int) -> str:
    """Line-independent identity: identical findings in the same symbol
    are disambiguated by their ordinal, not their line number, so a
    baseline survives code moving around above it."""
    raw = f"{rule}|{path}|{symbol}|{message}|{occurrence}"
    return hashlib.sha256(raw.encode()).hexdigest()[:12]


def assign_fingerprints(findings: list[Finding]) -> list[Finding]:
    """Returns findings with fingerprints filled in, ordered by
    severity/path/line.  Occurrence indices are assigned in line order
    within each (rule, path, symbol, message) group."""
    groups: dict[tuple, list[Finding]] = {}
    for f in findings:
        groups.setdefault((f.rule, f.path, f.symbol, f.message),
                          []).append(f)
    out = []
    for key, fs in groups.items():
        for i, f in enumerate(sorted(fs, key=lambda f: f.line)):
            out.append(Finding(
                rule=f.rule, severity=f.severity, path=f.path,
                line=f.line, symbol=f.symbol, message=f.message,
                fingerprint=_fingerprint(*key, i),
            ))
    out.sort(key=Finding.sort_key)
    return out


# ---------------------------------------------------------------------------
# Modules
# ---------------------------------------------------------------------------


class Module:
    """One parsed source file plus the lookups every rule needs: parent
    links, enclosing-symbol resolution, and source segments."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        # Dotted import name ("jepsen_tpu.ops.wgl"), for lock ids and
        # cross-module call resolution.
        mod = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        self.name = mod.replace("/", ".")
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._jl_parent = parent  # type: ignore[attr-defined]

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_jl_parent", None)

    def parents(self, node: ast.AST) -> Iterable[ast.AST]:
        p = self.parent(node)
        while p is not None:
            yield p
            p = self.parent(p)

    def enclosing_function(
        self, node: ast.AST
    ) -> Optional[ast.FunctionDef]:
        for p in self.parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return p
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for p in self.parents(node):
            if isinstance(p, ast.ClassDef):
                return p
        return None

    def symbol(self, node: ast.AST) -> str:
        """"Class.method" / "func" / "<module>" for a node; nested
        functions join with ".", matching how humans name the spot."""
        names = []
        n: Optional[ast.AST] = node
        while n is not None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                names.append(n.name)
            n = self.parent(n)
        return ".".join(reversed(names)) or "<module>"

    def seg(self, node: ast.AST) -> str:
        """Source text of a node ("" when unavailable).  Sliced from
        the precomputed line list — ast.get_source_segment re-splits
        the whole file per call, which alone blew the 30 s whole-repo
        budget ~2x."""
        try:
            l0 = node.lineno - 1
            l1 = node.end_lineno - 1
            c0, c1 = node.col_offset, node.end_col_offset
        except AttributeError:
            return ""
        try:
            if l0 == l1:
                return self.lines[l0][c0:c1]
            parts = [self.lines[l0][c0:]]
            parts.extend(self.lines[l0 + 1: l1])
            parts.append(self.lines[l1][:c1])
            return "\n".join(parts)
        except IndexError:
            return ""

    def finding(self, rule: str, severity: str, node: ast.AST,
                message: str) -> Finding:
        return Finding(
            rule=rule, severity=severity, path=self.rel,
            line=getattr(node, "lineno", 1),
            symbol=self.symbol(node), message=message,
        )


def load_modules(
    root: str, paths: Optional[list[str]] = None
) -> list[Module]:
    """Parses the scan set: ``jepsen_tpu/``, ``tools/``, and
    ``bench.py`` under `root` (or an explicit file/dir list).  Files
    that fail to parse become a synthetic ``lint.syntax-error`` via
    run_lint; here they are skipped."""
    roots: list[str] = []
    if paths:
        roots = [p if os.path.isabs(p) else os.path.join(root, p)
                 for p in paths]
    else:
        for rel in ("jepsen_tpu", "tools", "bench.py"):
            p = os.path.join(root, rel)
            if os.path.exists(p):
                roots.append(p)
    files: list[str] = []
    for p in roots:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d not in _SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.join(dirpath, fn))
    out: list[Module] = []
    for path in files:
        rel = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            out.append(Module(path, rel, src))
        except (OSError, SyntaxError, ValueError):
            continue
    return out


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


@dataclass
class Suppression:
    line: int
    rules: tuple[str, ...]
    reason: Optional[str]
    used: bool = False


def _comment_lines(module: Module) -> dict[int, str]:
    """{line: comment text} from real COMMENT tokens — a pragma quoted
    inside a docstring or f-string (docs showing the syntax) must not
    parse as a suppression, or the unused-suppression rule flags the
    documentation."""
    import io
    import tokenize

    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(module.source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # Fall back to raw lines on tokenizer trouble; the module
        # parsed, so this is vanishingly rare.
        return dict(enumerate(module.lines, start=1))
    return out


def parse_suppressions(module: Module) -> list[Suppression]:
    out = []
    for i, text in sorted(_comment_lines(module).items()):
        m = _PRAGMA_RE.match(text.strip())
        if not m:
            continue
        rules = tuple(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        reason = (m.group(2) or "").strip() or None
        out.append(Suppression(line=i, rules=rules or ("*",),
                               reason=reason))
    return out


def _matches(supp: Suppression, finding: Finding) -> bool:
    if finding.line not in (supp.line, supp.line + 1):
        return False
    return any(r in ("*", finding.rule, finding.family)
               for r in supp.rules)


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def baseline_path(root: str) -> str:
    return os.path.join(root, BASELINE_FILE)


def load_baseline(path: str) -> dict[str, dict]:
    """{fingerprint: entry}.  A missing or unreadable file is an empty
    baseline — the gate then simply requires a clean tree."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    out = {}
    for e in data.get("findings", []):
        if isinstance(e, dict) and e.get("fingerprint"):
            out[e["fingerprint"]] = e
    return out


def save_baseline(path: str, findings: list[Finding],
                  old: Optional[dict[str, dict]] = None,
                  justification: Optional[str] = None) -> None:
    """Writes the baseline as the given findings; justifications of
    surviving fingerprints are carried over, new entries get
    `justification` (or a to-be-filled marker CI will tolerate but a
    reviewer should not)."""
    old = old or {}
    entries = []
    for f in sorted(findings, key=Finding.sort_key):
        prev = old.get(f.fingerprint) or {}
        entries.append({
            **f.to_dict(),
            "justification": prev.get("justification")
            or justification
            or "UNREVIEWED — justify or fix before merging",
        })
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(
            {"version": 1, "tool": "jepsenlint", "findings": entries},
            fh, indent=2, sort_keys=True,
        )
        fh.write("\n")


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


@dataclass
class LintReport:
    findings: list[Finding] = field(default_factory=list)       # gate set
    baselined: list[Finding] = field(default_factory=list)
    suppressed: list[tuple[Finding, str]] = field(default_factory=list)
    stale_baseline: list[dict] = field(default_factory=list)
    duration_s: float = 0.0
    files: int = 0

    @property
    def all_findings(self) -> list[Finding]:
        return sorted(self.findings + self.baselined,
                      key=Finding.sort_key)

    def counts(self, which: Optional[list[Finding]] = None) -> dict:
        out = {s: 0 for s in SEVERITIES}
        for f in (self.all_findings if which is None else which):
            out[f.severity] = out.get(f.severity, 0) + 1
        return out

    def family_counts(
        self, which: Optional[list[Finding]] = None
    ) -> dict:
        """{family: {severity: count}} — the shape behind the
        jepsen_lint_findings{family,severity} gauges."""
        out: dict[str, dict] = {}
        for f in (self.all_findings if which is None else which):
            fam = out.setdefault(f.family, {s: 0 for s in SEVERITIES})
            fam[f.severity] = fam.get(f.severity, 0) + 1
        return out

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "clean": self.clean,
            "files": self.files,
            "duration_s": round(self.duration_s, 3),
            "counts": self.counts(),
            "unbaselined": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
            "suppressed": [
                {**f.to_dict(), "reason": reason}
                for f, reason in self.suppressed
            ],
            "stale_baseline": self.stale_baseline,
        }


def _families() -> dict[str, Callable]:
    from .rules import FAMILIES

    return FAMILIES


def analyze_modules(
    modules: list[Module],
    families: Optional[Iterable[str]] = None,
) -> list[Finding]:
    """Raw findings (fingerprinted, suppressions NOT yet applied) from
    running the selected rule families over parsed modules."""
    fams = _families()
    names = list(families) if families else list(fams)
    found: list[Finding] = []
    for name in names:
        found.extend(fams[name](modules))
    return assign_fingerprints(found)


def run_lint(
    root: str,
    *,
    paths: Optional[list[str]] = None,
    baseline: Optional[str] = None,
    families: Optional[Iterable[str]] = None,
) -> LintReport:
    t0 = time.perf_counter()
    # The batch allocates millions of AST/summary objects and frees
    # almost nothing until it returns — generational gc passes over
    # that live heap are pure overhead (~20% of the runtime budget).
    gc_was_on = gc.isenabled()
    gc.disable()
    try:
        modules = load_modules(root, paths)
        raw = analyze_modules(modules, families)
    finally:
        if gc_was_on:
            gc.enable()

    # Suppressions: a matching pragma with a reason silences the
    # finding; a matching pragma WITHOUT a reason converts it into a
    # suppression-missing-reason error on the pragma line.
    supps = {m.rel: parse_suppressions(m) for m in modules}
    kept: list[Finding] = []
    suppressed: list[tuple[Finding, str]] = []
    for f in raw:
        hit = next(
            (s for s in supps.get(f.path, []) if _matches(s, f)), None
        )
        if hit is None:
            kept.append(f)
        elif hit.reason:
            hit.used = True
            suppressed.append((f, hit.reason))
        else:
            hit.used = True
            kept.append(Finding(
                rule="lint.suppression-missing-reason",
                severity="error", path=f.path, line=hit.line,
                symbol=f.symbol,
                message=f"ignore[{f.rule}] pragma has no reason; write "
                        f"`# jepsenlint: ignore[{f.rule}] -- why`",
            ))
    # A reasoned pragma that matches nothing is debt pretending to be
    # documentation: the code it silenced was fixed (or the rule id is
    # wrong) and the pragma now silences whatever lands on that line
    # next.  Only meaningful on a full run — a subset of paths or
    # families legitimately leaves pragmas unmatched.
    if paths is None and families is None:
        for m in modules:
            for s in supps.get(m.rel, []):
                if not s.used:
                    kept.append(Finding(
                        rule="lint.unused-suppression",
                        severity="error", path=m.rel, line=s.line,
                        symbol="<module>",
                        message=(
                            f"ignore[{', '.join(s.rules)}] pragma "
                            "matches no finding — the debt it "
                            "documented is gone; delete the pragma"
                        ),
                    ))
    kept = assign_fingerprints(kept)

    bl_path = baseline or baseline_path(root)
    bl = load_baseline(bl_path)
    gate = [f for f in kept if f.fingerprint not in bl]
    matched = [f for f in kept if f.fingerprint in bl]
    live = {f.fingerprint for f in kept}
    stale = [e for fp, e in sorted(bl.items()) if fp not in live]

    return LintReport(
        findings=gate,
        baselined=matched,
        suppressed=suppressed,
        stale_baseline=stale,
        duration_s=time.perf_counter() - t0,
        files=len(modules),
    )


def lint_source(
    source: str,
    rel: str = "jepsen_tpu/fixture.py",
    families: Optional[Iterable[str]] = None,
    extra: Optional[dict[str, str]] = None,
) -> list[Finding]:
    """Lints a source string as if it lived at `rel` — the test-fixture
    entry point.  `extra` maps additional rel paths to sources analyzed
    in the same batch (for cross-module rules)."""
    modules = [Module(rel, rel, source)]
    for erel, esrc in (extra or {}).items():
        modules.append(Module(erel, erel, esrc))
    return analyze_modules(modules, families)


# ---------------------------------------------------------------------------
# Output + CLI
# ---------------------------------------------------------------------------


def render_human(report: LintReport, *, verbose: bool = False) -> str:
    lines = []
    for f in report.findings:
        lines.append(
            f"{f.path}:{f.line}: {f.severity} {f.rule} "
            f"[{f.fingerprint}] {f.symbol}: {f.message}"
        )
    if verbose:
        for f in report.baselined:
            lines.append(
                f"{f.path}:{f.line}: baselined {f.rule} "
                f"[{f.fingerprint}] {f.symbol}: {f.message}"
            )
        for f, reason in report.suppressed:
            lines.append(
                f"{f.path}:{f.line}: suppressed {f.rule}: {reason}"
            )
    for e in report.stale_baseline:
        lines.append(
            f"stale baseline entry [{e.get('fingerprint')}] "
            f"{e.get('rule')} at {e.get('path')} — fixed? run "
            f"--update-baseline to drop it"
        )
    c = report.counts()
    gate = report.counts(report.findings)
    lines.append(
        f"jepsenlint: {report.files} files in "
        f"{report.duration_s:.2f}s — "
        + ", ".join(f"{c[s]} {s}" for s in SEVERITIES)
        + f" ({sum(gate.values())} unbaselined, "
        f"{len(report.baselined)} baselined, "
        f"{len(report.suppressed)} suppressed, "
        f"{len(report.stale_baseline)} stale)"
    )
    return "\n".join(lines)


def render_json(report: LintReport) -> str:
    return json.dumps(report.to_dict(), indent=2, sort_keys=True)


def write_store_summary(report: LintReport, store_dir: str) -> Optional[str]:
    """Drops a lint.json summary into the store dir (when it exists) so
    the web /fleet page and /metrics scrape can surface lint state from
    another process.  Best-effort: lint's exit code never depends on
    this write."""
    if not os.path.isdir(store_dir):
        return None
    path = os.path.join(store_dir, "lint.json")
    try:
        payload = {
            "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "clean": report.clean,
            "counts": report.counts(),
            "families": report.family_counts(),
            "unbaselined": len(report.findings),
            "baselined": len(report.baselined),
            "suppressed": len(report.suppressed),
            "stale": len(report.stale_baseline),
            "duration_s": round(report.duration_s, 3),
            "files": report.files,
        }
        # Atomic: the web /fleet page and /metrics scrape read this
        # back from another process — a torn lint.json must never be
        # observable (durability.non-atomic-checkpoint, eating our
        # own dogfood).
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def read_store_summary(store_dir: str) -> Optional[dict]:
    try:
        with open(os.path.join(store_dir, "lint.json"),
                  encoding="utf-8") as f:
            data = json.load(f)
        return data if isinstance(data, dict) else None
    except (OSError, ValueError):
        return None


def add_lint_args(p: Any) -> None:
    """Registers the lint flags on an argparse parser (shared between
    `jepsen lint`, tools/lint.py, and python -m jepsen_tpu.analysis)."""
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files/dirs to lint (default: jepsen_tpu, tools, bench.py)",
    )
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detected)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--verbose", "-v", action="store_true",
                   help="also list baselined and suppressed findings")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: <root>/{BASELINE_FILE})")
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current findings (keeps "
        "existing justifications, drops stale entries); new entries "
        "need a written justification before merging",
    )
    p.add_argument(
        "--families", default=None,
        help="comma-separated rule families "
        "(device,concurrency,durability,protocol)",
    )
    p.add_argument(
        "--sarif", default=None, metavar="PATH",
        help="also write the unbaselined findings as SARIF 2.1.0 "
        "(for CI PR annotation); exit code is unchanged",
    )
    p.add_argument(
        "--write-counters", nargs="?", const="doc/counters.md",
        default=None, metavar="PATH",
        help="regenerate the canonical telemetry-counter table "
        "(default doc/counters.md) from the protocol rule's scan",
    )
    p.add_argument(
        "--lint-store-dir", default="store", metavar="DIR",
        help="store dir to drop the lint.json observatory summary "
        "into when it exists (default: store)",
    )


def find_root(start: Optional[str] = None) -> str:
    """The repo root: nearest ancestor holding jepsen_tpu/ (falls back
    to this package's grandparent)."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isdir(os.path.join(d, "jepsen_tpu")):
            return d
        nxt = os.path.dirname(d)
        if nxt == d:
            break
        d = nxt
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(opts: Any) -> int:
    """Shared driver behind every lint entry point.  Exit 0 = clean
    (no unbaselined findings), 1 = findings, 2 = internal error."""
    root = os.path.abspath(opts.root) if opts.root else find_root()
    families = None
    if getattr(opts, "families", None):
        families = [f.strip() for f in opts.families.split(",")
                    if f.strip()]

    if getattr(opts, "write_counters", None):
        from .rules import protocol

        path = opts.write_counters
        if not os.path.isabs(path):
            path = os.path.join(root, path)
        text = protocol.render_counters_md(load_modules(root))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {path}")
        return 0

    report = run_lint(
        root,
        paths=opts.paths or None,
        baseline=opts.baseline,
        families=families,
    )

    if getattr(opts, "update_baseline", False):
        bl_path = opts.baseline or baseline_path(root)
        old = load_baseline(bl_path)
        save_baseline(bl_path, report.all_findings, old)
        print(f"baseline rewritten: {bl_path} "
              f"({len(report.all_findings)} entries)")
        return 0

    sarif_path = getattr(opts, "sarif", None)
    if sarif_path:
        from . import sarif

        if not os.path.isabs(sarif_path):
            sarif_path = os.path.join(root, sarif_path)
        sarif.write_sarif(report, sarif_path)

    store_dir = getattr(opts, "lint_store_dir", None)
    if store_dir:
        if not os.path.isabs(store_dir):
            store_dir = os.path.join(root, store_dir)
        write_store_summary(report, store_dir)

    print(render_json(report) if opts.as_json
          else render_human(report, verbose=opts.verbose))
    return 0 if report.clean else 1
