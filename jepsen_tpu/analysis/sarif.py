"""SARIF 2.1.0 output for jepsenlint — the CI annotation surface.

One run, one tool (``jepsenlint``), rule metadata pulled from the
family catalogs, and one result per *unbaselined* finding (the gate
set: what a reviewer must act on).  Baselined findings are emitted as
suppressed results so the SARIF consumer sees the whole picture but
annotates only the live debt.  The line-motion-stable jepsenlint
fingerprint rides in ``partialFingerprints`` so GitHub's alert
tracking follows the same identity the baseline does.

The exit-code gate is unaffected: this is a *rendering* of the report,
written best-effort next to whatever the CLI was asked for.
"""

from __future__ import annotations

import json
from typing import Any

from .core import Finding, LintReport

#: SARIF `level` per jepsenlint severity.
_LEVEL = {"error": "error", "warning": "warning", "advice": "note"}


def _rule_ids(report: LintReport) -> list[str]:
    from .rules import RULES

    ids = set(RULES)
    for f in report.findings + report.baselined:
        ids.add(f.rule)
    return sorted(ids)


def _result(f: Finding, *, suppressed: bool = False) -> dict:
    out: dict[str, Any] = {
        "ruleId": f.rule,
        "level": _LEVEL.get(f.severity, "warning"),
        "message": {"text": f"{f.symbol}: {f.message}"},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": f.path,
                    "uriBaseId": "SRCROOT",
                },
                "region": {"startLine": max(1, f.line)},
            },
        }],
        "partialFingerprints": {"jepsenlint/v1": f.fingerprint},
    }
    if suppressed:
        out["suppressions"] = [{
            "kind": "external",
            "justification": "accepted in lint_baseline.json",
        }]
    return out


def render_sarif(report: LintReport) -> dict:
    from .rules import RULES

    rules = []
    for rid in _rule_ids(report):
        sev, doc = RULES.get(rid, ("warning", rid))
        rules.append({
            "id": rid,
            "shortDescription": {"text": doc},
            "defaultConfiguration": {
                "level": _LEVEL.get(sev, "warning"),
            },
        })
    results = [_result(f) for f in report.findings]
    results += [_result(f, suppressed=True) for f in report.baselined]
    return {
        "version": "2.1.0",
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "runs": [{
            "tool": {
                "driver": {
                    "name": "jepsenlint",
                    "informationUri":
                        "https://example.invalid/jepsenlint",
                    "rules": rules,
                },
            },
            "originalUriBaseIds": {
                "SRCROOT": {"uri": "file:///"},
            },
            "results": results,
        }],
    }


def write_sarif(report: LintReport, path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(render_sarif(report), f, indent=2, sort_keys=True)
        f.write("\n")
    return path
