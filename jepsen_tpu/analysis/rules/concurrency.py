"""Concurrency discipline: lock-order cycles, unsynced thread state.

~30 modules in this repo spawn threads: the interpreter's
worker-abandon protocol (lock + push-counter), the streaming front/back
buffer swap, the checkerd scheduler's condition queue, the health
monitor's probe loop, telemetry's registry lock with the span-exit hook
chained through profile captures.  None of their lock discipline was
machine-checked before this rule family.

``concurrency.lock-order-cycle`` (error) builds a **module-level
lock-order graph**: every ``with lock:`` / ``lock.acquire()`` defines
an acquisition scope; acquiring M while holding L adds the edge L→M.
Calls made while holding a lock propagate through a resolved
intra-repo call graph (``self.m`` → same class, bare names → same
module, imported names → the imported module), so the telemetry
span-exit hook chain — a pass holding its own lock calling
``telemetry.count`` which takes ``telemetry._lock`` — contributes its
edges without any annotation.  A cycle in the graph is a deadlock that
needs only the right interleaving; reentrant self-edges (RLock /
Condition) are exempt.

Lock identity is scoped to where the lock object lives: module-level
creations get ``module.NAME``, instance attributes
``module.Class.attr``, and function-local locks ``module.func.NAME`` —
so two unrelated local ``lock`` variables never alias into a false
cycle.

``concurrency.unsynced-thread-attr`` (advice) flags instance
attributes *written inside a ``threading.Thread(target=...)`` entry
method* and read from other methods with **no common lock** between
the write sites and the read sites.  That is exactly the shape of a
torn-state bug between a daemon thread and its controlling API
(stop flags get a pass: single-word stores the reader re-checks are
the repo's sanctioned idiom and belong in the baseline with that
justification, not silently exempted here).
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Module

RULES = {
    "concurrency.lock-order-cycle": (
        "error",
        "cycle in the cross-module lock-order graph (deadlock by "
        "interleaving)",
    ),
    "concurrency.unsynced-thread-attr": (
        "advice",
        "attribute written in a Thread entry point and read elsewhere "
        "with no common lock",
    ),
}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_REENTRANT_CTORS = {"RLock", "Condition"}


def _lockish_text(seg: str) -> bool:
    low = seg.lower()
    return ("lock" in low or "cond" in low or "sem" in low) and \
        "clock" not in low


class _Scope:
    """Lock creations and usages for one module."""

    def __init__(self, m: Module):
        self.m = m
        # (scope-symbol or "", name) -> reentrant?
        self.created: dict[tuple[str, str], bool] = {}
        self._collect()

    def _collect(self) -> None:
        for node in ast.walk(self.m.tree):
            if not isinstance(node, ast.Assign):
                continue
            ctor = self._ctor_of(node.value)
            if ctor is None:
                continue
            reentrant = ctor in _REENTRANT_CTORS
            fn = self.m.enclosing_function(node)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    scope = self.m.symbol(node) if fn is not None else ""
                    self.created[(scope, tgt.id)] = reentrant
                elif (isinstance(tgt, ast.Attribute)
                      and isinstance(tgt.value, ast.Name)
                      and tgt.value.id == "self"):
                    cls = self.m.enclosing_class(node)
                    if cls is not None:
                        self.created[(cls.name, tgt.attr)] = reentrant

    def _ctor_of(self, value: ast.AST) -> Optional[str]:
        # `threading.Lock()`, `Lock()`, and the `x or threading.Lock()`
        # defaulting idiom all count as creations.
        if isinstance(value, ast.BoolOp):
            for v in value.values:
                c = self._ctor_of(v)
                if c:
                    return c
            return None
        if not isinstance(value, ast.Call):
            return None
        f = value.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        return name if name in _LOCK_CTORS else None

    def resolve(self, node: ast.AST,
                expr: ast.AST) -> Optional[tuple[str, bool]]:
        """(lock-id, reentrant) for a with-item / acquire target, or
        None when the expression isn't a lock."""
        # Unwrap `self._lock.read()` / `.write()` style wrappers.
        if isinstance(expr, ast.Call):
            expr = expr.func
            if isinstance(expr, ast.Attribute):
                expr = expr.value
        m = self.m
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            cls = m.enclosing_class(node)
            cname = cls.name if cls is not None else "?"
            key = (cname, expr.attr)
            if key in self.created:
                return (f"{m.name}.{cname}.{expr.attr}",
                        self.created[key])
            if _lockish_text(expr.attr):
                return (f"{m.name}.{cname}.{expr.attr}", False)
            return None
        if isinstance(expr, ast.Name):
            # Innermost creating scope wins: function-local locks are
            # distinct per function, closures see their definer.
            fn = m.enclosing_function(node)
            while fn is not None:
                key = (m.symbol(fn), expr.id)
                if key in self.created:
                    return (f"{m.name}.{key[0]}.{expr.id}",
                            self.created[key])
                fn = m.enclosing_function(fn)
            if ("", expr.id) in self.created:
                return (f"{m.name}.{expr.id}",
                        self.created[("", expr.id)])
            if _lockish_text(expr.id):
                sym = m.symbol(node)
                scoped = sym if sym != "<module>" else ""
                return (f"{m.name}{'.' + scoped if scoped else ''}"
                        f".{expr.id}", False)
            return None
        seg = m.seg(expr)
        if _lockish_text(seg.split("(")[0].split("[")[0]):
            return (f"{m.name}.{seg.split('(')[0]}", False)
        return None


def _import_map(m: Module) -> dict[str, str]:
    """alias -> dotted target ("telemetry" -> "jepsen_tpu.telemetry",
    "_count" -> "jepsen_tpu.telemetry.count", ...)."""
    out: dict[str, str] = {}
    pkg_parts = m.name.split(".")
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[: len(pkg_parts) - node.level]
            else:
                base = []
            mod = ".".join(base + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = (
                    f"{mod}.{a.name}" if mod else a.name
                )
    return out


class _FnInfo:
    __slots__ = ("key", "module", "acquires", "calls_under",
                 "calls_all")

    def __init__(self, key: tuple[str, str], module: Module):
        self.key = key
        self.module = module
        self.acquires: set[str] = set()       # direct lock ids
        # [(held-tuple, callee-text, line)]
        self.calls_under: list[tuple[tuple[str, ...], str, int]] = []
        self.calls_all: list[str] = []        # every callee text


def _walk_function(m: Module, scope: _Scope, fn: ast.FunctionDef,
                   info: _FnInfo,
                   edges: dict[tuple[str, str], tuple[str, int, str]],
                   reentrant: set[str]) -> None:
    """Single in-order pass tracking the held-lock stack.  Nested
    function bodies are skipped (they run later, not under the lock)."""

    def visit(node: ast.AST, held: tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return
        if isinstance(node, ast.With):
            acquired: list[str] = []
            for item in node.items:
                r = scope.resolve(node, item.context_expr)
                if r is None:
                    continue
                lock, re_ok = r
                if re_ok:
                    reentrant.add(lock)
                info.acquires.add(lock)
                for h in held:
                    edges.setdefault(
                        (h, lock),
                        (m.rel, node.lineno, m.symbol(node)),
                    )
                acquired.append(lock)
            inner = held + tuple(acquired)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, ast.Call):
            func_seg = m.seg(node.func)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                r = scope.resolve(node, node.func.value)
                if r is not None:
                    lock, re_ok = r
                    if re_ok:
                        reentrant.add(lock)
                    info.acquires.add(lock)
                    for h in held:
                        edges.setdefault(
                            (h, lock),
                            (m.rel, node.lineno, m.symbol(node)),
                        )
            else:
                info.calls_all.append(func_seg)
                if held:
                    info.calls_under.append(
                        (held, func_seg, node.lineno)
                    )
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, ())


def _resolve_callee(
    text: str, m: Module, imports: dict[str, str],
    fns: dict[tuple[str, str], _FnInfo],
) -> Optional[_FnInfo]:
    """Best-effort: `self.m` -> same-class method, bare name -> same
    module, `alias.f` -> imported module's f."""
    text = text.strip()
    if text.startswith("self."):
        meth = text[5:].split("(")[0]
        for (mod, sym), fi in fns.items():
            if mod == m.name and sym.endswith(f".{meth}"):
                return fi
        return None
    head = text.split("(")[0]
    if "." not in head:
        target = imports.get(head, head)
        if "." in target:           # from x import f
            mod, _, f = target.rpartition(".")
            return fns.get((mod, f))
        return fns.get((m.name, head))
    alias, _, rest = head.partition(".")
    base = imports.get(alias)
    if base is None:
        return None
    parts = rest.split(".")
    # alias may be a module (alias.f) or a package (alias.sub.f).
    for split in range(len(parts), 0, -1):
        mod = ".".join([base] + parts[: split - 1])
        f = parts[split - 1]
        hit = fns.get((mod, f))
        if hit is not None:
            return hit
    return None


def _find_cycles(
    edges: dict[tuple[str, str], tuple[str, int, str]],
    reentrant: set[str],
) -> list[list[str]]:
    """Elementary cycles via DFS over the lock digraph, deduped by
    canonical rotation.  Self-loops on reentrant locks are fine."""
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        if a == b and a in reentrant:
            continue
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: dict[tuple[str, ...], list[str]] = {}

    def dfs(start: str, node: str, path: list[str],
            on_path: set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = path[:]
                i = cyc.index(min(cyc))
                key = tuple(cyc[i:] + cyc[:i])
                cycles.setdefault(key, cyc)
            elif nxt not in on_path and nxt > start:
                # Only explore nodes > start: each cycle found once,
                # rooted at its smallest node.
                path.append(nxt)
                on_path.add(nxt)
                dfs(start, nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return list(cycles.values())


def _check_lock_order(modules: list[Module]) -> list[Finding]:
    fns: dict[tuple[str, str], _FnInfo] = {}
    scopes: dict[str, _Scope] = {}
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}
    reentrant: set[str] = set()
    mod_by_name = {m.name: m for m in modules}

    for m in modules:
        scope = _Scope(m)
        scopes[m.name] = scope
        for node in ast.walk(m.tree):
            if isinstance(node, ast.FunctionDef):
                key = (m.name, m.symbol(node))
                fi = _FnInfo(key, m)
                fns[key] = fi
                _walk_function(m, scope, node, fi, edges, reentrant)

    # Transitive lock acquisition through calls: fixpoint over the
    # resolved call graph, then held×acquired(callee) edges.
    imports = {m.name: _import_map(m) for m in modules}
    trans: dict[tuple[str, str], set[str]] = {
        k: set(fi.acquires) for k, fi in fns.items()
    }
    for _ in range(6):          # bounded: call chains deeper than this
        changed = False         # don't exist in the lock protocols here
        for key, fi in fns.items():
            for text in fi.calls_all:
                callee = _resolve_callee(
                    text, fi.module, imports[fi.module.name], fns)
                if callee is None:
                    continue
                add = trans[callee.key] - trans[key]
                if add:
                    trans[key].update(add)
                    changed = True
        if not changed:
            break

    for key, fi in fns.items():
        for held, text, line in fi.calls_under:
            callee = _resolve_callee(
                text, fi.module, imports[fi.module.name], fns)
            if callee is None:
                continue
            for lock in trans[callee.key]:
                for h in held:
                    edges.setdefault(
                        (h, lock), (fi.module.rel, line, key[1]))

    out = []
    for cyc in _find_cycles(edges, reentrant):
        ring = cyc + [cyc[0]]
        witnesses = []
        for a, b in zip(ring, ring[1:]):
            w = edges.get((a, b))
            if w:
                witnesses.append(f"{a} -> {b} at {w[0]}:{w[1]}")
        first = edges.get((ring[0], ring[1])) or ("<unknown>", 1, "?")
        mod = mod_by_name.get(
            next((m.name for m in modules if m.rel == first[0]), ""),
        )
        f = Finding(
            rule="concurrency.lock-order-cycle", severity="error",
            path=first[0], line=first[1], symbol=first[2],
            message="lock-order cycle: " + "; ".join(witnesses)
                    + " — a timely interleaving deadlocks; impose one "
                    "global order or drop a lock before the call",
        )
        _ = mod
        out.append(f)
    return out


def _check_thread_attrs(modules: list[Module]) -> list[Finding]:
    out = []
    for m in modules:
        scope = _Scope(m)
        for cls in [n for n in ast.walk(m.tree)
                    if isinstance(n, ast.ClassDef)]:
            methods = {
                n.name: n for n in cls.body
                if isinstance(n, ast.FunctionDef)
            }
            entries: set[str] = set()
            for node in ast.walk(cls):
                if not isinstance(node, ast.Call):
                    continue
                seg = m.seg(node.func)
                if not seg.endswith("Thread"):
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    t = kw.value
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and t.attr in methods):
                        entries.add(t.attr)
            if not entries:
                continue
            # Everything the entry calls via self.* runs ON the spawned
            # thread too — close over the intra-class call graph.
            frontier = list(entries)
            while frontier:
                meth = methods[frontier.pop()]
                for node in ast.walk(meth):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id == "self"
                            and node.func.attr in methods
                            and node.func.attr not in entries):
                        entries.add(node.func.attr)
                        frontier.append(node.func.attr)

            def _locks_held(node: ast.AST) -> set[str]:
                held = set()
                for p in m.parents(node):
                    if isinstance(p, ast.With):
                        for item in p.items:
                            r = scope.resolve(p, item.context_expr)
                            if r:
                                held.add(r[0])
                return held

            # attr -> union of locks held over its write sites in the
            # thread entry, and a witness line.
            writes: dict[str, tuple[set[str], int, bool]] = {}
            for ename in sorted(entries):
                for node in ast.walk(methods[ename]):
                    tgt = None
                    if isinstance(node, ast.Assign):
                        tgt = node.targets
                    elif isinstance(node, ast.AugAssign):
                        tgt = [node.target]
                    if not tgt:
                        continue
                    for t in tgt:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            held = _locks_held(node)
                            prev = writes.get(t.attr)
                            if prev is None:
                                writes[t.attr] = (held, node.lineno,
                                                  True)
                            else:
                                writes[t.attr] = (
                                    prev[0] | held,
                                    min(prev[1], node.lineno),
                                    prev[2] and bool(held),
                                )
            for attr, (wlocks, wline, _all) in sorted(writes.items()):
                for mname, meth in methods.items():
                    if mname in entries or mname == "__init__":
                        continue
                    for node in ast.walk(meth):
                        if (isinstance(node, ast.Attribute)
                                and isinstance(node.ctx, ast.Load)
                                and isinstance(node.value, ast.Name)
                                and node.value.id == "self"
                                and node.attr == attr):
                            rlocks = _locks_held(node)
                            if wlocks & rlocks:
                                continue
                            out.append(Finding(
                                rule="concurrency.unsynced-thread-attr",
                                severity="advice", path=m.rel,
                                line=node.lineno,
                                symbol=f"{cls.name}.{mname}",
                                message=(
                                    f"self.{attr} is written in thread "
                                    f"entry `{cls.name}."
                                    f"{'/'.join(sorted(entries))}` "
                                    f"(line {wline}) and read here "
                                    f"with no common lock — torn "
                                    f"state unless it is a single-word "
                                    f"flag the reader re-checks"
                                ),
                            ))
                            break       # one finding per reading method
    return out


def check(modules: list[Module]) -> list[Finding]:
    scan = [m for m in modules if m.rel.startswith("jepsen_tpu/")]
    out = _check_lock_order(scan)
    out.extend(_check_thread_attrs(scan))
    return out
