"""Concurrency discipline: lock-order cycles, guarded-by contracts,
unsynced thread state.

~30 modules in this repo spawn threads: the interpreter's
worker-abandon protocol (lock + push-counter), the streaming front/back
buffer swap, the checkerd scheduler's condition queue, the health
monitor's probe loop, telemetry's registry lock with the span-exit hook
chained through profile captures.  This family machine-checks their
lock discipline over the shared interprocedural effect summaries
(analysis/effects.py).

``concurrency.lock-order-cycle`` (error) builds a **cross-module
lock-order graph**: every ``with lock:`` / ``lock.acquire()`` defines
an acquisition scope; acquiring M while holding L adds the edge L→M.
Calls made while holding a lock propagate through the program's
resolved call graph — including cross-module edges and the
unique-method dynamic-dispatch fallback — so the telemetry span-exit
hook chain contributes its edges without any annotation.  A cycle in
the graph is a deadlock that needs only the right interleaving;
reentrant self-edges (RLock / Condition) are exempt.

Lock identity is scoped to where the lock object lives: module-level
creations get ``module.NAME``, instance attributes
``module.Class.attr``, and function-local locks ``module.func.NAME`` —
so two unrelated local ``lock`` variables never alias into a false
cycle.

``concurrency.guarded-by`` (error) is the checked contract that PR 13's
ad-hoc locking fixes graduate into.  Declare it where the state is
born::

    self._tickets = {}   # guarded-by: self._lock

and every read or write of ``self._tickets`` anywhere in the class must
then happen while ``self._lock`` is held — directly, or because every
resolved caller of the accessing method holds it at the call site (the
private-helper-under-lock idiom), checked as a fixpoint over the call
graph.  ``__init__`` is exempt (construction happens-before
publication), and so are helpers reachable only from ``__init__``.
The same contract is **inferred** for thread-spawning classes whose
attribute writes all happen under one common lock: the writes declare
the protocol, the reads are held to it.

``concurrency.unsynced-thread-attr`` (advice) remains the fallback for
attributes with *no* lock discipline to infer: written inside a
``threading.Thread(target=...)`` entry and read from other methods
with no common lock between write and read sites.  Attributes covered
by a guarded-by contract (annotated or inferred) are checked by the
contract instead, not double-reported here.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Module
from .. import effects
from ..effects import Event, Key, LockScope, Program, import_map

# Older import sites (rules/device.py) use the leading-underscore
# names this module exported before the machinery moved to effects.py.
_Scope = LockScope
_import_map = import_map

RULES = {
    "concurrency.lock-order-cycle": (
        "error",
        "cycle in the cross-module lock-order graph (deadlock by "
        "interleaving)",
    ),
    "concurrency.guarded-by": (
        "error",
        "attribute with a guarded-by contract accessed without the "
        "declared lock held",
    ),
    "concurrency.unsynced-thread-attr": (
        "advice",
        "attribute written in a Thread entry point and read elsewhere "
        "with no common lock",
    ),
}


# ---------------------------------------------------------------------------
# lock-order-cycle
# ---------------------------------------------------------------------------


def _find_cycles(
    edges: dict[tuple[str, str], tuple[str, int, str]],
    reentrant: set[str],
) -> list[list[str]]:
    """Elementary cycles via DFS over the lock digraph, deduped by
    canonical rotation.  Self-loops on reentrant locks are fine."""
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        if a == b and a in reentrant:
            continue
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: dict[tuple[str, ...], list[str]] = {}

    def dfs(start: str, node: str, path: list[str],
            on_path: set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = path[:]
                i = cyc.index(min(cyc))
                key = tuple(cyc[i:] + cyc[:i])
                cycles.setdefault(key, cyc)
            elif nxt not in on_path and nxt > start:
                # Only explore nodes > start: each cycle found once,
                # rooted at its smallest node.
                path.append(nxt)
                on_path.add(nxt)
                dfs(start, nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return list(cycles.values())


def _check_lock_order(prog: Program) -> list[Finding]:
    """Held×acquired edges straight off the effect summaries: direct
    acquisitions carry the held stack, and calls made under a lock
    contribute the callee's *transitive* acquisitions (the program
    fixpoint — cross-module, recursion-safe)."""
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}
    for key, fi in prog.fns.items():
        for ev in fi.events:
            if ev.kind == "acquire":
                for h in ev.held:
                    edges.setdefault(
                        (h, ev.detail),
                        (fi.module.rel, ev.line, key[1]))
            elif ev.kind == "call" and ev.held:
                callee = prog.resolve(ev.detail, fi.module, fi.cls, fi)
                if callee is None:
                    continue
                for lock in prog.trans_acquires(callee):
                    for h in ev.held:
                        edges.setdefault(
                            (h, lock),
                            (fi.module.rel, ev.line, key[1]))

    out = []
    for cyc in _find_cycles(edges, prog.reentrant):
        ring = cyc + [cyc[0]]
        witnesses = []
        for a, b in zip(ring, ring[1:]):
            w = edges.get((a, b))
            if w:
                witnesses.append(f"{a} -> {b} at {w[0]}:{w[1]}")
        first = edges.get((ring[0], ring[1])) or ("<unknown>", 1, "?")
        out.append(Finding(
            rule="concurrency.lock-order-cycle", severity="error",
            path=first[0], line=first[1], symbol=first[2],
            message="lock-order cycle: " + "; ".join(witnesses)
                    + " — a timely interleaving deadlocks; impose one "
                    "global order or drop a lock before the call",
        ))
    return out


# ---------------------------------------------------------------------------
# guarded-by contracts
# ---------------------------------------------------------------------------


def _thread_entries(m: Module, cls: ast.ClassDef,
                    methods: dict[str, ast.FunctionDef]) -> set[str]:
    """Methods handed to ``threading.Thread(target=self.x)`` plus the
    intra-class closure of what they call — everything that runs ON
    the spawned thread."""
    entries: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        seg = m.seg(node.func)
        if not seg.endswith("Thread"):
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            t = kw.value
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and t.attr in methods):
                entries.add(t.attr)
    frontier = list(entries)
    while frontier:
        meth = methods[frontier.pop()]
        for node in ast.walk(meth):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and node.func.attr in methods
                    and node.func.attr not in entries):
                entries.add(node.func.attr)
                frontier.append(node.func.attr)
    return entries


def _class_methods(m: Module, cls: ast.ClassDef
                   ) -> dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, ast.FunctionDef)}


def _contracts(prog: Program) -> dict[tuple[str, str],
                                      dict[str, tuple[str, str]]]:
    """(module, class) -> {attr: (lock-id, "annotated"|"inferred")}.

    Annotated contracts come from ``# guarded-by:`` comments (parsed
    during the effect walk).  Inferred ones: in a thread-spawning
    class, an attribute whose every write outside ``__init__`` holds
    one common lock has declared its protocol by construction."""
    out: dict[tuple[str, str], dict[str, tuple[str, str]]] = {}
    for ck, guards in prog.guards.items():
        out[ck] = {attr: (lock, "annotated")
                   for attr, lock in guards.items()}
    for m in prog.modules:
        for cls in [n for n in ast.walk(m.tree)
                    if isinstance(n, ast.ClassDef)]:
            methods = _class_methods(m, cls)
            if not _thread_entries(m, cls, methods):
                continue
            ck = (m.name, cls.name)
            have = out.setdefault(ck, {})
            # attr -> intersection of held locks over write sites
            common: dict[str, set[str]] = {}
            for mname, key in prog.classes.get(ck, {}).items():
                if mname == "__init__":
                    continue
                fi = prog.fns.get(key)
                if fi is None:
                    continue
                for site in fi.attr_sites:
                    if site.kind != "write":
                        continue
                    held = set(site.held)
                    if site.attr in common:
                        common[site.attr] &= held
                    else:
                        common[site.attr] = held
            for attr, locks in sorted(common.items()):
                if attr in have or not locks:
                    continue
                have[attr] = (sorted(locks)[0], "inferred")
    return out


def _check_guarded_by(prog: Program,
                      contracts: dict) -> list[Finding]:
    # safe(key, lock): every resolved caller holds `lock` at the call
    # site, or is __init__ of the owning class, or is itself safe —
    # the private-helper-under-lock idiom, closed over the call graph.
    memo: dict[tuple[Key, str, str], bool] = {}

    def safe(key: Key, lock: str, init_key: str,
             active: frozenset) -> bool:
        mk = (key, lock, init_key)
        if mk in memo:
            return memo[mk]
        if key in active:
            return True         # call cycle: don't condemn on it
        callers = prog.callers().get(key)
        if not callers:
            memo[mk] = False
            return False
        ok = True
        for ckey, ev in callers:
            if ckey[1] == init_key:
                continue        # construction happens-before publication
            if lock in ev.held:
                continue
            if not safe(ckey, lock, init_key, active | {key}):
                ok = False
                break
        memo[mk] = ok
        return ok

    out = []
    for (mod, cname), attrs in sorted(contracts.items()):
        methods = prog.classes.get((mod, cname), {})
        init_key = f"{cname}.__init__"
        for mname, key in sorted(methods.items()):
            if mname == "__init__":
                continue
            fi = prog.fns.get(key)
            if fi is None:
                continue
            flagged: set[str] = set()
            for site in fi.attr_sites:
                spec = attrs.get(site.attr)
                if spec is None or site.attr in flagged:
                    continue
                lock, how = spec
                if lock in site.held:
                    continue
                if safe(key, lock, init_key, frozenset()):
                    continue
                flagged.add(site.attr)      # one finding per (method, attr)
                short = lock.rsplit(".", 1)[-1]
                out.append(Finding(
                    rule="concurrency.guarded-by", severity="error",
                    path=fi.module.rel, line=site.line,
                    symbol=f"{cname}.{mname}",
                    message=(
                        f"self.{site.attr} is guarded by self.{short} "
                        f"({how}) but {site.kind} here without it — "
                        "hold the lock, or show every caller does"
                    ),
                ))
    return out


# ---------------------------------------------------------------------------
# unsynced-thread-attr (fallback advice for contract-less attributes)
# ---------------------------------------------------------------------------


def _check_thread_attrs(modules: list[Module],
                        contracts: dict) -> list[Finding]:
    out = []
    for m in modules:
        scope = LockScope(m)
        for cls in [n for n in ast.walk(m.tree)
                    if isinstance(n, ast.ClassDef)]:
            methods = _class_methods(m, cls)
            entries = _thread_entries(m, cls, methods)
            if not entries:
                continue
            covered = contracts.get((m.name, cls.name), {})

            def _locks_held(node: ast.AST) -> set[str]:
                held = set()
                for p in m.parents(node):
                    if isinstance(p, ast.With):
                        for item in p.items:
                            r = scope.resolve(p, item.context_expr)
                            if r:
                                held.add(r[0])
                return held

            # attr -> union of locks held over its write sites in the
            # thread entry, and a witness line.
            writes: dict[str, tuple[set[str], int, bool]] = {}
            for ename in sorted(entries):
                for node in ast.walk(methods[ename]):
                    tgt = None
                    if isinstance(node, ast.Assign):
                        tgt = node.targets
                    elif isinstance(node, ast.AugAssign):
                        tgt = [node.target]
                    if not tgt:
                        continue
                    for t in tgt:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            held = _locks_held(node)
                            prev = writes.get(t.attr)
                            if prev is None:
                                writes[t.attr] = (held, node.lineno,
                                                  True)
                            else:
                                writes[t.attr] = (
                                    prev[0] | held,
                                    min(prev[1], node.lineno),
                                    prev[2] and bool(held),
                                )
            for attr, (wlocks, wline, _all) in sorted(writes.items()):
                if attr in covered:
                    continue        # the contract checks this one
                for mname, meth in methods.items():
                    if mname in entries or mname == "__init__":
                        continue
                    for node in ast.walk(meth):
                        if (isinstance(node, ast.Attribute)
                                and isinstance(node.ctx, ast.Load)
                                and isinstance(node.value, ast.Name)
                                and node.value.id == "self"
                                and node.attr == attr):
                            rlocks = _locks_held(node)
                            if wlocks & rlocks:
                                continue
                            out.append(Finding(
                                rule="concurrency.unsynced-thread-attr",
                                severity="advice", path=m.rel,
                                line=node.lineno,
                                symbol=f"{cls.name}.{mname}",
                                message=(
                                    f"self.{attr} is written in thread "
                                    f"entry `{cls.name}."
                                    f"{'/'.join(sorted(entries))}` "
                                    f"(line {wline}) and read here "
                                    f"with no common lock — torn "
                                    f"state unless it is a single-word "
                                    f"flag the reader re-checks"
                                ),
                            ))
                            break       # one finding per reading method
    return out


def check(modules: list[Module]) -> list[Finding]:
    scan = [m for m in modules if m.rel.startswith("jepsen_tpu/")]
    prog = effects.build(scan)
    contracts = _contracts(prog)
    out = _check_lock_order(prog)
    out.extend(_check_guarded_by(prog, contracts))
    out.extend(_check_thread_attrs(scan, contracts))
    return out
