"""Crash-durability protocol, machine-checked at every journal site.

The repo's persistence story is one discipline implemented many times:
**append → flush → fsync → only then reply**, torn tails truncated on
reopen, checkpoints replaced atomically.  PRs 4–14 proved individual
implementations point-by-point with crash tests; this family verifies
the protocol *statically* at every site, over the interprocedural
effect summaries (analysis/effects.py) so the append and its fsync may
live in different functions — or different modules — and still be
matched up.

``durability.fsync-missing`` (error) — a ``X.append(BLOCK_*, ...)``
journal append after which no fsync (``.sync()`` / ``os.fsync``)
happens in the appender, and no caller chain supplies one after the
call site either; plus ``.jsonl`` append-mode writes whose ``with``
body lacks flush+fsync.  The analysis is path-insensitive (events in
textual order) and absolves an appender when *every* resolved caller
fsyncs after the call — the ledger/journal idiom of a bare append
helper sealed by its caller stays clean without annotations.

``durability.reply-before-fsync`` (error) — a frame or socket send
(``write_frame`` / ``.sendall``) reachable while a journal append's
fsync has not yet happened: the ack can outlive the data.  Checked per
function over the effect walk with callee effects folded in, so
"append here, reply in the helper" is still caught.

``durability.torn-tail-unhandled`` (warning) — a call to the
low-level ``_read_block`` frame reader outside store/format.py whose
result is never None-checked in the enclosing function: ``None`` *is*
the torn tail, and ignoring it turns a crash-truncated file into a
crash of the reader.

``durability.non-atomic-checkpoint`` (warning) — persistent JSON
state (a ``.json`` file the repo also *reads back* somewhere) written
via bare ``open(path, "w")`` + ``json.dump`` with no ``os.replace`` in
the writing function: a crash mid-write leaves a half-written
checkpoint where a consumer expects valid JSON.  Write-only artifacts
(reports, rendered dossiers) are out of scope by construction — no
read site, no finding.

``durability.block-type-collision`` (error) — two ``BLOCK_*`` wire ids
with the same value, or a checkerd ``F_*`` frame type colliding with a
store block type: the whole point of the shared id space is that a
frame can never be mistaken for an on-disk block.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from ..core import Finding, Module
from .. import effects
from ..effects import Event, Key, Program

RULES = {
    "durability.fsync-missing": (
        "error",
        "journal append with no fsync afterwards in the function or "
        "any caller path",
    ),
    "durability.reply-before-fsync": (
        "error",
        "frame/socket send reachable while a journal append is not "
        "yet fsynced",
    ),
    "durability.torn-tail-unhandled": (
        "warning",
        "_read_block caller that never None-checks the result (None "
        "is the torn tail)",
    ),
    "durability.non-atomic-checkpoint": (
        "warning",
        "read-back JSON state written via bare open('w') instead of "
        "tmp + os.replace",
    ),
    "durability.block-type-collision": (
        "error",
        "duplicate BLOCK_*/F_* wire id — a frame could be mistaken "
        "for an on-disk block",
    ),
}

_HINT_RE = re.compile(r"[\w][\w.-]*\.json$")


# ---------------------------------------------------------------------------
# append → fsync ordering (fsync-missing, reply-before-fsync)
# ---------------------------------------------------------------------------


class _NetWalk:
    """Per-function 'does it leave an unfsynced journal append, and
    does a send happen while one is pending' — callee effects folded in
    via the program's transitive kinds and the callee's own net state
    (memoized, cycle-cut)."""

    def __init__(self, prog: Program):
        self.prog = prog
        # key -> (leaves_unsynced, origin_line) — origin is the append
        # (or call) line the pending obligation came from.
        self._net: dict[Key, tuple[bool, int]] = {}
        self._active: set[Key] = set()
        # key -> [(send-line, append-origin-line)]
        self.sends_while_pending: dict[Key, list[tuple[int, int]]] = {}

    def net(self, key: Key) -> tuple[bool, int]:
        if key in self._net:
            return self._net[key]
        if key in self._active:
            return (False, 0)           # recursion: cut, no obligation
        self._active.add(key)
        out = self._walk(key)
        self._active.discard(key)
        self._net[key] = out
        return out

    def _walk(self, key: Key) -> tuple[bool, int]:
        fi = self.prog.fns.get(key)
        if fi is None:
            return (False, 0)
        pending = False
        origin = 0
        bad_sends: list[tuple[int, int]] = []
        for ev in fi.events:
            if ev.kind == "append":
                pending, origin = True, ev.line
            elif ev.kind == "fsync":
                pending = False
            elif ev.kind == "send":
                if pending:
                    bad_sends.append((ev.line, origin))
            elif ev.kind == "call":
                callee = self.prog.resolve(ev.detail, fi.module, fi.cls, fi)
                if callee is None or callee == key:
                    continue
                kinds = self.prog.trans_kinds(callee)
                if "send" in kinds and pending:
                    bad_sends.append((ev.line, origin))
                if "fsync" in kinds:
                    pending = False
                sub_pending, _sub_origin = self.net(callee)
                if sub_pending:
                    pending, origin = True, ev.line
        if bad_sends:
            self.sends_while_pending[key] = bad_sends
        return (pending, origin)


def _absolved(prog: Program, walk: _NetWalk, key: Key,
              seen: frozenset) -> bool:
    """True when every resolved caller fsyncs after its call to `key`
    (directly or via its own callers) — the append helper whose caller
    owns the sync."""
    callers = prog.callers().get(key)
    if not callers:
        return False
    for ckey, ev in callers:
        if ckey in seen:
            continue                    # call cycle: don't block on it
        if not _fsync_after(prog, walk, ckey, ev,
                            seen | {key}):
            return False
    return True


def _fsync_after(prog: Program, walk: _NetWalk, caller: Key,
                 call_ev: Event, seen: frozenset) -> bool:
    fi = prog.fns.get(caller)
    if fi is None:
        return False
    idx = fi.events.index(call_ev)
    for ev in fi.events[idx + 1:]:
        if ev.kind == "fsync":
            return True
        if ev.kind == "call":
            callee = prog.resolve(ev.detail, fi.module, fi.cls, fi)
            if callee is not None and \
                    "fsync" in prog.trans_kinds(callee):
                return True
    return _absolved(prog, walk, caller, seen)


def _check_append_protocol(prog: Program) -> list[Finding]:
    out: list[Finding] = []
    walk = _NetWalk(prog)
    for key, fi in sorted(prog.fns.items()):
        if not fi.module.rel.startswith("jepsen_tpu/"):
            continue
        direct_appends = [e for e in fi.events if e.kind == "append"]
        pending, origin = walk.net(key)
        if direct_appends and pending and origin in {
                e.line for e in direct_appends}:
            if not _absolved(prog, walk, key, frozenset({key})):
                out.append(Finding(
                    rule="durability.fsync-missing", severity="error",
                    path=fi.module.rel, line=origin,
                    symbol=key[1],
                    message=(
                        "journal append is never fsynced: neither "
                        f"`{key[1]}` nor any caller calls .sync()/"
                        "os.fsync after the append — a crash loses "
                        "acknowledged records (protocol: append → "
                        "flush → fsync → reply)"
                    ),
                ))
    for key, sends in sorted(walk.sends_while_pending.items()):
        fi = prog.fns[key]
        if not fi.module.rel.startswith("jepsen_tpu/"):
            continue
        for line, origin in sends:
            out.append(Finding(
                rule="durability.reply-before-fsync", severity="error",
                path=fi.module.rel, line=line, symbol=key[1],
                message=(
                    f"reply/send reachable before the journal append "
                    f"at line {origin} is fsynced — the ack can "
                    "outlive the data; fsync before sending"
                ),
            ))
    return out


# ---------------------------------------------------------------------------
# .jsonl append-mode durability
# ---------------------------------------------------------------------------


def _const_strs(m: Module, expr: ast.AST) -> list[str]:
    """Every string constant inside `expr`, with module-level constant
    Names resolved one hop."""
    consts = _module_consts(m)
    out: list[str] = []
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append(sub.value)
        elif isinstance(sub, ast.Name) and sub.id in consts:
            out.append(consts[sub.id])
    return out


_CONSTS_CACHE: dict[int, dict[str, str]] = {}


def _module_consts(m: Module) -> dict[str, str]:
    key = id(m)
    if key in _CONSTS_CACHE:
        return _CONSTS_CACHE[key]
    out: dict[str, str] = {}
    for node in m.tree.body:
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value.value
    _CONSTS_CACHE[key] = out
    return out


def _open_mode(call: ast.Call) -> str:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return "r"


def _is_open(call: ast.Call) -> bool:
    return isinstance(call.func, ast.Name) and call.func.id == "open" \
        and bool(call.args)


def _check_jsonl_appends(modules: list[Module]) -> list[Finding]:
    out = []
    for m in modules:
        if not m.rel.startswith("jepsen_tpu/"):
            continue
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                call = item.context_expr
                if not isinstance(call, ast.Call) or not _is_open(call):
                    continue
                if "a" not in _open_mode(call):
                    continue
                hints = _const_strs(m, call.args[0])
                if not any(h.endswith(".jsonl") for h in hints):
                    continue
                has_flush = has_fsync = False
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and isinstance(
                            sub.func, ast.Attribute):
                        if sub.func.attr == "flush":
                            has_flush = True
                        elif sub.func.attr in ("fsync", "sync"):
                            has_fsync = True
                if not (has_flush and has_fsync):
                    missing = []
                    if not has_flush:
                        missing.append("flush")
                    if not has_fsync:
                        missing.append("fsync")
                    out.append(m.finding(
                        "durability.fsync-missing", "error", node,
                        ".jsonl journal appended without "
                        + "+".join(missing)
                        + " inside the with block — a crash loses "
                        "acknowledged records",
                    ))
    return out


# ---------------------------------------------------------------------------
# torn-tail-unhandled
# ---------------------------------------------------------------------------


def _check_torn_tail(modules: list[Module]) -> list[Finding]:
    out = []
    for m in modules:
        if not m.rel.startswith("jepsen_tpu/") or \
                m.rel.endswith("store/format.py"):
            continue
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = None
            if isinstance(node.func, ast.Name):
                fname = node.func.id
            elif isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            if fname != "_read_block":
                continue
            target = _assign_target(m, node)
            if target is not None and _none_checked(m, node, target):
                continue
            out.append(m.finding(
                "durability.torn-tail-unhandled", "warning", node,
                "_read_block result is never checked against None — "
                "None IS the torn tail; `if rec is None: break` (or "
                "route through the truncating BlockWriter reopen)",
            ))
    return out


def _assign_target(m: Module, call: ast.Call) -> Optional[str]:
    p = m.parent(call)
    if isinstance(p, ast.Assign) and len(p.targets) == 1 and \
            isinstance(p.targets[0], ast.Name):
        return p.targets[0].id
    if isinstance(p, ast.NamedExpr) and isinstance(p.target, ast.Name):
        return p.target.id
    return None


def _none_checked(m: Module, call: ast.Call, name: str) -> bool:
    fn = m.enclosing_function(call)
    scope: ast.AST = fn if fn is not None else m.tree
    for node in ast.walk(scope):
        if isinstance(node, ast.Compare):
            parts = [node.left] + list(node.comparators)
            names = {p.id for p in parts if isinstance(p, ast.Name)}
            nones = any(isinstance(p, ast.Constant) and p.value is None
                        for p in parts)
            if name in names and nones:
                return True
        elif isinstance(node, (ast.If, ast.While)):
            t = node.test
            if isinstance(t, ast.UnaryOp) and isinstance(
                    t.op, ast.Not):
                t = t.operand
            if isinstance(t, ast.Name) and t.id == name:
                return True
    return False


# ---------------------------------------------------------------------------
# non-atomic-checkpoint
# ---------------------------------------------------------------------------


def _check_checkpoints(modules: list[Module]) -> list[Finding]:
    # Pass 1: every .json filename the repo reads back (open for read
    # + json.load in the same function, or any json.load-bearing
    # module-level reader).  Tools count as readers too — a consumer
    # is a consumer.
    read_hints: set[str] = set()
    for m in modules:
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call) and _is_open(node)):
                continue
            mode = _open_mode(node)
            if any(c in mode for c in "wax"):
                continue
            fn = m.enclosing_function(node)
            scope: ast.AST = fn if fn is not None else m.tree
            loads = any(
                isinstance(s, ast.Call)
                and isinstance(s.func, ast.Attribute)
                and s.func.attr in ("load", "loads")
                and isinstance(s.func.value, ast.Name)
                and s.func.value.id == "json"
                for s in ast.walk(scope)
            )
            if not loads:
                continue
            for h in _const_strs(m, node.args[0]):
                if _HINT_RE.search(h):
                    read_hints.add(h)

    # Pass 2: bare open('w') + json.dump writers of those files.
    out = []
    for m in modules:
        if not m.rel.startswith("jepsen_tpu/"):
            continue
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                call = item.context_expr
                if not isinstance(call, ast.Call) or not _is_open(call):
                    continue
                if "w" not in _open_mode(call):
                    continue
                dumps = any(
                    isinstance(s, ast.Call)
                    and isinstance(s.func, ast.Attribute)
                    and s.func.attr == "dump"
                    and isinstance(s.func.value, ast.Name)
                    and s.func.value.id == "json"
                    for s in ast.walk(node)
                )
                if not dumps:
                    continue
                hints = [h for h in _const_strs(m, call.args[0])
                         if _HINT_RE.search(h)]
                hit = next((h for h in hints if h in read_hints), None)
                if hit is None:
                    continue
                if any(".tmp" in h for h in _const_strs(m, call.args[0])):
                    continue
                fn = m.enclosing_function(node)
                scope: ast.AST = fn if fn is not None else m.tree
                atomic = any(
                    isinstance(s, ast.Call)
                    and isinstance(s.func, ast.Attribute)
                    and s.func.attr == "replace"
                    and isinstance(s.func.value, ast.Name)
                    and s.func.value.id == "os"
                    for s in ast.walk(scope)
                )
                if atomic:
                    continue
                out.append(m.finding(
                    "durability.non-atomic-checkpoint", "warning", node,
                    f"`{hit}` is read back elsewhere but written via "
                    "bare open('w') — a crash mid-write leaves a "
                    "half-checkpoint; write to a .tmp sibling, fsync, "
                    "then os.replace",
                ))
    return out


# ---------------------------------------------------------------------------
# block-type-collision
# ---------------------------------------------------------------------------

_BLOCK_NAME = re.compile(r"^BLOCK_[A-Z0-9_]+$")
_FRAME_NAME = re.compile(r"^F_[A-Z0-9_]+$")


def _check_block_ids(modules: list[Module]) -> list[Finding]:
    # value -> [(module, const name, line)] over the shared wire-id
    # space: every BLOCK_* definition, plus F_* frame types in the
    # checkerd protocol module.
    defs: dict[int, list[tuple[Module, str, int]]] = {}
    for m in modules:
        if not m.rel.startswith("jepsen_tpu/"):
            continue
        frames_too = m.rel.endswith("checkerd/protocol.py")
        for node in m.tree.body:
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)
                    and not isinstance(node.value.value, bool)):
                continue
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if _BLOCK_NAME.match(tgt.id) or (
                        frames_too and _FRAME_NAME.match(tgt.id)):
                    defs.setdefault(node.value.value, []).append(
                        (m, tgt.id, node.lineno))
    out = []
    for value, sites in sorted(defs.items()):
        if len(sites) < 2:
            continue
        names = ", ".join(
            f"{mm.rel}:{ln} {name}" for mm, name, ln in sites)
        mm, name, ln = sites[-1]
        out.append(Finding(
            rule="durability.block-type-collision", severity="error",
            path=mm.rel, line=ln, symbol="<module>",
            message=(
                f"wire id {value} defined more than once ({names}) — "
                "block and frame types share one id space so a frame "
                "can never be mistaken for an on-disk block"
            ),
        ))
    return out


# ---------------------------------------------------------------------------


def check(modules: list[Module]) -> list[Finding]:
    _CONSTS_CACHE.clear()
    scan = [m for m in modules if m.rel.startswith("jepsen_tpu/")]
    prog = effects.build(scan)
    out = _check_append_protocol(prog)
    out.extend(_check_jsonl_appends(scan))
    out.extend(_check_torn_tail(scan))
    out.extend(_check_checkpoints(modules))
    out.extend(_check_block_ids(scan))
    return out
