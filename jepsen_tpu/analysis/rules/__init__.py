"""Rule families.  Each module exposes ``check(modules) -> [Finding]``
plus a ``RULES`` catalog ({rule-id: (severity, one-line doc)}) that
doc/design.md's rule table, SARIF rule metadata, and the test suite
are built from."""

from . import concurrency, device, durability, protocol

FAMILIES = {
    "device": device.check,
    "concurrency": concurrency.check,
    "durability": durability.check,
    "protocol": protocol.check,
}

#: {rule-id: (severity, doc)} over every family — the catalog.  The
#: ``lint.*`` entries are synthesized by the runner itself (core.py),
#: not by a family, but belong in the catalog so SARIF metadata and
#: the docs cover them.
RULES = {
    **device.RULES,
    **concurrency.RULES,
    **durability.RULES,
    **protocol.RULES,
    "lint.suppression-missing-reason": (
        "error",
        "ignore pragma with no written reason",
    ),
    "lint.unused-suppression": (
        "error",
        "ignore pragma that matches no finding — stale, delete it",
    ),
    "lint.syntax-error": (
        "error",
        "file in the scan set that does not parse",
    ),
}
