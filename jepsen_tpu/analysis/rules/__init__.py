"""Rule families.  Each module exposes ``check(modules) -> [Finding]``
plus a ``RULES`` catalog ({rule-id: (severity, one-line doc)}) that
doc/design.md's rule table and the test suite are built from."""

from . import concurrency, device, protocol

FAMILIES = {
    "device": device.check,
    "concurrency": concurrency.check,
    "protocol": protocol.check,
}

#: {rule-id: (severity, doc)} over every family — the catalog.
RULES = {
    **device.RULES,
    **concurrency.RULES,
    **protocol.RULES,
}
