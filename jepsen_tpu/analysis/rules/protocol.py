"""Framework-protocol contracts: ledger, telemetry, checker budgets.

These rules encode the conventions the last nine PRs established but
never enforced:

``protocol.intent-before-mutation`` (error) — nemesis ``invoke`` /
``inject*`` / ``teardown`` / ``heal*`` methods must journal ledger
intent *before* touching the session (``on_nodes`` / ``exec_star`` /
``drop_all`` / ...), and heal paths must consult ``heal_guard()``
before ``.heal(...)``.  Ordering is checked lexically within the
method: the whole point of the ledger (PR 4) is that a crash between
journal and injection replays the compensator, and a mutation above
the journal line reopens the stranded-fault window the ledger closed.

``protocol.unknown-compensator`` (error) — every ``compensator=
{"type": ...}`` literal must name a ctype that ``ledger.
run_compensator`` actually dispatches on.  The registry is parsed
out of ``nemesis/ledger.py``'s AST (the ``ctype == "..."`` chain), so
adding a fault with a typo'd or not-yet-implemented compensator fails
lint instead of raising ``unknown compensator type`` at repair time —
the single worst moment to discover it.

``protocol.counter-namespace`` (warning) — literal counter / gauge /
span names must live in a declared namespace (below).  f-strings are
resolved to their leading literal prefix.  The namespace table is what
``doc/counters.md`` is generated from (``jepsen lint
--write-counters``), and ``tests/test_analysis.py`` fails when the
committed table drifts from the code.

``protocol.fleet-counter-prefix`` (error) — counters emitted from the
fleet-scoped modules (``checkerd/``, ``streaming/``,
``nemesis/search.py``) must start with one of
``telemetry.FLEET_COUNTER_PREFIXES`` (parsed from
``telemetry/__init__.py``'s AST, not imported).  A counter outside the
prefixes is silently zeroed by ``scoped_reset`` at the next run scope
— exactly the drift this cross-check exists to catch.

``protocol.check-safe-bypass`` (error) — nothing outside
``checker/core.py`` calls ``<checker>.check(test, history, opts)``
directly; everything routes through ``check_safe`` so the wall-clock
budget and valid/unknown demotion (PR 2) apply.

``protocol.swallowed-teardown`` (warning) — ``except: pass`` bodies in
teardown/close/shutdown-shaped functions.  Teardown must not raise,
but it must not eat evidence either: the accepted ones are baselined
with their justification (usually "node already dead, OSError
expected"), new ones need a ``log.debug`` or their own justification.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from ..core import Finding, Module

RULES = {
    "protocol.intent-before-mutation": (
        "error",
        "nemesis mutates the session before journaling ledger intent "
        "(or heals without heal_guard)",
    ),
    "protocol.unknown-compensator": (
        "error",
        "compensator type literal not dispatched by "
        "ledger.run_compensator",
    ),
    "protocol.counter-namespace": (
        "warning",
        "telemetry counter/gauge/span name outside the declared "
        "namespaces",
    ),
    "protocol.fleet-counter-prefix": (
        "error",
        "fleet-module counter outside FLEET_COUNTER_PREFIXES — "
        "scoped_reset will zero it",
    ),
    "protocol.check-safe-bypass": (
        "error",
        "direct checker .check() call bypasses check_safe budgets",
    ),
    "protocol.swallowed-teardown": (
        "warning",
        "except-with-only-pass in a teardown path swallows evidence",
    ),
}

#: Counter/gauge/span namespaces with an owner.  Extending this tuple
#: is the declared way to introduce a namespace — doc/counters.md is
#: generated from it plus the live scan.
DECLARED_NAMESPACES = {
    "wgl": "device checker passes (ops/, streaming/, parallel/)",
    "wgl.packed": "bit-packed uint32-lane kernel variants: block "
                  "counts, lane-word gauges, shed-packing fallbacks "
                  "(ops/packing.py, ops/wgl*.py)",
    "wgl.plan": "checking-plan compiler/executor/cache (plan/)",
    "wgl.roofline": "achieved-vs-peak roofline gauges "
                    "(telemetry/roofline.py)",
    "ingest": "history ingest path: builder append/snapshot, remote "
              "framing, daemon decode (history/, streaming/, "
              "checkerd/)",
    "checker": "checker harness (checker/)",
    "checkerd": "checker daemon fleet (checkerd/)",
    "checkerd.queue": "crash-safe queue journal (checkerd/journal.py)",
    "checkerd.overload": "overload control plane: fair queue, deadline "
                         "shed, brownout ladder (checkerd/overload.py)",
    "router": "checkerd federation router (checkerd/router.py)",
    "chaos": "fleet self-chaos harness (nemesis/selfchaos.py)",
    "nemesis": "fault injection + ledger + schedule search (nemesis/)",
    "lifecycle": "core.run phases (core.py)",
    "interpreter": "op interpreter + workers (interpreter.py)",
    "client": "workload clients (workloads/, interpreter.py)",
    "node": "node health probes (control/health.py)",
    "net": "net fault plumbing (control/remotes.py)",
    "daemon": "remote daemon supervision (control/util.py)",
    "profile": "per-pass cost profiling (telemetry/profile.py)",
    "lint": "jepsenlint itself (analysis/)",
    "bench": "bench.py sweeps",
    "forensics": "anomaly dossiers (forensics.py)",
    "slo": "SLO alert engine (telemetry/slo.py)",
    "monitor": "standing continuous verification (monitor/)",
    "monitor.live": "live-target mode: suite-backed client pool, "
                    "in-run fault windows, daemon supervision "
                    "(monitor/live.py)",
    "monitor.shed": "tee shed handling: deadline-aware backoff and "
                    "retry on F_SHED instead of in-process fallback "
                    "(monitor/loop.py)",
    "fleet": "multi-tenant fleet supervisor: tenant lifecycle, "
             "crash-loop parking, drains (monitor/fleet.py)",
    "fleet.retention": "per-tenant disk-budgeted dossier/series GC "
                       "(monitor/retention.py)",
    "alert": "alert router sink deliveries (monitor/alerts.py)",
}

#: Fleet-scoped modules: counters here survive scoped_reset only when
#: under a FLEET_COUNTER_PREFIXES prefix.
_FLEET_PATHS = ("jepsen_tpu/checkerd/", "jepsen_tpu/streaming/")
_FLEET_FILES = ("jepsen_tpu/nemesis/search.py",
                "jepsen_tpu/nemesis/selfchaos.py")

_TELEMETRY_INIT = "jepsen_tpu/telemetry/__init__.py"
_LEDGER = "jepsen_tpu/nemesis/ledger.py"
_CHECKER_CORE = "jepsen_tpu/checker/core.py"

# --------------------------------------------------------------------------
# intent-before-mutation
# --------------------------------------------------------------------------

#: Session-mutating call shapes (source-segment match, lexical).
_MUT_RE = re.compile(
    r"\.(drop_all|drop|slow|flaky|exec_star|exec|su|kill_daemon|"
    r"start_daemon|signal_daemon)\s*\(|\bon_nodes\s*\("
)
_HEAL_RE = re.compile(r"\.heal\s*\(")
_INTENT_RE = re.compile(
    r"\b(fault_ledger|ledger)\s*\.\s*(intent|note)\s*\(|\bled\.intent\s*\("
)
_GUARD_RE = re.compile(r"\bheal_guard\s*\(")
_INJECTISH = re.compile(r"^(invoke|inject\w*|teardown|heal\w*)$")


def _check_intent_order(modules: list[Module]) -> list[Finding]:
    out = []
    for m in modules:
        if not m.rel.startswith("jepsen_tpu/nemesis/"):
            continue
        if m.rel == _LEDGER:
            continue        # the ledger is the mechanism, not a client
        for fn in [n for n in ast.walk(m.tree)
                   if isinstance(n, ast.FunctionDef)
                   and _INJECTISH.match(n.name)
                   and m.enclosing_class(n) is not None]:
            first_mut: Optional[ast.Call] = None
            first_intent_line: Optional[int] = None
            first_guard_line: Optional[int] = None
            first_heal: Optional[ast.Call] = None
            # Nested defs (the on_nodes closure idiom) execute at
            # their call site, not where they are written — the
            # `on_nodes(...)` call is the mutation, so closure bodies
            # are excluded from the lexical order.
            def _own_nodes(root: ast.AST):
                for child in ast.iter_child_nodes(root):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        continue
                    yield child
                    yield from _own_nodes(child)

            for node in _own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                seg = m.seg(node)
                head = seg.split("\n")[0]
                if _INTENT_RE.search(seg):
                    if (first_intent_line is None
                            or node.lineno < first_intent_line):
                        first_intent_line = node.lineno
                if _GUARD_RE.search(head):
                    if (first_guard_line is None
                            or node.lineno < first_guard_line):
                        first_guard_line = node.lineno
                if _MUT_RE.search(head):
                    if first_mut is None or node.lineno < first_mut.lineno:
                        first_mut = node
                if _HEAL_RE.search(head):
                    if first_heal is None or node.lineno < first_heal.lineno:
                        first_heal = node
            if first_mut is not None:
                if first_intent_line is None:
                    out.append(m.finding(
                        "protocol.intent-before-mutation", "error",
                        first_mut,
                        f"`{m.seg(first_mut).split(chr(10))[0][:60]}` "
                        "mutates the session but this method never "
                        "journals ledger intent — a crash here strands "
                        "the fault with no compensator to replay",
                    ))
                elif first_mut.lineno < first_intent_line:
                    out.append(m.finding(
                        "protocol.intent-before-mutation", "error",
                        first_mut,
                        f"session mutation at line {first_mut.lineno} "
                        f"precedes the first ledger intent at line "
                        f"{first_intent_line} — journal intent first so "
                        "a crash between them is replayable",
                    ))
            if first_heal is not None and (
                    first_guard_line is None
                    or first_guard_line > first_heal.lineno):
                out.append(m.finding(
                    "protocol.intent-before-mutation", "error",
                    first_heal,
                    "heal path runs without consulting heal_guard() "
                    "first — abandon-mode crash tests will double-heal",
                ))
    return out


# --------------------------------------------------------------------------
# unknown-compensator
# --------------------------------------------------------------------------


def _registry_from_ledger(modules: list[Module]) -> Optional[set[str]]:
    """The ctypes run_compensator dispatches on, parsed from its AST:
    every ``ctype == "x"`` comparison plus the intent() default."""
    ledger = next((m for m in modules if m.rel == _LEDGER), None)
    if ledger is None:
        return None
    ctypes: set[str] = set()
    for node in ast.walk(ledger.tree):
        if (isinstance(node, ast.Compare)
                and isinstance(node.left, ast.Name)
                and node.left.id == "ctype"
                and len(node.ops) == 1
                and isinstance(node.ops[0], ast.Eq)
                and isinstance(node.comparators[0], ast.Constant)
                and isinstance(node.comparators[0].value, str)):
            ctypes.add(node.comparators[0].value)
    # intent() defaults a missing compensator to {"type": "unreplayable"}.
    ctypes.add("unreplayable")
    return ctypes or None


def _check_compensators(modules: list[Module]) -> list[Finding]:
    registry = _registry_from_ledger(modules)
    if registry is None:
        return []            # fixture batch without the ledger: no-op
    out = []
    for m in modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg != "compensator":
                    continue
                d = kw.value
                if not isinstance(d, ast.Dict):
                    continue
                for k, v in zip(d.keys, d.values):
                    if (isinstance(k, ast.Constant) and k.value == "type"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)
                            and v.value not in registry):
                        out.append(m.finding(
                            "protocol.unknown-compensator", "error", v,
                            f"compensator type {v.value!r} is not "
                            f"dispatched by ledger.run_compensator "
                            f"(knows: {', '.join(sorted(registry))}) — "
                            "repair would raise at the worst moment",
                        ))
    return out


# --------------------------------------------------------------------------
# counter scan (shared by the namespace rules and doc/counters.md)
# --------------------------------------------------------------------------

_EMIT_ATTRS = {"count": "counter", "gauge": "gauge", "span": "span"}


def _literal_name(node: ast.AST, m: Module) -> Optional[str]:
    """Counter-name argument as text: plain literals verbatim,
    f-strings as ``prefix.{expr}`` with the leading literal kept.
    None for non-literal names (variables)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                parts.append("{" + (m.seg(v.value) or "…") + "}")
        text = "".join(parts)
        return text if text and not text.startswith("{") else None
    return None


def scan_counters(modules: list[Module]) -> list[dict]:
    """Every literal telemetry emission in the scan set:
    ``{name, kind, path, line, subsystem}``.  The protocol rules, the
    generated doc/counters.md, and the drift test all consume this."""
    out = []
    for m in modules:
        is_telemetry_pkg = m.rel.startswith("jepsen_tpu/telemetry/")
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            f = node.func
            kind = None
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "telemetry"
                    and f.attr in _EMIT_ATTRS):
                kind = _EMIT_ATTRS[f.attr]
            elif (isinstance(f, ast.Name) and is_telemetry_pkg
                    and f.id in ("_count", "count", "gauge", "span")):
                kind = _EMIT_ATTRS[f.id.lstrip("_")]
            if kind is None:
                continue
            name = _literal_name(node.args[0], m)
            if not name:        # "" is the shared no-op span — skip
                continue
            parts = m.rel.split("/")
            subsystem = (parts[1] if len(parts) > 2
                         else parts[-1].removesuffix(".py"))
            out.append({
                "name": name, "kind": kind, "path": m.rel,
                "line": node.lineno, "subsystem": subsystem,
                "node": node, "module": m,
            })
    out.sort(key=lambda e: (e["name"], e["path"], e["line"]))
    return out


def _fleet_prefixes(modules: list[Module]) -> Optional[tuple[str, ...]]:
    """FLEET_COUNTER_PREFIXES parsed out of telemetry/__init__.py —
    never imported, so lint sees exactly what is committed."""
    tele = next((m for m in modules if m.rel == _TELEMETRY_INIT), None)
    if tele is None:
        return None
    for node in ast.walk(tele.tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "FLEET_COUNTER_PREFIXES"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            vals = [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
            return tuple(vals)
    return None


def declared_namespace(name: str) -> Optional[str]:
    """The longest declared dotted prefix of a counter name, or None.
    Sub-namespaces (e.g. wgl.plan under wgl) resolve to the most
    specific owner, so doc/counters.md files them under the right
    subsystem."""
    parts = name.split(".")
    for i in range(len(parts), 0, -1):
        ns = ".".join(parts[:i])
        if ns in DECLARED_NAMESPACES:
            return ns
    return None


def _check_counters(modules: list[Module]) -> list[Finding]:
    out = []
    emissions = scan_counters(modules)
    for e in emissions:
        ns = declared_namespace(e["name"])
        if ns is None:
            m: Module = e["module"]
            out.append(m.finding(
                "protocol.counter-namespace", "warning", e["node"],
                f"{e['kind']} name {e['name']!r} is outside the "
                f"declared namespaces "
                f"({', '.join(sorted(DECLARED_NAMESPACES))}) — add the "
                "namespace to DECLARED_NAMESPACES + doc/counters.md or "
                "rename",
            ))
    prefixes = _fleet_prefixes(modules)
    if prefixes:
        for e in emissions:
            if e["kind"] != "counter":
                continue
            rel = e["path"]
            if not (rel.startswith(_FLEET_PATHS) or rel in _FLEET_FILES):
                continue
            if not e["name"].startswith(prefixes):
                m = e["module"]
                out.append(m.finding(
                    "protocol.fleet-counter-prefix", "error", e["node"],
                    f"counter {e['name']!r} in fleet module {rel} "
                    f"does not match FLEET_COUNTER_PREFIXES "
                    f"{prefixes} — telemetry.scoped_reset will zero it "
                    "at the next run scope",
                ))
    return out


# --------------------------------------------------------------------------
# check-safe bypass
# --------------------------------------------------------------------------


def _check_bypass(modules: list[Module]) -> list[Finding]:
    out = []
    for m in modules:
        if m.rel == _CHECKER_CORE:
            continue        # check_safe's own call site lives here
        for node in ast.walk(m.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "check"
                    and len(node.args) >= 2):
                out.append(m.finding(
                    "protocol.check-safe-bypass", "error", node,
                    f"`{m.seg(node)[:60]}` calls a checker directly — "
                    "route through checker.check_safe so the "
                    "wall-clock budget and valid:unknown demotion "
                    "apply",
                ))
    return out


# --------------------------------------------------------------------------
# swallowed teardown exceptions
# --------------------------------------------------------------------------

_TEARDOWNISH = re.compile(
    r"teardown|cleanup|shutdown|__exit__|__del__|^(close|stop|kill)$"
)


def _check_swallowed(modules: list[Module]) -> list[Finding]:
    out = []
    for m in modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            body = [s for s in node.body]
            if not all(isinstance(s, ast.Pass) for s in body):
                continue
            fn = m.enclosing_function(node)
            if fn is None or not _TEARDOWNISH.search(fn.name):
                continue
            exc = m.seg(node.type) if node.type is not None else "Exception"
            out.append(m.finding(
                "protocol.swallowed-teardown", "warning", node,
                f"except {exc}: pass in teardown path `{fn.name}` "
                "swallows the evidence — log.debug it or baseline with "
                "a written justification",
            ))
    return out


# --------------------------------------------------------------------------
# doc/counters.md generation
# --------------------------------------------------------------------------


def render_counters_md(modules: list[Module]) -> str:
    """The canonical counter table.  Regenerate with
    ``jepsen lint --write-counters``; tests/test_analysis.py fails when
    the committed file drifts from this output."""
    emissions = scan_counters(modules)
    by_name: dict[tuple[str, str], list[dict]] = {}
    for e in emissions:
        by_name.setdefault((e["name"], e["kind"]), []).append(e)
    lines = [
        "# Telemetry counters, gauges, and spans",
        "",
        "Generated by `jepsen lint --write-counters` from the live "
        "counter scan",
        "(`jepsen_tpu/analysis/rules/protocol.py:scan_counters`). "
        "Do not edit by",
        "hand — `tests/test_analysis.py::test_counters_doc_drift` "
        "fails when this",
        "table and the code disagree.",
        "",
        "## Namespaces",
        "",
        "| namespace | owner |",
        "|---|---|",
    ]
    for ns, owner in sorted(DECLARED_NAMESPACES.items()):
        lines.append(f"| `{ns}.` | {owner} |")
    lines += [
        "",
        "Fleet-scoped prefixes (survive `telemetry.scoped_reset`): "
        + ", ".join(f"`{p}`" for p in (_fleet_prefixes(modules) or ())),
        "",
        "## Emissions",
        "",
        "| name | kind | subsystem | emitted at |",
        "|---|---|---|---|",
    ]
    for (name, kind), es in sorted(by_name.items()):
        sites = ", ".join(
            f"{e['path']}:{e['line']}" for e in es[:3]
        ) + (f" (+{len(es) - 3} more)" if len(es) > 3 else "")
        subsystems = ", ".join(sorted({e["subsystem"] for e in es}))
        lines.append(f"| `{name}` | {kind} | {subsystems} | {sites} |")
    lines.append("")
    return "\n".join(lines)


def doc_counter_names(text: str) -> set[str]:
    """Counter names committed in doc/counters.md — the drift test
    compares these against the live scan."""
    out = set()
    for line in text.splitlines():
        m = re.match(r"\|\s*`([^`]+)`\s*\|\s*(counter|gauge|span)\s*\|",
                     line)
        if m:
            out.add(m.group(1))
    return out


def check(modules: list[Module]) -> list[Finding]:
    scan = [m for m in modules if m.rel.startswith("jepsen_tpu/")]
    out = _check_intent_order(scan)
    out.extend(_check_compensators(scan))
    out.extend(_check_counters(scan))
    out.extend(_check_bypass(scan))
    out.extend(_check_swallowed(scan))
    return out
