"""Device/JAX hygiene rules.

The bug classes here are the ones this repo has actually shipped and
hand-caught:

  * ``device.unguarded-narrowing`` — ``.astype(np.int32)`` (or any
    narrower integer) with no *dominating* range guard.  The exemplar
    is ``wgl_witness._plan_blocks``: it raises ``OverflowError`` when
    the 64-bit timeline maximum reaches int32 INF *before* casting,
    because a wrapped ``inv`` silently corrupts the barrier order
    (ADVICE round 5 caught this by hand; this rule is that reviewer).
    A cast counts as guarded when the enclosing function asserts/bails
    on a bound before it, or the cast source is already clamped
    (``np.minimum`` / ``.clip``) or inherently bounded (comparison
    masks, ``searchsorted`` ranks, ``arange``).
  * ``device.host-sync-in-jit`` — ``.item()`` / ``np.asarray`` /
    ``block_until_ready`` / ``float()`` on a traced value inside a
    jit/pmap-traced function: either a trace-time crash or a silent
    device→host sync per call.
  * ``device.np-in-jit`` — ``np.`` *compute* calls inside traced
    functions (dtype/constant accessors are fine): numpy ops trace as
    constants and pin the value on host.
  * ``device.host-sync-in-capture`` — ``.item()`` / ``np.asarray`` /
    ``block_until_ready`` inside a loop inside a ``profile.capture``
    block.  Per-iteration syncs are the classic hidden serializer in a
    device pipeline; the witness search's one-scalar-per-block sync is
    the *intended* shape and gets baselined, anything new must argue.
  * ``device.uncaptured-device-call`` — a function in ``ops/`` or
    ``streaming/`` that demonstrably drives devices (calls a jitted
    function, ``device_put``, ``block_until_ready``) but is neither
    under a ``profile.capture`` itself nor only reachable from covered
    functions: a pass invisible to the PR 9 cost profiles and the
    ROADMAP-3 cost model's training set.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Finding, Module

RULES = {
    "device.unguarded-narrowing": (
        "warning",
        ".astype to a narrower int with no dominating range guard",
    ),
    "device.host-sync-in-jit": (
        "error",
        "host sync (.item/np.asarray/block_until_ready/float) inside a "
        "jit-traced function",
    ),
    "device.np-in-jit": (
        "warning",
        "np.* compute call inside a jit-traced function (use jnp)",
    ),
    "device.host-sync-in-capture": (
        "advice",
        "per-iteration host sync inside a profile.capture hot loop",
    ),
    "device.uncaptured-device-call": (
        "warning",
        "device-driving function in ops//streaming/ not under "
        "profile.capture",
    ),
}

#: Integer dtypes narrower than the int64 indices/timestamps the
#: history pipeline carries.
_NARROW_INTS = {
    "int32", "int16", "int8", "uint32", "uint16", "uint8",
}

#: Tokens whose presence in a preceding raise/assert marks the cast
#: range-checked (the _plan_blocks idiom and its relatives).
_GUARD_TOKENS = ("INF", "iinfo", "int32", "overflow", "Overflow")

#: Call names in the cast source that already bound the value.
_CLAMP_TOKENS = ("minimum(", ".clip(", "clip(", "searchsorted(",
                 "arange(", "argsort(", "nonzero(", "cumsum(")

_HOST_SYNC_ATTRS = {"item", "block_until_ready", "tolist"}


def _dtype_of_astype(call: ast.Call) -> Optional[str]:
    """"int32" when `call` is `<x>.astype(<narrow int dtype>)`."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "astype" and call.args):
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Attribute):          # np.int32 / jnp.int32
        name = arg.attr
    elif isinstance(arg, ast.Name):             # bare int32
        name = arg.id
    elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        name = arg.value                        # .astype("int32")
    else:
        return None
    return name if name in _NARROW_INTS else None


def _is_bounded_value(m: Module, value: ast.AST) -> bool:
    """Casts of masks/ranks/clamped values can't overflow int32."""
    if isinstance(value, (ast.Compare, ast.BoolOp)):
        return True
    seg = m.seg(value)
    return any(tok in seg for tok in _CLAMP_TOKENS)


def _has_dominating_guard(m: Module, fn: ast.FunctionDef,
                          cast_line: int) -> bool:
    """A raise/assert before the cast whose text talks about the int32
    bound — the lexical stand-in for dominance that matches how every
    real guard in this repo is written (straight-line prologue checks)."""
    for node in ast.walk(fn):
        if getattr(node, "lineno", 1 << 30) >= cast_line:
            continue
        if isinstance(node, (ast.Raise, ast.Assert)):
            seg = m.seg(node)
            if any(tok in seg for tok in _GUARD_TOKENS):
                return True
        # Delegated guards: a bare call statement whose name says it
        # range-checks (`_require_i32(arr)`) is the same idiom hoisted
        # into a helper.
        if (isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)):
            name = m.seg(node.value.func).lower()
            if any(t in name for t in ("i32", "int32", "overflow")) \
                    and any(t in name for t in
                            ("require", "guard", "check", "assert")):
                return True
    return False


def _traced_functions(m: Module) -> set[ast.FunctionDef]:
    """FunctionDefs traced by jax: decorated with jit/pmap (directly or
    via partial), or passed to a jax.jit/jax.pmap call anywhere in the
    module."""
    out: set[ast.FunctionDef] = set()
    by_name: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(m.tree):
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, []).append(node)
            for dec in node.decorator_list:
                seg = m.seg(dec)
                if "jit" in seg or "pmap" in seg or "shard_map" in seg:
                    out.add(node)
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call):
            continue
        seg = m.seg(node.func)
        if seg.split("(")[0] not in (
            "jax.jit", "jit", "jax.pmap", "pmap"
        ):
            continue
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name):
                for fn in by_name.get(arg.id, []):
                    out.add(fn)
    return out


def _in_any(m: Module, node: ast.AST,
            fns: set[ast.FunctionDef]) -> Optional[ast.FunctionDef]:
    f = m.enclosing_function(node)
    while f is not None:
        if f in fns:
            return f
        f = m.enclosing_function(f)
    return None


def _check_narrowing(m: Module) -> list[Finding]:
    out = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call):
            continue
        dtype = _dtype_of_astype(node)
        if dtype is None:
            continue
        value = node.func.value  # type: ignore[union-attr]
        if _is_bounded_value(m, value):
            continue
        fn = m.enclosing_function(node)
        if fn is not None and _has_dominating_guard(m, fn, node.lineno):
            continue
        out.append(m.finding(
            "device.unguarded-narrowing", "warning", node,
            f".astype({dtype}) narrows a 64-bit value with no "
            f"dominating range guard; assert/bail on the max first "
            f"(the wgl_witness._plan_blocks idiom) or clamp with "
            f"np.minimum",
        ))
    return out


def _check_jit_bodies(m: Module) -> list[Finding]:
    out = []
    traced = _traced_functions(m)
    if not traced:
        return out
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = _in_any(m, node, traced)
        if fn is None:
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in _HOST_SYNC_ATTRS:
                out.append(m.finding(
                    "device.host-sync-in-jit", "error", node,
                    f".{func.attr}() inside jit-traced `{fn.name}` "
                    f"forces a device→host sync (or a trace error); "
                    f"keep the value on device",
                ))
            elif (isinstance(func.value, ast.Name)
                  and func.value.id == "np"):
                if func.attr in ("asarray", "array"):
                    out.append(m.finding(
                        "device.host-sync-in-jit", "error", node,
                        f"np.{func.attr}() inside jit-traced "
                        f"`{fn.name}` pulls the tracer to host",
                    ))
                elif not _NP_DTYPE_OK(func.attr):
                    out.append(m.finding(
                        "device.np-in-jit", "warning", node,
                        f"np.{func.attr}() inside jit-traced "
                        f"`{fn.name}` computes on host and traces as "
                        f"a constant; use jnp.{func.attr}",
                    ))
        elif isinstance(func, ast.Name) and func.id in ("float", "int"):
            if node.args and not isinstance(node.args[0], ast.Constant):
                out.append(m.finding(
                    "device.host-sync-in-jit", "error", node,
                    f"{func.id}() on a traced value inside "
                    f"`{fn.name}` concretizes the tracer",
                ))
    return out


def _NP_DTYPE_OK(attr: str) -> bool:
    return attr in {
        "int8", "int16", "int32", "int64", "uint8", "uint16",
        "uint32", "uint64", "float16", "float32", "float64",
        "bool_", "iinfo", "finfo", "dtype", "ndarray", "integer",
        "floating", "generic", "shape", "bfloat16",
    }


def _capture_withs(m: Module) -> list[ast.With]:
    out = []
    for node in ast.walk(m.tree):
        if isinstance(node, ast.With):
            for item in node.items:
                if "profile.capture" in m.seg(item.context_expr):
                    out.append(node)
                    break
    return out


def _check_capture_loops(m: Module) -> list[Finding]:
    out = []
    for w in _capture_withs(m):
        for loop in ast.walk(w):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                name = None
                if isinstance(func, ast.Attribute):
                    if func.attr in ("item", "block_until_ready"):
                        name = f".{func.attr}()"
                    elif (isinstance(func.value, ast.Name)
                          and func.value.id == "np"
                          and func.attr == "asarray"):
                        name = "np.asarray()"
                if name:
                    out.append(m.finding(
                        "device.host-sync-in-capture", "advice", node,
                        f"{name} per loop iteration inside a "
                        f"profile.capture hot path serializes the "
                        f"device pipeline; batch the sync or justify "
                        f"it (sequential-by-design searches are)",
                    ))
    return out


#: Source markers that say "this function drives a device from host".
_DEVICE_MARKERS = ("block_until_ready", "device_put(", ".addressable_",
                   "jax.block_until_ready")


def _check_uncaptured(modules: list[Module]) -> list[Finding]:
    """Repo-wide coverage fixpoint: a device-driving ops//streaming/
    function is fine when it runs under profile.capture itself or is
    only reachable from covered callers — *in any scanned module*
    (check_wgl_witness is covered by wgl.py's `capture("witness")`
    around the call, one module over)."""
    from .concurrency import _import_map

    targets = [m for m in modules
               if m.rel.startswith(("jepsen_tpu/ops/",
                                    "jepsen_tpu/streaming/"))]
    if not targets:
        return []

    # Every function in the scan set is a potential caller; module-level
    # functions in target modules are the flag candidates.
    fn_info: dict[tuple[str, str], dict] = {}
    traced_by_mod: dict[str, set[ast.FunctionDef]] = {}
    for m in modules:
        traced_by_mod[m.name] = _traced_functions(m)
        traced_names = {f.name for f in traced_by_mod[m.name]}
        for fn in [n for n in ast.walk(m.tree)
                   if isinstance(n, ast.FunctionDef)]:
            seg = m.seg(fn)
            drives = any(tok in seg for tok in _DEVICE_MARKERS)
            if not drives:
                # Calling a locally jitted function executes on device.
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Name)
                            and node.func.id in traced_names):
                        drives = True
                        break
            fn_info[(m.name, fn.name)] = {
                "m": m, "fn": fn, "drives": drives,
                "captures": "profile.capture" in seg,
                "callers": set(), "called_at_toplevel": False,
            }

    # Call graph across modules: bare names resolve in the caller's
    # module, `alias.f(...)` through its import map.
    for m in modules:
        imports = _import_map(m)
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            key = None
            if isinstance(f, ast.Name):
                tgt = imports.get(f.id)
                if tgt and "." in tgt:          # from x import f
                    key = tuple(tgt.rsplit(".", 1))
                else:
                    key = (m.name, f.id)
            elif (isinstance(f, ast.Attribute)
                  and isinstance(f.value, ast.Name)):
                base = imports.get(f.value.id)
                if base:
                    key = (base, f.attr)
            if key is None or key not in fn_info:
                continue
            caller = m.enclosing_function(node)
            if caller is None:
                fn_info[key]["called_at_toplevel"] = True
            elif (m.name, caller.name) in fn_info \
                    and fn_info[(m.name, caller.name)]["fn"] is caller:
                fn_info[key]["callers"].add((m.name, caller.name))
            else:
                # Nested/method caller: count its own capture state.
                if "profile.capture" in m.seg(caller):
                    fn_info[key]["callers"].add(("<covered>", ""))
                else:
                    fn_info[key]["called_at_toplevel"] = True

    # Greatest fixpoint: start with everything covered and strip any
    # function that doesn't capture and has an uncovered entry path.
    # (A least fixpoint can never cover recursion — check_wgl_witness's
    # _retry_on_scan/_retry_smaller cycle — even when every external
    # caller runs under capture.)
    covered = set(fn_info) | {("<covered>", "")}
    changed = True
    while changed:
        changed = False
        for key, i in fn_info.items():
            if key not in covered or i["captures"]:
                continue
            if (i["called_at_toplevel"] or not i["callers"]
                    or not (i["callers"] <= covered)):
                covered.discard(key)
                changed = True

    out = []
    for m in targets:
        traced = traced_by_mod[m.name]
        for (mod, _name), i in fn_info.items():
            if mod != m.name or i["m"] is not m:
                continue
            if i["fn"] in traced:   # the kernel itself, not the driver
                continue
            if i["drives"] and (mod, i["fn"].name) not in covered:
                out.append(m.finding(
                    "device.uncaptured-device-call", "warning", i["fn"],
                    f"`{i['fn'].name}` drives devices but neither runs "
                    f"under profile.capture nor is only called from "
                    f"covered functions — its cost is invisible to the "
                    f"per-pass profile store (telemetry/profile.py)",
                ))
    return out


def check(modules: list[Module]) -> list[Finding]:
    out: list[Finding] = []
    scan = [m for m in modules if m.rel.startswith("jepsen_tpu/")]
    for m in scan:
        out.extend(_check_narrowing(m))
        out.extend(_check_jit_bodies(m))
        out.extend(_check_capture_loops(m))
    out.extend(_check_uncaptured(scan))
    return out
