"""Device mesh construction for checker sharding.

The reference's parallel axis is JVM threads under `bounded-pmap`
(independent.clj:346-367); ours is a `jax.sharding.Mesh` whose "keys"
axis carries independent per-key searches.  One mesh axis suffices:
per-key WGL has no cross-key communication, so any physical topology
(v5e-8 ring, multi-host DCN) works — XLA never inserts collectives into
the hot loop.
"""

from __future__ import annotations

from typing import Optional

_mesh_cache: dict = {}


def default_mesh(n_devices: Optional[int] = None, axis: str = "keys"):
    """A 1-D mesh over (the first n) local devices.  Memoized: device
    kernel caches key on mesh identity, so repeated checks must see the
    same Mesh object."""
    key = (n_devices, axis)
    mesh = _mesh_cache.get(key)
    if mesh is not None:
        return mesh

    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    mesh = Mesh(np.asarray(devs), (axis,))
    _mesh_cache[key] = mesh
    return mesh


def multihost_init(coordinator: str, num_processes: int,
                   process_id: int) -> None:
    """Joins this process to a multi-host JAX cluster (the DCN analog
    of the reference's control-plane fan-out; its NCCL/MPI role is
    played by XLA collectives here).  After it returns,
    `jax.devices()` is the GLOBAL device list, so `default_mesh()`
    spans every host with no further changes.

    Mesh-axis guidance for multi-host runs:
      * the "keys" axis (per-key batched WGL, elle screens) has NO
        cross-key communication — shard it across hosts freely; the
        only DCN traffic is the initial scatter and final gather.
      * the "beam" axis (frontier sharding of ONE search,
        ops/wgl.py) all-gathers candidates every round — keep that
        mesh within one host's ICI domain (pass the local slice of
        jax.devices() to Mesh) or the collective rides DCN every
        barrier block.

    Call BEFORE any other JAX use: jax.distributed.initialize refuses
    an already-initialized backend, so there is no late-join path (a
    prior default_mesh()/jax.devices() call makes this raise).
    Exercised in CI by tests/test_multihost.py: two fresh processes
    join one cluster over localhost, build the global mesh, and run a
    cross-process psum.  The call delegates to
    jax.distributed.initialize, which blocks until all
    `num_processes` join."""
    if not coordinator or ":" not in coordinator:
        raise ValueError(
            f"coordinator must be host:port, got {coordinator!r}"
        )
    if not (0 <= process_id < num_processes):
        raise ValueError(
            f"process_id {process_id} outside [0, {num_processes})"
        )
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def shard_map_compat():
    """(shard_map, replication-check kwargs) across jax versions: the
    stable `jax.shard_map` (>= 0.8) renamed check_rep -> check_vma.
    Checking is disabled either way — checker outputs are fully
    sharded or psum-replicated by construction.  Single shim for the
    three shard_map call sites (wgl, wgl_batched, scc)."""
    try:
        from jax import shard_map  # jax >= 0.8

        return shard_map, {"check_vma": False}
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

        return shard_map, {"check_rep": False}


def checker_mesh(test: Optional[dict] = None):
    """The mesh a checker should use: the test map's "mesh" entry if set,
    else all local devices, else None for single-device."""
    if test and test.get("mesh") is not None:
        return test["mesh"]
    import jax

    if len(jax.devices()) > 1:
        return default_mesh()
    return None
