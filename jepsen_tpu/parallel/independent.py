"""Per-key independent checking — `jepsen.independent`, TPU-sharded.

The reference lifts single-key workloads to many keys: op values become
`(k, v)` tuples, the history is split into per-key subhistories, and each
key is checked independently under a bounded thread pool
(/root/reference/jepsen/src/jepsen/independent.clj:27, :259-325,
:327-377).  This module keeps the same host API but re-designs the
compute: when the base checker is a packed-model linearizability check,
all keys are packed into one padded batch and decided by a single
vmapped + shard_mapped device search (ops/wgl_batched.py) — per-key data
parallelism across the TPU mesh instead of a JVM thread pool.

Generator-side lifting (`sequential_generator`/`concurrent_generator`,
independent.clj:37-257) lives in jepsen_tpu.generator.independent, next
to the generator machinery it builds on.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Callable, Iterable, NamedTuple, Optional

from .. import telemetry
from ..telemetry import profile
from ..checker.core import Checker, check_safe, merge_valid
from ..checker.linearizable import Linearizable
from ..history.core import History, Op
from ..utils import bounded_pmap


class KV(NamedTuple):
    """A `[key value]` tuple op payload (independent.clj:18-35).  A
    distinct type — not a plain tuple — so multi-argument payloads like
    cas `(old, new)` aren't mistaken for keyed values."""

    key: Any
    value: Any

    def __repr__(self) -> str:
        return f"[{self.key!r} {self.value!r}]"


def kv(key: Any, value: Any) -> KV:
    return KV(key, value)


def is_kv(v: Any) -> bool:
    return isinstance(v, KV)


def tuple_gen(key: Any, value: Any) -> KV:
    """Alias mirroring `independent/tuple`."""
    return KV(key, value)


# ---------------------------------------------------------------------------
# Settle-verdict memoization
# ---------------------------------------------------------------------------

#: digest -> sanitized settle verdict.  Bounded LRU: planted-violation
#: and replayed-nemesis workloads repeat the SAME bad subhistory across
#: keys and across checks; each distinct one is decided once.
_SETTLE_MEMO_MAX = 2048
_settle_memo: "OrderedDict[str, dict]" = OrderedDict()
_settle_memo_lock = threading.Lock()

#: Result fields that cite positions in ONE key's slice of the full
#: history (src_index-based certificates, rendered artifacts).  A memo
#: entry is shared by textually identical subhistories at DIFFERENT
#: positions, so these never ride along.
_POSITIONAL_FIELDS = ("final-configs", "crashed-op", "counterexample-file")


def _settle_digest(p, pm) -> str:
    """Packed-history digest keying the settle memo.  Sound for verdict
    sharing because the packed check is purely code-level: the verdict
    is a function of the (inv, ret, status, f, a0, a1) columns, the
    model's step semantics (named), and its initial state — regardless
    of which concrete values the interner codes denote.  src_index is
    deliberately excluded: identical subhistories at different offsets
    in the full history must collide."""
    import numpy as np

    h = hashlib.sha256()
    h.update(
        f"{pm.name}|{tuple(int(v) for v in pm.init_state)}|"
        f"{pm.state_width}".encode()
    )
    for col in (p.inv, p.ret, p.status, p.f, p.a0, p.a1):
        h.update(np.ascontiguousarray(col).tobytes())
    return h.hexdigest()


def _online_digest(sess, pm, sub) -> Optional[str]:
    """Digest of one key's subhistory in the STREAMING SESSION's code
    space, for `sess.consume`.  Register encoders intern values in
    first-seen order, so a freshly compiled PackedModel assigns
    different codes than the session's (which interned in journal
    order) — the caller's own pack can never match the digest the
    session recorded.  Re-packing with the session's encoder INSTANCE
    reuses its interner, reproducing the exact byte stream the proof
    was recorded against.  Returns None (never consume) when the
    session checked a different model shape, or the re-pack fails."""
    spm = sess.pm
    if (spm.name != pm.name
            or tuple(int(v) for v in spm.init_state)
            != tuple(int(v) for v in pm.init_state)
            or spm.state_width != pm.state_width):
        return None
    try:
        from ..history.packed import pack_history

        return _settle_digest(pack_history(sub, spm.encode), spm)
    except Exception:  # noqa: BLE001 — fail closed to the post-hoc path
        return None


def _sanitize_settle(res: dict) -> dict:
    """A memo-shareable copy of a settle result: verdict and metadata,
    minus the positional certificate fields."""
    return {k: v for k, v in res.items() if k not in _POSITIONAL_FIELDS}


def _memo_get(digest: str) -> Optional[dict]:
    with _settle_memo_lock:
        r = _settle_memo.get(digest)
        if r is not None:
            _settle_memo.move_to_end(digest)
            return dict(r)
    return None


def clear_settle_memo() -> None:
    """Empties the cross-call settle memo.  Benchmarks and perf tests
    call this between reps so every rep measures the COLD settling
    ladder (screens + search), not a memo replay."""
    with _settle_memo_lock:
        _settle_memo.clear()


def invalidate_settle_memo(digest: str) -> None:
    """Evicts ONE digest's memoized verdict.  The streaming checker
    (jepsen_tpu/streaming/) memoizes a key's proof the moment the key
    goes quiet; when the key later takes more ops, that entry describes
    a mid-run prefix that no finished history will ever equal — it is
    dead weight at best and, if a recheck re-records the key, a stale
    twin of the live verdict.  Eviction is keyed so an online recheck
    drops exactly its own superseded entry instead of dumping every
    other run's cohort (which a full clear_settle_memo would)."""
    with _settle_memo_lock:
        _settle_memo.pop(digest, None)


def _memo_put(digest: str, res: dict) -> None:
    # Only decisive verdicts are worth remembering: an "unknown" is a
    # budget artifact of THIS call, and a later call with more budget
    # must not inherit it.
    if res.get("valid") not in (True, False):
        return
    with _settle_memo_lock:
        _settle_memo[digest] = _sanitize_settle(res)
        _settle_memo.move_to_end(digest)
        while len(_settle_memo) > _SETTLE_MEMO_MAX:
            _settle_memo.popitem(last=False)


def history_keys(h: History) -> list:
    """All keys in KV-valued ops, in first-seen order
    (independent.clj:259-269)."""
    seen: dict[Any, None] = {}
    for o in h:
        if is_kv(o.value):
            seen.setdefault(o.value.key, None)
    return list(seen)


def subhistories(h: History) -> dict[Any, History]:
    """Splits a history into per-key histories, unwrapping KV values
    (independent.clj:271-325).  Completions that lost their KV payload
    (e.g. an :info with value None) inherit the key of their process's
    pending invocation.  Ops keep their original indices, so per-key
    results can cite positions in the full history."""
    per_key: dict[Any, list[Op]] = {}
    pending: dict[Any, Any] = {}  # process -> key
    # Hot loop (every op of every test history passes through here):
    # one isinstance per op, one dict lookup per key, bound methods
    # hoisted — measured 2x over the straightforward form at 20k ops.
    pop = pending.pop
    for o in h:
        val = o.value
        if isinstance(val, KV):
            k = val.key
            if o.is_invoke:
                pending[o.process] = k
            else:
                pop(o.process, None)
            v = val.value
        elif not o.is_invoke and o.process in pending:
            k = pop(o.process)
            v = val
        else:
            continue
        lst = per_key.get(k)
        if lst is None:
            per_key[k] = lst = []
        lst.append(o.replace(value=v))
    return {k: History(ops, reindex=False) for k, ops in per_key.items()}


class IndependentChecker(Checker):
    """Applies `base` to each key's subhistory and merges validity
    (independent.clj:327-377).

    Fast path: if `base` is a Linearizable checker whose model packs to
    int32 form, every key is packed and decided in one batched device
    search sharded over the mesh; only keys the beam search could not
    settle fall back to the exact CPU search (still sound).  Any other
    checker runs per-key under bounded_pmap, like the reference.
    """

    def __init__(self, base: Checker, *, bound: Optional[int] = None,
                 streaming: bool = True):
        self.base = base
        self.bound = bound
        #: Consume online verdicts from a run's StreamingSession
        #: (jepsen_tpu/streaming/) when one is present in the test map.
        #: Off means every key settles post-hoc even on streamed runs.
        self.streaming = streaming

    def check(self, test: dict, history: History, opts: dict) -> dict:
        subs = subhistories(history)
        keys = list(subs)
        if not keys:
            return {"valid": True, "results": {}, "key-count": 0}

        from ..ops import degrade

        results: dict[Any, dict]
        # The capture collects degradation-ladder steps taken by the
        # shared tiers (stream witness / batched BFS) that run on this
        # thread, outside any single key's Linearizable.check.
        with degrade.capture() as steps:
            if isinstance(self.base, Linearizable):
                results = self._check_linearizable(test, subs, opts)
            else:
                rs = bounded_pmap(
                    lambda k: check_safe(
                        self.base, test, subs[k], {**opts, "history_key": k}
                    ),
                    keys,
                    bound=self.bound,
                )
                results = dict(zip(keys, rs))

        valid = merge_valid(r.get("valid") for r in results.values())
        failures = [k for k, r in results.items() if r.get("valid") is False]
        self._write_key_artifacts(opts, subs, results)
        out = {
            "valid": valid,
            "key-count": len(keys),
            "failures": failures[:32],
            "failure-count": len(failures),
            "results": results,
        }
        if steps:
            out["degradations"] = steps
        return out

    #: Per-key artifact budget: failed keys always write; passing keys
    #: only up to this many (the reference writes every key's dir,
    #: independent.clj:355-364, but per-key workloads here can carry
    #: tens of thousands of keys).
    MAX_OK_KEY_DIRS = 256

    def _write_key_artifacts(self, opts: dict, subs: dict,
                             results: dict) -> None:
        """store/<test>/independent/<key>/{results.json,history.txt}
        per key, like the reference's per-key dirs.  Failures never
        raise: a side-output must not change the verdict."""
        import json
        import logging
        import os

        import hashlib

        from ..utils import sanitize_path_part

        directory = (opts or {}).get("dir")
        if not directory:
            return
        log = logging.getLogger(__name__)

        def jsonable_keys(x):
            # json.dump coerces dict VALUES via default=, never KEYS;
            # skipkeys would silently drop diagnostic entries.
            if isinstance(x, dict):
                return {
                    k if isinstance(k, str) else repr(k):
                        jsonable_keys(v)
                    for k, v in x.items()
                }
            if isinstance(x, (list, tuple)):
                return [jsonable_keys(v) for v in x]
            return x

        ok_written = 0
        used: set = set()
        for k, res in results.items():
            # Only fully-passing keys count against the budget:
            # False AND "unknown" verdicts are exactly the ones a
            # maintainer must inspect, so they always write.
            budgeted = res.get("valid") is True
            if budgeted and ok_written >= self.MAX_OK_KEY_DIRS:
                continue
            safe = sanitize_path_part(k)[:80]
            if safe in used:
                # Disambiguate truncation collisions with a stable
                # digest of the full key, keeping names bounded.
                digest = hashlib.sha1(
                    repr(k).encode()
                ).hexdigest()[:10]
                safe = f"{safe[:69]}-{digest}"
            used.add(safe)
            # Per-key isolation: one key's write failure (quota,
            # unserializable value, hostile op repr) must neither
            # skip later keys nor — via check_safe — replace the
            # computed verdict with "unknown".
            try:
                d = os.path.join(directory, "independent", safe)
                os.makedirs(d, exist_ok=True)
                with open(os.path.join(d, "results.json"), "w") as f:
                    json.dump(jsonable_keys(res), f, indent=2,
                              default=repr)
                with open(os.path.join(d, "history.txt"), "w",
                          errors="replace") as f:
                    for o in subs.get(k, ()):
                        f.write(str(o) + "\n")
                if budgeted:
                    ok_written += 1  # only successful writes consume budget
            except Exception as e:  # noqa: BLE001 — side output only
                log.warning(
                    "could not write artifacts for key %r: %r", k, e
                )

    # -- batched device path ------------------------------------------------

    def _check_linearizable(
        self, test: dict, subs: dict[Any, History], opts: dict
    ) -> dict[Any, dict]:
        lin = self.base
        model = lin.model or test.get("model")
        keys = list(subs)
        try:
            pm = model.packed()
        except (NotImplementedError, AttributeError):
            pm = None
        if pm is None or lin.algorithm in ("wgl", "linear", "cpu",
                                           "event", "settle"):
            rs = bounded_pmap(
                lambda k: check_safe(
                    lin, test, subs[k], {**opts, "history_key": k}
                ),
                keys,
                bound=self.bound,
            )
            return dict(zip(keys, rs))

        from ..history.packed import pack_history
        from .mesh import checker_mesh

        all_packs = {}
        unpackable = []
        for k in keys:
            try:
                p = pack_history(subs[k], pm.encode)
            except ValueError:
                # e.g. an indeterminate dequeue: no packed form for
                # this key — the single-key checker falls back to the
                # host-model search itself.
                unpackable.append(k)
                continue
            if pm.validate_packed is not None and \
                    pm.validate_packed(p) is not None:
                unpackable.append(k)
                continue
            all_packs[k] = p

        # Compiled-plan route (jepsen_tpu/plan/): the same ladder —
        # online consume, long-key split, stream witness, settle
        # pipeline — expressed as a pass DAG and run by the plan
        # executor, with cost-model knobs and (opt-in) persistent
        # memoization.  JEPSEN_PLAN=0 keeps the hand-wired ladder
        # below, which the parity suites diff against.
        from ..plan import enabled as _plan_enabled

        if _plan_enabled():
            try:
                from ..plan.compiler import run_cohort

                return run_cohort(
                    self, test, subs,
                    [k for k in keys if k in all_packs],
                    unpackable, all_packs, model, pm, lin, opts,
                )
            except Exception:  # noqa: BLE001 — legacy ladder is the net
                telemetry.count("wgl.plan.fallback")
                import logging

                logging.getLogger(__name__).warning(
                    "plan executor failed; using the legacy ladder",
                    exc_info=True,
                )

        results_unpack: dict[Any, dict] = {}
        if unpackable:
            rs = bounded_pmap(
                lambda k: check_safe(
                    lin, test, subs[k], {**opts, "history_key": k}
                ),
                unpackable,
                bound=self.bound,
            )
            results_unpack = dict(zip(unpackable, rs))
            keys = [k for k in keys if k in all_packs]
            if not keys:
                return results_unpack
        # Online verdicts first: a streaming session (jepsen_tpu/
        # streaming/) may have proven keys while the run was still
        # generating ops.  A verdict is consumed only when the key's
        # re-packed digest equals the one recorded at proof time, so a
        # key that changed after its proof settles from scratch here.
        results_online: dict[Any, dict] = {}
        sess = (test or {}).get("streaming-session")
        if self.streaming and sess is not None:
            for k in keys:
                d = _online_digest(sess, pm, subs[k])
                r = sess.consume(k, d) if d is not None else None
                if r is not None:
                    results_online[k] = r
            if results_online:
                keys = [k for k in keys if k not in results_online]
                if telemetry.enabled():
                    telemetry.count("wgl.settle.online-proven",
                                    len(results_online))
            if not keys:
                return {**results_unpack, **results_online}
        # Long keys skip the batched kernel entirely: its compile/pad
        # cost scales with the LONGEST key, and the single-history
        # witness-first path (check_wgl_device) is built for length.
        long_keys = [k for k in keys if all_packs[k].n > 2000]
        keys = [k for k in keys if all_packs[k].n <= 2000]
        results_long: dict[Any, dict] = {}
        if long_keys:
            long_chk = Linearizable(
                model, "wgl-tpu",
                beam=lin.beam, max_beam=lin.max_beam,
                time_limit_s=lin.time_limit_s,
                max_configs=lin.max_configs,
            )
            rs = bounded_pmap(
                lambda k: check_safe(
                    long_chk, test, subs[k], {**opts, "history_key": k}
                ),
                long_keys,
                bound=self.bound,
            )
            results_long = dict(zip(long_keys, rs))
            if not keys:
                return {**results_unpack, **results_online,
                        **results_long}

        # Stream-witness first (ops/wgl_stream.py): ALL keys ride one
        # concatenated barrier stream through the witness engine —
        # measured ~20x the batched-BFS rate on the 200x100 shape
        # (VERDICT r4 'weak' #3).  Keys it proves are done; the rest
        # (rare) fall through to the exact engines below.
        from ..ops.wgl_stream import check_wgl_witness_stream

        # One budget for the whole tier ladder: the stream's elapsed
        # time is deducted before the batched search and the per-key
        # CPU settles, so the caller's time_limit_s bounds the whole
        # check, not each tier separately.
        import time as _time

        t_tiers = _time.monotonic()

        def budget_left():
            if lin.time_limit_s is None:
                return None
            return max(1.0, lin.time_limit_s
                       - (_time.monotonic() - t_tiers))

        results_stream: dict[Any, dict] = {}
        try:
            stream_v = check_wgl_witness_stream(
                [all_packs[k] for k in keys], pm,
                time_limit_s=lin.time_limit_s,
            )
        except Exception:  # noqa: BLE001 — sound fallback exists
            import logging

            logging.getLogger(__name__).warning(
                "stream witness failed; falling back to the batched "
                "search for all keys", exc_info=True,
            )
            stream_v = [None] * len(keys)
        for k, v in zip(keys, stream_v):
            if v is True:
                results_stream[k] = {
                    "valid": True,
                    "algorithm": "wgl-tpu-stream",
                    "configs-explored": int(all_packs[k].n_ok),
                }
        keys = [k for k, v in zip(keys, stream_v) if v is not True]
        if telemetry.enabled():
            telemetry.count("wgl.settle.stream-proven",
                            len(results_stream))
        if not keys:
            return {**results_unpack, **results_online, **results_long,
                    **results_stream}

        results: dict[Any, dict] = {
            **results_unpack, **results_online, **results_long,
            **results_stream,
        }
        results.update(self._settle_cohort(
            keys, all_packs, subs, model, pm, lin, test, opts,
            budget_left, checker_mesh(test),
        ))
        return results

    #: Detail budget for keys the batched kernel already proved invalid
    #: EXACTLY: the CPU pass is reporting-only there (the verdict
    #: stands), so it gets a small slice, not the whole tier budget.
    REFUTED_DETAIL_BUDGET_S = 10.0

    def _settle_cohort(
        self, cohort_keys, all_packs, subs, model, pm, lin, test, opts,
        budget_left, mesh,
    ) -> dict[Any, dict]:
        """Decides the cohort the stream witness left unproven, under
        the shared tier budget.  The pipeline, cheapest tier first:

          1. **memo** — identical subhistories (packed digest,
             src_index excluded) replay a prior decisive verdict; one
             representative per digest runs the rest of the pipeline
             and fans its sanitized verdict out.
          2. **refutation screens** (checker/refute.py) — host numpy,
             O(n log n), exact when they fire.  They classify the
             planted-violation/bad-read families in milliseconds, so
             those keys never enter the batched BFS (proving `invalid`
             there means EXHAUSTING the per-key search — the expensive
             direction).
          3. **batched BFS** (ops/wgl_batched.py) — screen survivors
             only, vmapped over the mesh; True is proven, False is an
             exact device refutation.
          4. **parallel CPU settle** — the remainder (screen-refuted
             keys for certificate detail, device-refuted keys for a
             small-budget detail pass, unknowns for the exact engine)
             under bounded_pmap, every slice carved from the same
             tier budget."""
        # One cost record for the whole settle pipeline; the
        # chained span hook folds the batched children's
        # compile/execute time into this record too.
        with profile.capture(
            "settle", keys=len(cohort_keys),
            ops=int(sum(all_packs[k].n for k in cohort_keys)),
        ) as _ps:
            import logging

            from ..checker.refute import check_refute
            from ..ops.wgl_batched import check_wgl_batched

            log = logging.getLogger(__name__)
            groups: "OrderedDict[str, list]" = OrderedDict()
            for k in cohort_keys:
                d = _settle_digest(all_packs[k], pm)
                groups.setdefault(d, []).append(k)

            group_result: dict[str, dict] = {}
            reps: list[str] = []
            for d in groups:
                hit = _memo_get(d)
                if hit is not None:
                    group_result[d] = hit
                else:
                    reps.append(d)
            n_memo = sum(len(groups[d]) for d in group_result)

            # Screen classifier: which representatives are provably invalid
            # without any search.  Sound-when-fires; None = no opinion.
            def screen_one(d: str):
                b = budget_left()
                try:
                    return check_refute(
                        all_packs[groups[d][0]], pm,
                        time_limit_s=30.0 if b is None else min(b, 30.0),
                    )
                except Exception:  # noqa: BLE001 — a screen bug must not
                    log.warning("refutation screen failed for key %r",
                                groups[d][0], exc_info=True)
                    return None  # change a verdict; the search tiers decide

            screened = dict(zip(reps, bounded_pmap(screen_one, reps,
                                                   bound=self.bound)))
            refuted_reps = [d for d in reps if screened[d] is not None]
            survivors = [d for d in reps if screened[d] is None]

            # Batched frontier BFS over the screen survivors.  Start the
            # beam SMALL: the overflow-retry ladder re-batches only the
            # keys that overflowed, so typical short per-key histories
            # settle in the cheap narrow passes and only the rare wide key
            # climbs.  Measured (200 keys x 100 ops, 8-dev CPU mesh,
            # warm): start 32 = 1.8 s vs start 256 = 16.3 s — the
            # per-step frontier work scales with the start width for
            # EVERY key, paid even by keys the narrowest pass would
            # settle.  32 is the kernel's smallest beam bucket
            # (check_wgl_batched's _bucket lo=32; anything lower rounds
            # up to it).  Worst case (all keys climb to max) the
            # geometric ladder costs ~2x the final pass — bounded, and
            # far rarer than the all-keys-small common case.
            device_verdict: dict[str, Any] = {d: None for d in reps}
            device_explored: dict[str, int] = {d: 0 for d in reps}
            n_batched_proven = 0
            if survivors:
                batch = check_wgl_batched(
                    [all_packs[groups[d][0]] for d in survivors],
                    pm,
                    beam=min(lin.beam, 32),
                    max_beam=max(lin.max_beam, lin.beam),
                    mesh=mesh,
                    time_limit_s=budget_left(),
                )
                for i, d in enumerate(survivors):
                    device_verdict[d] = batch.valid[i]
                    device_explored[d] = int(batch.explored[i])
                    if batch.valid[i] is True:
                        group_result[d] = {
                            "valid": True,
                            "algorithm": "wgl-tpu-batched",
                            "configs-explored": int(batch.explored[i]),
                        }
                        _memo_put(d, group_result[d])
                        n_batched_proven += 1

            # Parallel CPU settle of everything still without a result:
            # screen-refuted reps (the "settle" algorithm re-fires the
            # cheap screen and renders the certificate), device-refuted
            # reps (small detail slice; the exact device verdict stands if
            # the slice expires), and device unknowns (exact engine).
            todo = [d for d in reps if d not in group_result]

            def settle_one(d: str) -> dict:
                k = groups[d][0]
                dv = device_verdict[d]
                budget = budget_left()
                if dv is False:
                    budget = (self.REFUTED_DETAIL_BUDGET_S if budget is None
                              else min(budget, self.REFUTED_DETAIL_BUDGET_S))
                single = Linearizable(
                    model,
                    "settle",
                    time_limit_s=budget,
                    max_configs=lin.max_configs,
                )
                r = check_safe(single, test, subs[k],
                               {**opts, "history_key": k})
                if dv is not None:
                    r["device-verdict"] = dv
                if dv is False:
                    if r.get("valid") == "unknown":
                        # The detail slice expired; the device refutation
                        # is exact (search exhausted without overflow) and
                        # settles the verdict on its own.
                        r = {
                            "valid": False,
                            "algorithm": "wgl-tpu-batched",
                            "configs-explored": device_explored[d],
                            "device-verdict": False,
                        }
                    elif r.get("valid") is True:
                        # Exact engines disagreeing is a checker bug, not a
                        # history property; surface it loudly and keep the
                        # CPU verdict (parity with per-key exact checking).
                        log.error(
                            "device/CPU verdict mismatch on key %r: batched"
                            " kernel proved invalid, exact engine proved "
                            "valid — keeping the CPU verdict", k,
                        )
                return r

            n_screen = n_device_refuted = n_cpu = 0
            screen_fired = set(refuted_reps)
            for d, r in zip(todo, bounded_pmap(settle_one, todo,
                                               bound=self.bound)):
                group_result[d] = r
                _memo_put(d, r)
                if device_verdict[d] is False:
                    n_device_refuted += 1
                elif d in screen_fired:
                    n_screen += 1
                else:
                    n_cpu += 1

            # Fan every group's verdict out: the representative carries the
            # full result (positional certificate fields cite ITS slice of
            # the history); other members share the sanitized verdict.
            settled: dict[Any, dict] = {}
            live = set(reps)
            for d, members in groups.items():
                r = group_result.get(d)
                if r is None:  # defensive: unreachable
                    continue
                if d in live:
                    settled[members[0]] = r
                    extra = members[1:]
                    n_memo += len(extra)
                else:
                    extra = members  # cross-call memo hit: all share
                for k2 in extra:
                    shared = _sanitize_settle(r)
                    shared["memo-hit"] = True
                    settled[k2] = shared
            if telemetry.enabled():
                telemetry.count("wgl.settle.screen-refuted", n_screen)
                telemetry.count("wgl.settle.batched-proven",
                                n_batched_proven)
                telemetry.count("wgl.settle.batched-refuted",
                                n_device_refuted)
                telemetry.count("wgl.settle.cpu-settled", n_cpu)
                telemetry.count("wgl.settle.memo-hit", n_memo)
            _ps.outcome = {
                "screen-refuted": n_screen,
                "batched-proven": n_batched_proven,
                "batched-refuted": n_device_refuted,
                "cpu-settled": n_cpu,
                "memo-hit": n_memo,
            }
            return settled


def independent_checker(base: Checker, **kw: Any) -> IndependentChecker:
    return IndependentChecker(base, **kw)
