"""Per-key independent checking — `jepsen.independent`, TPU-sharded.

The reference lifts single-key workloads to many keys: op values become
`(k, v)` tuples, the history is split into per-key subhistories, and each
key is checked independently under a bounded thread pool
(/root/reference/jepsen/src/jepsen/independent.clj:27, :259-325,
:327-377).  This module keeps the same host API but re-designs the
compute: when the base checker is a packed-model linearizability check,
all keys are packed into one padded batch and decided by a single
vmapped + shard_mapped device search (ops/wgl_batched.py) — per-key data
parallelism across the TPU mesh instead of a JVM thread pool.

Generator-side lifting (`sequential_generator`/`concurrent_generator`,
independent.clj:37-257) lives in jepsen_tpu.generator.independent, next
to the generator machinery it builds on.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, NamedTuple, Optional

from ..checker.core import Checker, check_safe, merge_valid
from ..checker.linearizable import Linearizable
from ..history.core import History, Op
from ..utils import bounded_pmap


class KV(NamedTuple):
    """A `[key value]` tuple op payload (independent.clj:18-35).  A
    distinct type — not a plain tuple — so multi-argument payloads like
    cas `(old, new)` aren't mistaken for keyed values."""

    key: Any
    value: Any

    def __repr__(self) -> str:
        return f"[{self.key!r} {self.value!r}]"


def kv(key: Any, value: Any) -> KV:
    return KV(key, value)


def is_kv(v: Any) -> bool:
    return isinstance(v, KV)


def tuple_gen(key: Any, value: Any) -> KV:
    """Alias mirroring `independent/tuple`."""
    return KV(key, value)


def history_keys(h: History) -> list:
    """All keys in KV-valued ops, in first-seen order
    (independent.clj:259-269)."""
    seen: dict[Any, None] = {}
    for o in h:
        if is_kv(o.value):
            seen.setdefault(o.value.key, None)
    return list(seen)


def subhistories(h: History) -> dict[Any, History]:
    """Splits a history into per-key histories, unwrapping KV values
    (independent.clj:271-325).  Completions that lost their KV payload
    (e.g. an :info with value None) inherit the key of their process's
    pending invocation.  Ops keep their original indices, so per-key
    results can cite positions in the full history."""
    per_key: dict[Any, list[Op]] = {}
    pending: dict[Any, Any] = {}  # process -> key
    # Hot loop (every op of every test history passes through here):
    # one isinstance per op, one dict lookup per key, bound methods
    # hoisted — measured 2x over the straightforward form at 20k ops.
    pop = pending.pop
    for o in h:
        val = o.value
        if isinstance(val, KV):
            k = val.key
            if o.is_invoke:
                pending[o.process] = k
            else:
                pop(o.process, None)
            v = val.value
        elif not o.is_invoke and o.process in pending:
            k = pop(o.process)
            v = val
        else:
            continue
        lst = per_key.get(k)
        if lst is None:
            per_key[k] = lst = []
        lst.append(o.replace(value=v))
    return {k: History(ops, reindex=False) for k, ops in per_key.items()}


class IndependentChecker(Checker):
    """Applies `base` to each key's subhistory and merges validity
    (independent.clj:327-377).

    Fast path: if `base` is a Linearizable checker whose model packs to
    int32 form, every key is packed and decided in one batched device
    search sharded over the mesh; only keys the beam search could not
    settle fall back to the exact CPU search (still sound).  Any other
    checker runs per-key under bounded_pmap, like the reference.
    """

    def __init__(self, base: Checker, *, bound: Optional[int] = None):
        self.base = base
        self.bound = bound

    def check(self, test: dict, history: History, opts: dict) -> dict:
        subs = subhistories(history)
        keys = list(subs)
        if not keys:
            return {"valid": True, "results": {}, "key-count": 0}

        from ..ops import degrade

        results: dict[Any, dict]
        # The capture collects degradation-ladder steps taken by the
        # shared tiers (stream witness / batched BFS) that run on this
        # thread, outside any single key's Linearizable.check.
        with degrade.capture() as steps:
            if isinstance(self.base, Linearizable):
                results = self._check_linearizable(test, subs, opts)
            else:
                rs = bounded_pmap(
                    lambda k: check_safe(
                        self.base, test, subs[k], {**opts, "history_key": k}
                    ),
                    keys,
                    bound=self.bound,
                )
                results = dict(zip(keys, rs))

        valid = merge_valid(r.get("valid") for r in results.values())
        failures = [k for k, r in results.items() if r.get("valid") is False]
        self._write_key_artifacts(opts, subs, results)
        out = {
            "valid": valid,
            "key-count": len(keys),
            "failures": failures[:32],
            "failure-count": len(failures),
            "results": results,
        }
        if steps:
            out["degradations"] = steps
        return out

    #: Per-key artifact budget: failed keys always write; passing keys
    #: only up to this many (the reference writes every key's dir,
    #: independent.clj:355-364, but per-key workloads here can carry
    #: tens of thousands of keys).
    MAX_OK_KEY_DIRS = 256

    def _write_key_artifacts(self, opts: dict, subs: dict,
                             results: dict) -> None:
        """store/<test>/independent/<key>/{results.json,history.txt}
        per key, like the reference's per-key dirs.  Failures never
        raise: a side-output must not change the verdict."""
        import json
        import logging
        import os

        import hashlib

        from ..utils import sanitize_path_part

        directory = (opts or {}).get("dir")
        if not directory:
            return
        log = logging.getLogger(__name__)

        def jsonable_keys(x):
            # json.dump coerces dict VALUES via default=, never KEYS;
            # skipkeys would silently drop diagnostic entries.
            if isinstance(x, dict):
                return {
                    k if isinstance(k, str) else repr(k):
                        jsonable_keys(v)
                    for k, v in x.items()
                }
            if isinstance(x, (list, tuple)):
                return [jsonable_keys(v) for v in x]
            return x

        ok_written = 0
        used: set = set()
        for k, res in results.items():
            # Only fully-passing keys count against the budget:
            # False AND "unknown" verdicts are exactly the ones a
            # maintainer must inspect, so they always write.
            budgeted = res.get("valid") is True
            if budgeted and ok_written >= self.MAX_OK_KEY_DIRS:
                continue
            safe = sanitize_path_part(k)[:80]
            if safe in used:
                # Disambiguate truncation collisions with a stable
                # digest of the full key, keeping names bounded.
                digest = hashlib.sha1(
                    repr(k).encode()
                ).hexdigest()[:10]
                safe = f"{safe[:69]}-{digest}"
            used.add(safe)
            # Per-key isolation: one key's write failure (quota,
            # unserializable value, hostile op repr) must neither
            # skip later keys nor — via check_safe — replace the
            # computed verdict with "unknown".
            try:
                d = os.path.join(directory, "independent", safe)
                os.makedirs(d, exist_ok=True)
                with open(os.path.join(d, "results.json"), "w") as f:
                    json.dump(jsonable_keys(res), f, indent=2,
                              default=repr)
                with open(os.path.join(d, "history.txt"), "w",
                          errors="replace") as f:
                    for o in subs.get(k, ()):
                        f.write(str(o) + "\n")
                if budgeted:
                    ok_written += 1  # only successful writes consume budget
            except Exception as e:  # noqa: BLE001 — side output only
                log.warning(
                    "could not write artifacts for key %r: %r", k, e
                )

    # -- batched device path ------------------------------------------------

    def _check_linearizable(
        self, test: dict, subs: dict[Any, History], opts: dict
    ) -> dict[Any, dict]:
        lin = self.base
        model = lin.model or test.get("model")
        keys = list(subs)
        try:
            pm = model.packed()
        except (NotImplementedError, AttributeError):
            pm = None
        if pm is None or lin.algorithm in ("wgl", "linear", "cpu", "event"):
            rs = bounded_pmap(
                lambda k: check_safe(
                    lin, test, subs[k], {**opts, "history_key": k}
                ),
                keys,
                bound=self.bound,
            )
            return dict(zip(keys, rs))

        from ..history.packed import pack_history
        from ..ops.wgl_batched import check_wgl_batched
        from .mesh import checker_mesh

        all_packs = {}
        unpackable = []
        for k in keys:
            try:
                p = pack_history(subs[k], pm.encode)
            except ValueError:
                # e.g. an indeterminate dequeue: no packed form for
                # this key — the single-key checker falls back to the
                # host-model search itself.
                unpackable.append(k)
                continue
            if pm.validate_packed is not None and \
                    pm.validate_packed(p) is not None:
                unpackable.append(k)
                continue
            all_packs[k] = p
        results_unpack: dict[Any, dict] = {}
        if unpackable:
            rs = bounded_pmap(
                lambda k: check_safe(
                    lin, test, subs[k], {**opts, "history_key": k}
                ),
                unpackable,
                bound=self.bound,
            )
            results_unpack = dict(zip(unpackable, rs))
            keys = [k for k in keys if k in all_packs]
            if not keys:
                return results_unpack
        # Long keys skip the batched kernel entirely: its compile/pad
        # cost scales with the LONGEST key, and the single-history
        # witness-first path (check_wgl_device) is built for length.
        long_keys = [k for k in keys if all_packs[k].n > 2000]
        keys = [k for k in keys if all_packs[k].n <= 2000]
        results_long: dict[Any, dict] = {}
        if long_keys:
            long_chk = Linearizable(
                model, "wgl-tpu",
                beam=lin.beam, max_beam=lin.max_beam,
                time_limit_s=lin.time_limit_s,
                max_configs=lin.max_configs,
            )
            rs = bounded_pmap(
                lambda k: check_safe(
                    long_chk, test, subs[k], {**opts, "history_key": k}
                ),
                long_keys,
                bound=self.bound,
            )
            results_long = dict(zip(long_keys, rs))
            if not keys:
                return {**results_unpack, **results_long}

        # Stream-witness first (ops/wgl_stream.py): ALL keys ride one
        # concatenated barrier stream through the witness engine —
        # measured ~20x the batched-BFS rate on the 200x100 shape
        # (VERDICT r4 'weak' #3).  Keys it proves are done; the rest
        # (rare) fall through to the exact engines below.
        from ..ops.wgl_stream import check_wgl_witness_stream

        # One budget for the whole tier ladder: the stream's elapsed
        # time is deducted before the batched search and the per-key
        # CPU settles, so the caller's time_limit_s bounds the whole
        # check, not each tier separately.
        import time as _time

        t_tiers = _time.monotonic()

        def budget_left():
            if lin.time_limit_s is None:
                return None
            return max(1.0, lin.time_limit_s
                       - (_time.monotonic() - t_tiers))

        results_stream: dict[Any, dict] = {}
        try:
            stream_v = check_wgl_witness_stream(
                [all_packs[k] for k in keys], pm,
                time_limit_s=lin.time_limit_s,
            )
        except Exception:  # noqa: BLE001 — sound fallback exists
            import logging

            logging.getLogger(__name__).warning(
                "stream witness failed; falling back to the batched "
                "search for all keys", exc_info=True,
            )
            stream_v = [None] * len(keys)
        for k, v in zip(keys, stream_v):
            if v is True:
                results_stream[k] = {
                    "valid": True,
                    "algorithm": "wgl-tpu-stream",
                    "configs-explored": int(all_packs[k].n_ok),
                }
        keys = [k for k, v in zip(keys, stream_v) if v is not True]
        if not keys:
            return {**results_unpack, **results_long, **results_stream}

        packs = [all_packs[k] for k in keys]
        mesh = checker_mesh(test)
        # Start the beam SMALL: the overflow-retry ladder re-batches
        # only the keys that overflowed, so typical short per-key
        # histories settle in the cheap narrow passes and only the
        # rare wide key climbs.  Measured (200 keys x 100 ops, 8-dev
        # CPU mesh, warm): start 32 = 1.8 s vs start 256 = 16.3 s —
        # the per-step frontier work scales with the start width for
        # EVERY key, paid even by keys the narrowest pass would
        # settle.  32 is the kernel's smallest beam bucket
        # (check_wgl_batched's _bucket lo=32; anything lower rounds
        # up to it).  Worst case (all keys climb to max) the
        # geometric ladder costs ~2x the final pass — bounded, and
        # far rarer than the all-keys-small common case.
        batch = check_wgl_batched(
            packs,
            pm,
            beam=min(lin.beam, 32),
            max_beam=max(lin.max_beam, lin.beam),
            mesh=mesh,
            time_limit_s=budget_left(),
        )

        results: dict[Any, dict] = {
            **results_unpack, **results_long, **results_stream,
        }
        for i, k in enumerate(keys):
            v = batch.valid[i]
            if v is True:
                results[k] = {
                    "valid": True,
                    "algorithm": "wgl-tpu-batched",
                    "configs-explored": int(batch.explored[i]),
                }
            else:
                # invalid or unknown: settle on CPU for the exact verdict
                # and the counterexample detail (per-key histories are
                # short; checker.clj renders these via knossos.linear.report).
                # "cpu" auto-routes info-heavy keys to the event-walk
                # engine, which settles cases the memoized DFS cannot.
                single = Linearizable(
                    model,
                    "cpu",
                    time_limit_s=budget_left(),
                    max_configs=lin.max_configs,
                )
                r = check_safe(single, test, subs[k], {**opts, "history_key": k})
                r["algorithm"] = "wgl-tpu-batched+cpu"
                r["device-verdict"] = v
                results[k] = r
        return results


def independent_checker(base: Checker, **kw: Any) -> IndependentChecker:
    return IndependentChecker(base, **kw)
