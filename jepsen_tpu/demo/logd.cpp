// logd: a deliberately small append-only partitioned log — the
// "kafka-shaped" demo system driven by workloads/kafka.py, playing the
// role real Kafka plays for the reference's hardest checker
// (jepsen/src/jepsen/tests/kafka.clj:24-180 built its workload against
// real brokers; this server gives that checker REAL anomalies to find
// instead of injected ones).
//
// Partitions are named keys; producers SEND values which get
// monotonically-increasing offsets; consumers POLL from a position
// they track themselves (Kafka consumer semantics); COMMIT appends a
// transaction marker that burns one offset per touched partition the
// way Kafka's commit markers do — so polls legitimately see offset
// gaps.
//
// Client protocol (one request per line):
//   SEND <k> <v>             -> OFF <offset>
//   POLL <k> <pos> <limit>   -> MSGS <next_pos> [<off>:<v> ...]
//   DEQ <k> <limit>          -> DEQD [<v> ...] | EMPTY
//   COMMIT <k1,k2,...>       -> OK
//   PING                     -> PONG
//
// DEQ is the queue face of the log: a SERVER-side shared cursor per
// key (one consumer group) hands each record to exactly one caller.
// The cursor lives in process memory only, deliberately: a restart
// rewinds it to zero and redelivers — classic at-least-once, which
// the total-queue checker reports as duplicates but does NOT convict.
// What it does convict is records that can never come out at all:
// in write-behind mode a SIGKILL drops acked-but-unflushed SENDs from
// the WAL, and no amount of redelivery brings those back.
//
// The interesting physics — why kills produce checker-visible
// anomalies: SEND acknowledges from memory, and a flusher thread
// write()s the tail to <dir>/wal.log every --flush-ms (default 50).
// SIGKILL inside that window loses acknowledged records; on restart
// the log reloads from the WAL, so the next SEND REUSES the lost
// offsets — the checker then finds lost writes (acked values nobody
// can ever poll) and inconsistent offsets (two values observed at one
// (key, offset)).  --sync flushes inline before acking: the control
// group, which survives kills cleanly.
//
// Fresh implementation for this framework's demo suite (the kvdb/repkv
// mold, demo/kvdb/kvdb.cpp).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <fstream>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

std::mutex g_mu;
// Value "" is a transaction marker / burned offset: it occupies an
// offset but is never delivered to polls.
std::map<std::string, std::vector<std::string>> g_logs;
// Shared consumer-group cursors for DEQ — in-memory only (see header
// comment: rewind-on-restart is the at-least-once demo physics).
std::map<std::string, size_t> g_cursors;
std::deque<std::string> g_pending;  // WAL lines not yet written
std::condition_variable g_flush_cv;
bool g_sync = false;
int g_flush_ms = 50;
std::string g_wal_path;

// Drains pending WAL lines to disk.  fflush moves them to the page
// cache: enough to survive a SIGKILL of this process (the fault the
// suite injects), deliberately not an fsync (machine crashes are out
// of scope for the demo).
void flush_pending_locked(FILE* wal) {
  while (!g_pending.empty()) {
    fputs(g_pending.front().c_str(), wal);
    g_pending.pop_front();
  }
  fflush(wal);
}

void flusher_loop(FILE* wal) {
  std::unique_lock<std::mutex> l(g_mu);
  while (true) {
    g_flush_cv.wait_for(l, std::chrono::milliseconds(g_flush_ms));
    flush_pending_locked(wal);
  }
}

FILE* g_wal = nullptr;

void reload() {
  std::ifstream in(g_wal_path);
  std::string line;
  while (std::getline(in, line)) {
    if (line.size() < 2) continue;
    std::istringstream is(line);
    std::string tag, k, v;
    is >> tag >> k;
    if (tag == "D") {
      std::getline(is, v);
      if (!v.empty() && v[0] == ' ') v.erase(0, 1);
      g_logs[k].push_back(v);
    } else if (tag == "M") {
      g_logs[k].push_back("");
    }
  }
}

void serve(int fd) {
  FILE* rf = fdopen(fd, "r");
  if (!rf) { close(fd); return; }
  char buf[4096];
  while (fgets(buf, sizeof(buf), rf)) {
    std::istringstream in(buf);
    std::string cmd;
    in >> cmd;
    std::string resp;
    if (cmd == "PING") {
      resp = "PONG";
    } else if (cmd == "SEND") {
      std::string k, v;
      in >> k >> v;
      std::lock_guard<std::mutex> l(g_mu);
      auto& log = g_logs[k];
      size_t off = log.size();
      log.push_back(v);
      g_pending.push_back("D " + k + " " + v + "\n");
      // async mode: the TIMER alone flushes — waking the flusher per
      // send would close the durability window this demo exists for.
      if (g_sync) flush_pending_locked(g_wal);
      resp = "OFF " + std::to_string(off);
    } else if (cmd == "COMMIT") {
      std::string ks;
      in >> ks;
      std::lock_guard<std::mutex> l(g_mu);
      std::stringstream s(ks);
      std::string k;
      while (std::getline(s, k, ',')) {
        if (k.empty()) continue;
        g_logs[k].push_back("");
        g_pending.push_back("M " + k + "\n");
      }
      if (g_sync) flush_pending_locked(g_wal);
      resp = "OK";
    } else if (cmd == "DEQ") {
      std::string k;
      size_t limit = 1;
      in >> k >> limit;
      if (limit == 0) limit = 1;
      std::lock_guard<std::mutex> l(g_mu);
      auto& log = g_logs[k];
      size_t& cur = g_cursors[k];
      std::ostringstream out;
      size_t n = 0;
      while (cur < log.size() && n < limit) {
        if (!log[cur].empty()) {  // markers burn offsets, not values
          out << " " << log[cur];
          n++;
        }
        cur++;
      }
      resp = n ? "DEQD" + out.str() : "EMPTY";
    } else if (cmd == "POLL") {
      std::string k;
      size_t pos = 0, limit = 32;
      in >> k >> pos >> limit;
      std::lock_guard<std::mutex> l(g_mu);
      auto& log = g_logs[k];
      std::ostringstream out;
      size_t n = 0;
      while (pos < log.size() && n < limit) {
        if (!log[pos].empty()) {
          out << " " << pos << ":" << log[pos];
          n++;
        }
        pos++;
      }
      resp = "MSGS " + std::to_string(pos) + out.str();
    } else {
      resp = "ERR badcmd";
    }
    resp += "\n";
    if (write(fd, resp.data(), resp.size()) != (ssize_t)resp.size())
      break;
  }
  fclose(rf);
}

}  // namespace

int main(int argc, char** argv) {
  int port = 7500;
  std::string dir = "/tmp/logd";
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() { return std::string(argv[++i]); };
    if (a == "--port") port = atoi(next().c_str());
    else if (a == "--dir") dir = next();
    else if (a == "--flush-ms") g_flush_ms = atoi(next().c_str());
    else if (a == "--sync") g_sync = true;
  }
  signal(SIGPIPE, SIG_IGN);
  mkdir(dir.c_str(), 0755);
  g_wal_path = dir + "/wal.log";
  reload();
  g_wal = fopen(g_wal_path.c_str(), "a");
  if (!g_wal) { perror("wal"); return 1; }
  if (!g_sync) std::thread(flusher_loop, g_wal).detach();

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  if (bind(srv, (sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  listen(srv, 64);
  size_t keys = 0, records = 0;
  for (auto& e : g_logs) { keys++; records += e.second.size(); }
  fprintf(stderr, "logd on 127.0.0.1:%d dir=%s (%s, flush %dms) "
          "reloaded %zu keys / %zu records\n",
          port, dir.c_str(), g_sync ? "sync" : "async", g_flush_ms,
          keys, records);
  while (true) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    int nd = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
    std::thread(serve, fd).detach();
  }
}
