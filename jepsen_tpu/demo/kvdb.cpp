// kvdb: a deliberately small networked key-value store used as the
// framework's demo "system under test" — the role zookeeper plays for
// the reference's canonical suite (zookeeper/src/jepsen/zookeeper.clj).
//
// Protocol (one request per line, '\n'-terminated):
//   SET <k> <v>          -> OK
//   GET <k>              -> VAL <v> | NIL
//   CAS <k> <old> <new>  -> OK | FAIL | NIL
//   ADD <k> <v>          -> OK            (grow-only set per key)
//   MEMBERS <k>          -> VAL <v1,v2,...> | NIL
//   PING                 -> PONG
//
// Durability: every mutation appends to an op log.  With --fsync each
// append is fdatasync'd before the client sees OK; without it,
// acknowledged writes can vanish on kill -9 — a real consistency bug
// the set workload detects end-to-end.
//
// Single process, thread-per-connection, one global mutex: the store
// itself is linearizable by construction, so any anomaly the checker
// reports was injected by the harness (kills, partitions), not the db.

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

std::mutex g_mu;
std::map<std::string, std::string> g_kv;
std::map<std::string, std::set<std::string>> g_sets;
int g_log_fd = -1;
bool g_fsync = false;
size_t g_buffer_cap = 0;   // --buffer N: userspace buffering (bug mode)
std::string g_log_buf;

void flush_log() {
  if (g_log_fd < 0 || g_log_buf.empty()) return;
  ssize_t off = 0;
  while (off < (ssize_t)g_log_buf.size()) {
    ssize_t n =
        write(g_log_fd, g_log_buf.data() + off, g_log_buf.size() - off);
    if (n <= 0) return;
    off += n;
  }
  g_log_buf.clear();
  if (g_fsync) fdatasync(g_log_fd);
}

void log_op(const std::string &line) {
  if (g_log_fd < 0) return;
  g_log_buf += line;
  g_log_buf += '\n';
  // With --buffer, acknowledged mutations sit in THIS PROCESS's memory
  // until the buffer fills — kill -9 loses them.  That's the bug the
  // set workload catches.  Without it, every op hits the kernel first.
  if (g_buffer_cap == 0 || g_log_buf.size() >= g_buffer_cap) flush_log();
}

void replay(const std::string &path) {
  FILE *f = fopen(path.c_str(), "r");
  if (!f) return;
  char buf[1 << 16];
  while (fgets(buf, sizeof buf, f)) {
    std::istringstream in(buf);
    std::string op, k, v;
    in >> op >> k >> v;
    if (op == "SET")
      g_kv[k] = v;
    else if (op == "ADD")
      g_sets[k].insert(v);
  }
  fclose(f);
}

std::string handle(const std::string &line) {
  std::istringstream in(line);
  std::string op;
  in >> op;
  std::lock_guard<std::mutex> lock(g_mu);
  if (op == "PING") return "PONG";
  if (op == "SET") {
    std::string k, v;
    in >> k >> v;
    if (k.empty()) return "ERR usage";
    log_op("SET " + k + " " + v);
    g_kv[k] = v;
    return "OK";
  }
  if (op == "GET") {
    std::string k;
    in >> k;
    auto it = g_kv.find(k);
    return it == g_kv.end() ? "NIL" : "VAL " + it->second;
  }
  if (op == "CAS") {
    std::string k, oldv, newv;
    in >> k >> oldv >> newv;
    auto it = g_kv.find(k);
    if (it == g_kv.end()) return "NIL";
    if (it->second != oldv) return "FAIL";
    log_op("SET " + k + " " + newv);
    it->second = newv;
    return "OK";
  }
  if (op == "ADD") {
    std::string k, v;
    in >> k >> v;
    log_op("ADD " + k + " " + v);
    g_sets[k].insert(v);
    return "OK";
  }
  if (op == "INCR") {
    // Atomic add under the global mutex: the counter workload's
    // CONTROL op.  (Its conviction arm never calls this — clients do
    // GET + SET round trips whose interleavings lose updates.)
    std::string k, d;
    in >> k >> d;
    if (k.empty() || d.empty()) return "ERR usage";
    long long cur = 0;
    auto it = g_kv.find(k);
    if (it != g_kv.end()) cur = atoll(it->second.c_str());
    long long next = cur + atoll(d.c_str());
    std::string nv = std::to_string(next);
    log_op("SET " + k + " " + nv);
    g_kv[k] = nv;
    return "VAL " + nv;
  }
  if (op == "MEMBERS") {
    std::string k;
    in >> k;
    auto it = g_sets.find(k);
    if (it == g_sets.end()) return "NIL";
    std::string out = "VAL ";
    bool first = true;
    for (const auto &v : it->second) {
      if (!first) out += ",";
      out += v;
      first = false;
    }
    return out;
  }
  return "ERR unknown op";
}

void serve_conn(int fd) {
  std::string buf;
  char chunk[4096];
  for (;;) {
    ssize_t n = read(fd, chunk, sizeof chunk);
    if (n <= 0) break;
    buf.append(chunk, n);
    size_t pos;
    while ((pos = buf.find('\n')) != std::string::npos) {
      std::string line = buf.substr(0, pos);
      buf.erase(0, pos + 1);
      if (!line.empty() && line.back() == '\r') line.pop_back();
      std::string resp = handle(line) + "\n";
      ssize_t off = 0;
      while (off < (ssize_t)resp.size()) {
        ssize_t w = write(fd, resp.data() + off, resp.size() - off);
        if (w <= 0) goto done;
        off += w;
      }
    }
  }
done:
  close(fd);
}

}  // namespace

int main(int argc, char **argv) {
  int port = 7400;
  std::string data;
  std::string listen_addr = "127.0.0.1";
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    if (a == "--port" && i + 1 < argc)
      port = atoi(argv[++i]);
    else if (a == "--listen" && i + 1 < argc)
      listen_addr = argv[++i];
    else if (a == "--data" && i + 1 < argc)
      data = argv[++i];
    else if (a == "--fsync")
      g_fsync = true;
    else if (a == "--buffer" && i + 1 < argc)
      g_buffer_cap = (size_t)atoll(argv[++i]);
  }
  signal(SIGPIPE, SIG_IGN);
  if (!data.empty()) {
    replay(data);
    g_log_fd = open(data.c_str(), O_WRONLY | O_APPEND | O_CREAT, 0644);
    if (g_log_fd < 0) {
      perror("open data log");
      return 1;
    }
  }

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, listen_addr.c_str(), &addr.sin_addr) != 1) {
    fprintf(stderr, "bad --listen address %s\n", listen_addr.c_str());
    return 2;
  }
  addr.sin_port = htons(port);
  if (bind(srv, (sockaddr *)&addr, sizeof addr) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(srv, 128) != 0) {
    perror("listen");
    return 1;
  }
  fprintf(stderr, "kvdb listening on %s:%d (fsync=%d data=%s)\n",
          listen_addr.c_str(), port, (int)g_fsync, data.c_str());
  for (;;) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    std::thread(serve_conn, fd).detach();
  }
}
