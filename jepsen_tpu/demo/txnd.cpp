// txnd: a tiny MVCC key-value store with snapshot-isolation
// transactions — and snapshot isolation's signature bug, write skew.
//
// The role: a REAL transactional system for the elle-equivalent
// checkers (jepsen_tpu/checker/elle) to convict, the way the
// reference project aims elle at tidb/cockroachdb/yugabyte (SURVEY.md
// §2.5).  kvdb/repkv/logd cover durability, replication, and logs;
// this covers transactions.
//
// Storage: versioned values per key, each stamped with the commit
// sequence number that wrote it.  A transaction takes a snapshot
// (the commit counter at BEGIN), reads the latest version <= its
// snapshot, buffers writes, and at COMMIT aborts iff some written
// key gained a version after the snapshot — first-committer-wins on
// WRITE-write conflicts only.  That is textbook snapshot isolation:
// two transactions that READ overlapping keys but WRITE disjoint
// ones both commit, producing G2/write-skew anomalies (Berenson et
// al. 1995; Adya's G2) that serializability forbids.
//
// --serializable widens commit validation to the READ set (aborts if
// any key read has a newer version than the snapshot — backward
// OCC), which closes the skew window: the control group.
//
// --read-committed drops BOTH the snapshot and commit validation:
// each read is its own statement against the latest committed state
// (lock taken and released per read), and writes apply blindly.
// That is READ COMMITTED — no dirty reads (only committed versions
// are ever visible), but read skew (a multi-key read straddling a
// concurrent commit) and lost updates (two read-modify-writes off
// the same stale read) are both admitted.  The bank workload's
// conserved-total invariant convicts exactly this level, the way the
// reference's bank test convicts weak MySQL/Galera settings
// (tests/bank.clj:56-120); snapshot isolation is its control group.
//
// --think-us N sleeps between snapshot acquisition and commit
// validation (and, under --read-committed, between the per-statement
// reads), widening the race window so short test runs reliably
// exhibit the anomaly (a production system's window is its
// transaction duration; we just make ours honest and visible).
//
// Protocol (line-based TCP, one txn per line, executed server-side;
// micro-ops execute in client order with intra-txn visibility):
//   TXN [r <k> | w <k> <v> | a <k> <v>] ...\n
//     -> OK [<read-val-or-NIL> per r, in order]\n   committed
//        (list keys read back comma-joined: "1,2,3")
//     -> ABORT\n                                    conflict: nothing applied
//   `a` appends to a comma-joined list — the elle list-append
//   workload's mop, a read-modify-write that rides the same
//   isolation machinery (FCW guards it under SI; --read-committed
//   computes it off a per-statement read and loses appends).
//   TRANSFER <from> <to> <amount>\n    server-side read-modify-write
//     -> OK\n          committed: from -= amount, to += amount
//     -> NSF\n         insufficient funds: nothing applied
//     -> ABORT\n       first-committer-wins conflict: nothing applied
//   PING\n -> PONG\n
//
// --init <key> <value> (repeatable) seeds a committed version before
// the listener opens — bank accounts exist race-free from op one.
//
// Values are integers; TXN writes are expected globally unique per
// key (the elle rw-register workload guarantees this).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdio.h>
#include <stdint.h>
#include <stdlib.h>
#include <time.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

struct Version {
  long long seq;
  std::string value;  // int string (wr/bank) or comma list (append)
};

static std::map<std::string, std::vector<Version>> g_store;
static long long g_commit_seq = 0;
static std::mutex g_mu;  // guards g_store + g_commit_seq

static bool g_serializable = false;
static bool g_read_committed = false;
static long g_think_us = 2000;

static void think() {
  // Uniform in [0, 2*think_us] (mean = think_us): real transactions
  // have VARIED durations, and heterogeneity is load-bearing for
  // observability — with a fixed gap, every read in a group sits on
  // the same side of the write-separation threshold, so e.g. the
  // long-fork anomaly's two contradictory read directions can never
  // coexist (measured: 82 partial-sighting groups, all one-sided).
  if (g_think_us <= 0) return;
  thread_local unsigned seed =
      (unsigned)(uintptr_t)&seed ^ (unsigned)time(nullptr);
  long us = (long)(rand_r(&seed) % (2 * g_think_us + 1));
  std::this_thread::sleep_for(std::chrono::microseconds(us));
}

// One transaction micro-op, in client order: 'r' read, 'w' blind
// write, 'a' list-append (read-modify-write of a comma-joined list).
struct Mop {
  char type;
  std::string key;
  std::string value;
};

// Latest committed value of key visible at `snap`; false if none.
static bool read_at(const std::string &key, long long snap,
                    std::string *out) {
  auto it = g_store.find(key);
  if (it == g_store.end()) return false;
  const auto &vs = it->second;
  for (auto r = vs.rbegin(); r != vs.rend(); ++r) {
    if (r->seq <= snap) {
      *out = r->value;
      return true;
    }
  }
  return false;
}

// Newest version seq of key (0 if never written).
static long long newest_seq(const std::string &key) {
  auto it = g_store.find(key);
  if (it == g_store.end() || it->second.empty()) return 0;
  return it->second.back().seq;
}

static std::string run_txn(const std::vector<Mop> &mops) {
  long long snap = 0;
  std::vector<std::pair<bool, std::string>> results;  // per 'r' mop
  // Txn-local effects: later mops of this txn see earlier ones
  // (standard intra-txn visibility; elle's intermediate-read analysis
  // depends on it).  Committed atomically at the end.
  std::map<std::string, std::string> buffered;

  // Reads a key as this txn sees it mid-flight: its own buffered
  // write first, else the committed version at `at`.
  auto visible = [&](const std::string &k, long long at,
                     std::string *out) -> bool {
    auto b = buffered.find(k);
    if (b != buffered.end()) {
      *out = b->second;
      return true;
    }
    return read_at(k, at, out);
  };

  auto apply = [&](const Mop &m, long long at) {
    std::string v;
    if (m.type == 'r') {
      bool have = visible(m.key, at, &v);
      results.push_back({have, v});
    } else if (m.type == 'w') {
      buffered[m.key] = m.value;
    } else {  // 'a': append to the list this txn can see
      bool have = visible(m.key, at, &v);
      buffered[m.key] = have && !v.empty() ? v + "," + m.value
                                           : m.value;
    }
  };

  if (g_read_committed) {
    // Each mop is its own statement: lock per statement, latest
    // committed state, think between statements.  A commit landing
    // in a gap is read skew; an append computed off a stale read is
    // a lost append.
    for (size_t i = 0; i < mops.size(); i++) {
      if (i > 0) think();
      std::lock_guard<std::mutex> lk(g_mu);
      apply(mops[i], g_commit_seq);
    }
  } else {
    std::lock_guard<std::mutex> lk(g_mu);
    snap = g_commit_seq;
    for (const auto &m : mops) apply(m, snap);
  }

  // The transaction "thinks" between snapshot and commit — the window
  // in which a concurrent committer can invalidate its premises.
  if (!buffered.empty()) think();

  {
    std::lock_guard<std::mutex> lk(g_mu);
    if (!g_read_committed) {
      // First-committer-wins on the write set (appends included:
      // they read the key they write, so FCW also guards their
      // read-modify-write premise).
      for (const auto &w : buffered)
        if (newest_seq(w.first) > snap) return "ABORT";
      if (g_serializable)
        for (const auto &m : mops)
          if (m.type == 'r' && newest_seq(m.key) > snap)
            return "ABORT";
    }
    if (!buffered.empty()) {
      long long seq = ++g_commit_seq;
      for (const auto &w : buffered)
        g_store[w.first].push_back({seq, w.second});
    }
  }

  std::ostringstream out;
  out << "OK";
  for (const auto &res : results) {
    if (res.first && !res.second.empty())
      out << " " << res.second;
    else
      out << " NIL";
  }
  return out.str();
}

// Server-side read-modify-write: from -= amount, to += amount.  The
// balances the writes are computed FROM come out of the same
// isolation machinery as TXN reads — a snapshot (validated
// first-committer-wins at commit) or, under --read-committed,
// per-statement latest reads applied blindly, which is where lost
// updates and skewed totals come from.
static std::string run_transfer(const std::string &from,
                                const std::string &to,
                                long long amount) {
  // Self-transfers would push two same-seq versions of one key and
  // negative amounts would bypass the NSF check — either mints or
  // destroys money under EVERY isolation level, which the bank
  // checker would then blame on isolation.  Malformed, not a txn.
  if (from == to || amount <= 0) return "ERR bad transfer";
  long long snap = 0;
  std::string raw_from, raw_to;
  bool have_from = false, have_to = false;
  if (g_read_committed) {
    {
      std::lock_guard<std::mutex> lk(g_mu);
      have_from = read_at(from, g_commit_seq, &raw_from);
    }
    think();
    {
      std::lock_guard<std::mutex> lk(g_mu);
      have_to = read_at(to, g_commit_seq, &raw_to);
    }
  } else {
    std::lock_guard<std::mutex> lk(g_mu);
    snap = g_commit_seq;
    have_from = read_at(from, snap, &raw_from);
    have_to = read_at(to, snap, &raw_to);
  }
  long long bal_from = atoll(raw_from.c_str());
  long long bal_to = atoll(raw_to.c_str());
  if (!have_from || bal_from < amount) return "NSF";

  think();

  {
    std::lock_guard<std::mutex> lk(g_mu);
    if (!g_read_committed) {
      // Write set = {from, to}; read set is the same, so SI and
      // serializable validation coincide for transfers.
      if (newest_seq(from) > snap || newest_seq(to) > snap)
        return "ABORT";
    }
    long long seq = ++g_commit_seq;
    g_store[from].push_back({seq, std::to_string(bal_from - amount)});
    g_store[to].push_back(
        {seq, std::to_string(have_to ? bal_to + amount : amount)});
  }
  return "OK";
}

static void serve(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  FILE *in = fdopen(fd, "r");
  FILE *out = fdopen(dup(fd), "w");
  if (!in || !out) {
    close(fd);
    return;
  }
  char line[65536];
  while (fgets(line, sizeof(line), in)) {
    std::istringstream ss(line);
    std::string cmd;
    ss >> cmd;
    std::string resp;
    if (cmd == "PING") {
      resp = "PONG";
    } else if (cmd == "TXN") {
      std::vector<Mop> mops;
      std::string op;
      bool bad = false;
      while (ss >> op) {
        if (op == "r") {
          std::string k;
          if (!(ss >> k)) { bad = true; break; }
          mops.push_back({'r', k, ""});
        } else if (op == "w" || op == "a") {
          std::string k, v;
          if (!(ss >> k >> v)) { bad = true; break; }
          // Values are integers on the wire (appends build the comma
          // lists server-side).  The old `>> long long` rejected
          // garbage; keep that guard — a committed non-numeric value
          // would silently zero bank balances via atoll later.
          size_t p = (v[0] == '-') ? 1 : 0;
          if (p >= v.size() ||
              v.find_first_not_of("0123456789", p) != std::string::npos) {
            bad = true;
            break;
          }
          mops.push_back({op[0], k, v});
        } else {
          bad = true;
          break;
        }
      }
      resp = bad ? "ERR bad txn" : run_txn(mops);
    } else if (cmd == "TRANSFER") {
      std::string from, to;
      long long amount;
      if (ss >> from >> to >> amount)
        resp = run_transfer(from, to, amount);
      else
        resp = "ERR bad transfer";
    } else {
      resp = "ERR unknown command";
    }
    fputs(resp.c_str(), out);
    fputc('\n', out);
    fflush(out);
  }
  fclose(in);
  fclose(out);
}

int main(int argc, char **argv) {
  int port = 7500;
  std::string listen_addr = "127.0.0.1";
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    if (a == "--port" && i + 1 < argc)
      port = atoi(argv[++i]);
    else if (a == "--listen" && i + 1 < argc)
      listen_addr = argv[++i];
    else if (a == "--serializable")
      g_serializable = true;
    else if (a == "--read-committed")
      g_read_committed = true;
    else if (a == "--think-us" && i + 1 < argc)
      g_think_us = atol(argv[++i]);
    else if (a == "--init" && i + 2 < argc) {
      std::string key = argv[++i];
      std::string value = argv[++i];
      g_store[key].push_back({++g_commit_seq, value});
    } else {
      fprintf(stderr, "unknown arg %s\n", a.c_str());
      return 2;
    }
  }
  signal(SIGPIPE, SIG_IGN);

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, listen_addr.c_str(), &addr.sin_addr) != 1) {
    fprintf(stderr, "bad --listen address %s\n", listen_addr.c_str());
    return 2;
  }
  if (bind(srv, (sockaddr *)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  listen(srv, 64);
  fprintf(stderr, "txnd listening on %s:%d (%s, think %ld us)\n",
          listen_addr.c_str(), port,
          g_read_committed ? "read-committed"
          : g_serializable ? "serializable"
                           : "snapshot-isolation",
          g_think_us);
  for (;;) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    std::thread(serve, fd).detach();
  }
}
