"""The demo systems under test: five small C++ servers, each built to
exhibit one canonical distributed-systems bug class for the framework
to convict (SURVEY.md §2.5's per-database-suite role):

* ``kvdb.cpp``  — single-node KV store; ``--buffer`` holds acked
  writes in process memory, so kill -9 loses them (durability).
* ``repkv.cpp`` — primary/backup replication with JOIN/LEAVE
  membership; async replication serves stale backup reads under
  partitions (replication).
* ``logd.cpp``  — kafka-shaped partitioned log; ``--flush-ms``
  write-behind loses acked records on SIGKILL (logs).
* ``txnd.cpp``  — MVCC snapshot isolation; first-committer-wins
  admits textbook write skew (transactions).
* ``electd.cpp`` — bully-style leader election with no fencing;
  partitions split-brain it and heal discards one side's acked
  writes (election / lost updates); ``--quorum`` swaps in ABD
  majority rounds as the linearizable control group.

Shipped as package data so the suites (jepsen_tpu/suites/) can upload
and compile them on nodes from any install, not just a repo checkout;
each suite's DB.setup compiles its server with g++ on the node, the
way the reference compiles C helpers there (nemesis/time.clj:21-40).
"""

import os


def source(name: str) -> str:
    """Absolute path of a demo server's source file, e.g.
    source("kvdb") -> .../jepsen_tpu/demo/kvdb.cpp."""
    path = os.path.join(os.path.dirname(__file__), f"{name}.cpp")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no demo source {name!r} at {path}")
    return path
