// repkv: a deliberately small REPLICATED key-value store — the
// framework's multi-node demo system, playing the role a real
// replicated database (etcd/zookeeper) plays for the reference's
// suites.  N processes form a primary/backup group: the primary
// accepts writes and streams them to backups; any node serves reads.
//
// Replication is primary -> backup over persistent TCP connections.
// In the default (async) mode the primary acknowledges writes without
// waiting for backups; with --sync it waits for every *reachable*
// backup's ack, but silently degrades to async for peers that time
// out — exactly the kind of "mostly synchronous" replication that
// looks linearizable until a partition makes backup reads stale.
// Split-brain is reachable too: PROMOTE turns a backup into a second
// primary.  The checker, not the server, is supposed to catch all of
// this.
//
// Client protocol (one request per line):
//   GET <k>              -> VAL <v> | NIL
//   SET <k> <v>          -> OK | ERR notprimary
//   CAS <k> <old> <new>  -> OK | FAIL | NIL | ERR notprimary
//   PING                 -> PONG
//   ROLE                 -> PRIMARY | BACKUP
//   PROMOTE / DEMOTE     -> OK            (failover / fault injection)
//   BLOCK <id>           -> OK  (drop replication to/from peer <id> —
//   UNBLOCK <id> | *     -> OK   app-level partition injection, used
//                                by the suite's Net implementation)
// Membership (grow/shrink; the target of the membership nemesis,
// reference design nemesis/membership.clj:1-47):
//   VIEW                 -> VIEW <view_id> <id@host:port,...>
//   JOIN <id> <host:port>-> OK | ERR notprimary | ERR member
//   LEAVE <id>           -> OK | ERR notprimary | ERR nomember|self
// View changes are decided by the primary and PROPAGATE over the
// ordered replication stream (REPL ... VIEW lines), so backups learn
// with replication lag — and a node removed by LEAVE is deliberately
// never told: it keeps its stale view and keeps serving reads from
// data frozen at removal time.  That removed-but-unaware replica is
// the membership suite's checker-visible violation.
// Known limitation (deliberate — repkv is a fault playground, not a
// consensus system): views live only in memory.  A killed-and-
// restarted node reboots with its static --peers membership at view 1
// and, if it is the primary, its next view change is rejected by
// backups holding a higher view id (install_view ignores stale ids) —
// the suite's resolve_op abandons such ops rather than wedging.  Real
// systems persist membership in their log; repkv's whole point is to
// show what happens when pieces like that go missing.
// Peer protocol (on the same port):
//   REPL <from> <seq> SET <k> <v>   -> ACK <seq>   (unless blocked)
//   REPL <from> <seq> CAS ... same shape.
//   REPL <from> <seq> VIEW <view_id> <id@host:port,...> -> ACK <seq>.
//
// Fresh implementation for this framework's demo suite.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

int g_id = 0;
bool g_sync = false;
int g_ack_timeout_ms = 150;
std::mutex g_mu;
std::map<std::string, std::string> g_kv;
long long g_seq = 0;          // last locally applied sequence
bool g_primary = false;
std::set<int> g_blocked;      // peer ids we refuse to talk to
std::map<int, long long> g_applied_from;  // per-sender dedup watermark

struct Peer {
  int id;
  std::string host;
  int port;
  std::mutex mu;
  std::condition_variable cv;
  std::deque<std::string> queue;   // REPL lines to ship
  long long acked = 0;
  bool stop = false;
};

std::vector<Peer*> g_peers;   // channels to current members (guarded
                              // by g_peers_mu; stopped peers stay in
                              // the vector with stop=true — never
                              // freed, so replicate() can't race a
                              // delete)
std::mutex g_peers_mu;
std::mutex g_ack_mu;
std::condition_variable g_ack_cv;

// Membership view: id -> "host:port" for every member INCLUDING self.
long long g_view_id = 1;
std::map<int, std::string> g_members;
std::string g_self_addr;

bool blocked(int id) {
  std::lock_guard<std::mutex> l(g_mu);
  return g_blocked.count(id) > 0;
}

// One writer thread per peer: connect, ship queued REPL lines, read
// ACKs.  Reconnects forever; drops the connection while blocked.
void peer_loop(Peer* p) {
  int fd = -1;
  FILE* rf = nullptr;
  std::string carry;
  while (true) {
    std::string line;
    {
      std::unique_lock<std::mutex> l(p->mu);
      p->cv.wait_for(l, std::chrono::milliseconds(100), [&] {
        return p->stop || !p->queue.empty();
      });
      if (p->stop) break;
      if (p->queue.empty()) continue;
      line = p->queue.front();
    }
    if (blocked(p->id)) {
      // Simulated partition: connection torn down, nothing shipped.
      if (fd >= 0) { fclose(rf); rf = nullptr; close(fd); fd = -1; }
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
      continue;
    }
    if (fd < 0) {
      fd = socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in a{};
      a.sin_family = AF_INET;
      a.sin_port = htons(p->port);
      inet_pton(AF_INET, p->host.c_str(), &a.sin_addr);
      if (connect(fd, (sockaddr*)&a, sizeof(a)) != 0) {
        close(fd);
        fd = -1;
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // Bounded ack wait: a receiver that swallows a REPL line (its
      // side of a partition) must not wedge this thread in fgets
      // forever — timeout, drop the conn, retry the queued line.
      timeval tv{};
      tv.tv_sec = 0;
      tv.tv_usec = 500 * 1000;
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      rf = fdopen(fd, "r");
    }
    if (write(fd, line.data(), line.size()) != (ssize_t)line.size()) {
      fclose(rf); rf = nullptr; close(fd); fd = -1;
      continue;
    }
    char buf[256];
    if (!fgets(buf, sizeof(buf), rf)) {
      fclose(rf); rf = nullptr; close(fd); fd = -1;
      continue;
    }
    long long seq = 0;
    if (sscanf(buf, "ACK %lld", &seq) == 1) {
      {
        std::lock_guard<std::mutex> l(p->mu);
        if (seq > p->acked) p->acked = seq;
        p->queue.pop_front();
      }
      g_ack_cv.notify_all();
    }
  }
  if (rf) fclose(rf);
  else if (fd >= 0) close(fd);
}

// Starts (or restarts) the replication channel to member <id>.
// Caller must NOT hold g_peers_mu.
void ensure_peer(int id, const std::string& hostport) {
  std::lock_guard<std::mutex> l(g_peers_mu);
  for (Peer* p : g_peers) {
    if (p->id == id) {
      std::lock_guard<std::mutex> pl(p->mu);
      if (!p->stop) return;  // already live
    }
  }
  auto colon = hostport.rfind(':');
  Peer* p = new Peer();
  p->id = id;
  p->host = hostport.substr(0, colon);
  p->port = atoi(hostport.substr(colon + 1).c_str());
  g_peers.push_back(p);
  std::thread(peer_loop, p).detach();
}

void retire_peer(int id) {
  std::lock_guard<std::mutex> l(g_peers_mu);
  for (Peer* p : g_peers) {
    if (p->id == id) {
      std::lock_guard<std::mutex> pl(p->mu);
      p->stop = true;
      p->cv.notify_one();
    }
  }
}

// "id@host:port,id@host:port" for the current members, sorted by id.
// Caller holds g_mu.
std::string view_members_str() {
  std::ostringstream out;
  bool first = true;
  for (auto& m : g_members) {
    if (!first) out << ",";
    out << m.first << "@" << m.second;
    first = false;
  }
  return out.str();
}

// Installs a view received over replication (or decided locally).
// Caller holds g_mu; peer channel reconciliation happens lazily by the
// caller OUTSIDE g_mu via the returned flag.
bool install_view(long long view_id, const std::string& members) {
  if (view_id <= g_view_id) return false;
  g_view_id = view_id;
  g_members.clear();
  std::stringstream ms(members);
  std::string item;
  while (std::getline(ms, item, ',')) {
    if (item.empty()) continue;
    auto at = item.find('@');
    g_members[atoi(item.substr(0, at).c_str())] = item.substr(at + 1);
  }
  return true;
}

// Brings replication channels in line with g_members: channels only
// for members other than self; removed members' channels retire.
void reconcile_peers() {
  std::map<int, std::string> members;
  {
    std::lock_guard<std::mutex> l(g_mu);
    members = g_members;
  }
  std::vector<int> live;
  {
    std::lock_guard<std::mutex> l(g_peers_mu);
    for (Peer* p : g_peers) live.push_back(p->id);
  }
  for (int id : live)
    if (!members.count(id)) retire_peer(id);
  for (auto& m : members)
    if (m.first != g_id) ensure_peer(m.first, m.second);
}

// Applies a mutation under g_mu; returns the response for the client.
std::string apply(const std::string& op, const std::string& k,
                  const std::string& a, const std::string& b,
                  bool* mutated) {
  *mutated = false;
  if (op == "SET") {
    g_kv[k] = a;
    *mutated = true;
    return "OK";
  }
  auto it = g_kv.find(k);
  if (it == g_kv.end()) return "NIL";
  if (it->second != a) return "FAIL";
  it->second = b;
  *mutated = true;
  return "OK";
}

// Enqueues an already-applied mutation onto every live peer channel.
// MUST be called while still holding g_mu (the lock that assigned the
// line's seq): releasing between seq assignment and enqueue lets a
// racing higher-seq line enqueue first, and the receiver's per-sender
// watermark then drops the lower-seq line forever — survivable for a
// SET, fatal for a VIEW change (a backup stuck on stale membership).
// Lock order g_mu -> g_peers_mu is used consistently.  Retired
// channels (members removed by LEAVE) are skipped: the removed node
// silently stops receiving updates.
void enqueue_all_g_mu_held(const std::string& line) {
  std::lock_guard<std::mutex> l(g_peers_mu);
  for (Peer* p : g_peers) {
    std::lock_guard<std::mutex> pl(p->mu);
    if (p->stop) continue;
    p->queue.push_back(line);
    p->cv.notify_one();
  }
}

// In --sync mode, wait for acks from unblocked live peers (timeout
// degrades to async — the bug).  Called WITHOUT g_mu.
void await_acks(long long seq) {
  if (!g_sync) return;
  std::vector<Peer*> peers;
  {
    std::lock_guard<std::mutex> l(g_peers_mu);
    peers = g_peers;
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(g_ack_timeout_ms);
  std::unique_lock<std::mutex> l(g_ack_mu);
  g_ack_cv.wait_until(l, deadline, [&] {
    for (Peer* p : peers) {
      if (blocked(p->id)) continue;
      std::lock_guard<std::mutex> pl(p->mu);
      if (p->stop) continue;
      if (p->acked < seq) return false;
    }
    return true;
  });
}

void serve(int fd) {
  FILE* rf = fdopen(fd, "r");
  if (!rf) { close(fd); return; }
  char buf[4096];
  while (fgets(buf, sizeof(buf), rf)) {
    std::istringstream in(buf);
    std::string cmd;
    in >> cmd;
    std::string resp;
    if (cmd == "PING") {
      resp = "PONG";
    } else if (cmd == "GET") {
      std::string k;
      in >> k;
      std::lock_guard<std::mutex> l(g_mu);
      auto it = g_kv.find(k);
      resp = it == g_kv.end() ? "NIL" : ("VAL " + it->second);
    } else if (cmd == "SET" || cmd == "CAS") {
      std::string k, a, b;
      in >> k >> a;
      if (cmd == "CAS") in >> b;
      long long seq = 0;
      bool mutated = false;
      {
        std::lock_guard<std::mutex> l(g_mu);
        if (!g_primary) {
          resp = "ERR notprimary";
        } else {
          resp = apply(cmd, k, a, b, &mutated);
          if (mutated) {
            seq = ++g_seq;
            std::ostringstream repl;
            repl << "REPL " << g_id << " " << seq << " SET " << k << " "
                 << (cmd == "SET" ? a : b) << "\n";
            enqueue_all_g_mu_held(repl.str());
          }
        }
      }
      if (mutated) await_acks(seq);
    } else if (cmd == "ADD") {
      // Set face: primary-only atomic append to a comma-joined
      // element list, replicated INCREMENTALLY (one small
      // "REPL .. ADD k v" line per element — a full-list SET line
      // would outgrow the 4096-byte request buffers within a
      // minute-long run and tear on the wire).  The per-peer queue
      // retains lines until ACKed and ships them FIFO, so a healed
      // partition converges; DURING it, backups serve frozen lists —
      // the staleness the set-full checker measures.
      std::string k, v;
      in >> k >> v;
      long long seq = 0;
      bool mutated = false;
      {
        std::lock_guard<std::mutex> l(g_mu);
        if (!g_primary) {
          resp = "ERR notprimary";
        } else {
          std::string& cur = g_kv[k];
          cur = cur.empty() ? v : cur + "," + v;
          mutated = true;
          seq = ++g_seq;
          std::ostringstream repl;
          repl << "REPL " << g_id << " " << seq << " ADD " << k << " "
               << v << "\n";
          enqueue_all_g_mu_held(repl.str());
          resp = "OK";
        }
      }
      if (mutated) await_acks(seq);
    } else if (cmd == "MEMBERS") {
      std::string k;
      in >> k;
      std::lock_guard<std::mutex> l(g_mu);
      auto it = g_kv.find(k);
      resp = (it == g_kv.end() || it->second.empty())
                 ? "NIL"
                 : ("VAL " + it->second);
    } else if (cmd == "REPL") {
      int from;
      long long seq;
      std::string op, k, v;
      in >> from >> seq >> op >> k >> v;
      if (blocked(from)) {
        // Partitioned: swallow silently (no ack) so the sender times
        // out, like a dropped packet.
        continue;
      }
      bool views_changed = false;
      {
        // Idempotent apply: a slow ack (> the sender's recv timeout)
        // makes the sender re-ship the line on a fresh connection, so
        // replays at or below the per-sender watermark are ACKed
        // without re-applying.
        std::lock_guard<std::mutex> l(g_mu);
        long long& applied = g_applied_from[from];
        if (seq > applied) {
          if (op == "VIEW") {
            views_changed = install_view(atoll(k.c_str()), v);
          } else if (op == "ADD") {
            std::string& cur = g_kv[k];
            cur = cur.empty() ? v : cur + "," + v;
          } else {
            g_kv[k] = v;
          }
          applied = seq;
          if (seq > g_seq) g_seq = seq;
        }
      }
      if (views_changed) reconcile_peers();
      resp = "ACK " + std::to_string(seq);
    } else if (cmd == "VIEW") {
      std::lock_guard<std::mutex> l(g_mu);
      resp = "VIEW " + std::to_string(g_view_id) + " " +
             view_members_str();
    } else if (cmd == "JOIN" || cmd == "LEAVE") {
      int id;
      std::string hostport;
      in >> id;
      if (cmd == "JOIN") in >> hostport;
      long long seq = 0;
      bool changed = false;
      {
        std::lock_guard<std::mutex> l(g_mu);
        if (!g_primary) {
          resp = "ERR notprimary";
        } else if (cmd == "JOIN" &&
                   hostport.find(':') == std::string::npos) {
          resp = "ERR badaddr";
        } else if (cmd == "JOIN" && g_members.count(id)) {
          resp = "ERR member";
        } else if (cmd == "LEAVE" &&
                   (id == g_id || !g_members.count(id))) {
          resp = id == g_id ? "ERR self" : "ERR nomember";
        } else {
          if (cmd == "JOIN") g_members[id] = hostport;
          else g_members.erase(id);
          g_view_id++;
          resp = "OK";
          changed = true;
          seq = ++g_seq;
          // Channel changes and the view line's enqueue happen under
          // the SAME g_mu hold that assigned seq (see
          // enqueue_all_g_mu_held): a joined member's channel exists
          // before the line ships so it hears the view; a removed
          // member's channel retires first so the leaver never learns
          // it left (the membership suite's stale-replica physics).
          if (cmd == "JOIN") ensure_peer(id, hostport);
          else retire_peer(id);
          std::ostringstream repl;
          repl << "REPL " << g_id << " " << seq << " VIEW " << g_view_id
               << " " << view_members_str() << "\n";
          enqueue_all_g_mu_held(repl.str());
        }
      }
      if (changed) await_acks(seq);
    } else if (cmd == "ROLE") {
      std::lock_guard<std::mutex> l(g_mu);
      resp = g_primary ? "PRIMARY" : "BACKUP";
    } else if (cmd == "PROMOTE") {
      std::lock_guard<std::mutex> l(g_mu);
      g_primary = true;
      resp = "OK";
    } else if (cmd == "DEMOTE") {
      std::lock_guard<std::mutex> l(g_mu);
      g_primary = false;
      resp = "OK";
    } else if (cmd == "BLOCK") {
      int id;
      in >> id;
      std::lock_guard<std::mutex> l(g_mu);
      g_blocked.insert(id);
      resp = "OK";
    } else if (cmd == "UNBLOCK") {
      std::string id;
      in >> id;
      std::lock_guard<std::mutex> l(g_mu);
      if (id == "*") g_blocked.clear();
      else g_blocked.erase(atoi(id.c_str()));
      resp = "OK";
    } else {
      resp = "ERR badcmd";
    }
    resp += "\n";
    if (write(fd, resp.data(), resp.size()) != (ssize_t)resp.size())
      break;
  }
  fclose(rf);
}

}  // namespace

int main(int argc, char** argv) {
  int port = 7100;
  std::string listen_addr = "127.0.0.1";
  std::string advertise;  // routable self-address for views
  std::string peers;  // "id@host:port,id@host:port"
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() { return std::string(argv[++i]); };
    if (a == "--port") port = atoi(next().c_str());
    else if (a == "--listen") listen_addr = next();
    else if (a == "--advertise") advertise = next();
    else if (a == "--id") g_id = atoi(next().c_str());
    else if (a == "--peers") peers = next();
    else if (a == "--primary") g_primary = true;
    else if (a == "--sync") g_sync = true;
    else if (a == "--ack-timeout-ms") g_ack_timeout_ms = atoi(next().c_str());
  }
  signal(SIGPIPE, SIG_IGN);

  // The advertised self-address enters membership views and is what
  // OTHER nodes dial after a failover: it must be routable, so a
  // wildcard --listen needs an explicit --advertise.
  g_self_addr = advertise.empty()
                    ? listen_addr + ":" + std::to_string(port)
                    : advertise;
  g_members[g_id] = g_self_addr;
  std::stringstream ps(peers);
  std::string item;
  while (std::getline(ps, item, ',')) {
    if (item.empty()) continue;
    auto at = item.find('@');
    g_members[atoi(item.substr(0, at).c_str())] = item.substr(at + 1);
  }
  reconcile_peers();

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  inet_pton(AF_INET, listen_addr.c_str(), &addr.sin_addr);
  if (bind(srv, (sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  listen(srv, 64);
  fprintf(stderr, "repkv id=%d %s on %s:%d (%s)\n", g_id,
          g_primary ? "PRIMARY" : "backup", listen_addr.c_str(), port,
          g_sync ? "sync" : "async");
  while (true) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    int nd = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
    std::thread(serve, fd).detach();
  }
}
