// electd: a deliberately small LEADER-ELECTED register store — the
// framework's split-brain demo system.  N processes heartbeat each
// other; each node believes it is the leader iff it has heard no live
// peer with a LOWER id recently (bully-style, no terms, no fencing —
// that absence is the point).  Clients find a node claiming LEADER and
// do register ops there.
//
// The physics (default, "unsafe" mode): a partition that separates the
// lowest-id node from the rest makes BOTH sides elect a leader — the
// low side keeps its leader, the high side stops hearing it and
// promotes itself.  Both leaders accept and acknowledge writes.  On
// heal, the higher-id leader notices the lower one, steps down, and
// adopts the survivor's state WHOLESALE (a DUMP pull) — every write it
// acknowledged during the split is silently discarded.  Acked-then-
// lost updates and resurrected stale values are exactly what the
// linearizability checker (checker/linearizable.py, the knossos
// equivalent — checker.clj:202-233) must convict; the famous
// split-brain findings of the reference's published analyses are this
// shape.
//
// The control group (--quorum): leadership is ignored and every op is
// an ABD majority round (Attiya-Bar-Noy-Dolev): reads and writes each
// do a timestamp query phase and a store phase against a majority of
// nodes, with (ts, writer-id) lexicographic ordering.  ABD's atomic
// register is linearizable by construction, so the SAME partitions
// convict unsafe mode and leave quorum mode valid.  (ABD covers
// read/write registers only — CAS needs consensus, which electd
// deliberately does not have; the suite's quorum workload is rw-only.)
//
// Second experiment (crash amnesia): ABD assumes replicas remember
// their (ts, wid, val) across failures.  Without --wal the store and
// the timestamp clock are process memory, so kill -9 + restart
// reboots a replica EMPTY — a later majority can miss an acked write
// entirely (and a reused timestamp can diverge replicas).  That is
// the reference's canonical volatile-quorum finding.  --wal <path>
// appends every accepted (k, ts, wid, val) to a fsync'd log replayed
// at boot (clock floor included), closing the amnesia hole; the suite
// runs the same kill schedule volatile (convicted) and durable
// (valid).  The WAL is quorum-mode durability: unsafe mode's
// wholesale state adoption deliberately discards entries, which an
// append-only replay cannot represent.
//
// Client protocol (one request per line):
//   GET <k>               -> VAL <v> | NIL | ERR notleader|noquorum
//   SET <k> <v>           -> OK | ERR notleader|noquorum
//   CAS <k> <old> <new>   -> OK | FAIL | NIL | ERR notleader (unsafe only)
//   ROLE                  -> LEADER | FOLLOWER | QUORUM
//   PING                  -> PONG
//   CLOCK                 -> CLOCK <abd_clock>   (admin observability)
//   DUMP <from>           -> STATE <k>=<ts>:<wid>:<v>,...   (step-down pull)
//   BLOCK <id> / UNBLOCK <id>|* -> OK   (app-level partition injection,
//                                        the suite's Net implementation)
// Peer protocol (same port; silently dropped while the sender is
// blocked, like a partitioned packet):
//   HB <from>                      -> HBACK
//   QREAD <from> <k>               -> QVAL <ts> <wid> <v|__nil__>
//   QSTORE <from> <k> <ts> <wid> <v> -> QACK
//
// Fresh implementation for this framework's demo suite.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Entry {
  long long ts = 0;
  int wid = 0;
  std::string val;
};

struct PeerAddr {
  int id;
  std::string host;
  int port;
};

int g_id = 0;
bool g_quorum = false;
int g_stale_ms = 500;    // lower peer unheard this long => it's dead
int g_peer_timeout_ms = 100;  // per-peer connect/read budget
std::mutex g_mu;
std::map<std::string, Entry> g_kv;
long long g_abd_clock = 0;  // node-local monotonic ABD timestamp floor
FILE* g_wal = nullptr;      // quorum-mode durability; null = volatile
std::mutex g_wal_mu;        // append order; never held with g_mu
std::set<int> g_blocked;
std::map<int, Clock::time_point> g_last_heard;
bool g_leader = false;
std::vector<PeerAddr> g_peers;

bool blocked(int id) {
  std::lock_guard<std::mutex> l(g_mu);
  return g_blocked.count(id) > 0;
}

// One short-lived request/response round trip to a peer.  Returns ""
// on any failure (unreachable, blocked receiver swallowing the line,
// timeout) — the caller treats that as a dead peer / dropped packet.
std::string peer_rpc(const PeerAddr& p, const std::string& line) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return "";
  timeval tv{};
  tv.tv_sec = 0;
  tv.tv_usec = g_peer_timeout_ms * 1000;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  sockaddr_in a{};
  a.sin_family = AF_INET;
  a.sin_port = htons(p.port);
  inet_pton(AF_INET, p.host.c_str(), &a.sin_addr);
  if (connect(fd, (sockaddr*)&a, sizeof(a)) != 0) {
    close(fd);
    return "";
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  if (write(fd, line.data(), line.size()) != (ssize_t)line.size()) {
    close(fd);
    return "";
  }
  // Responses are one newline-terminated line; a DUMP reply can span
  // TCP segments, so read until the newline (or timeout/EOF) — a
  // truncated STATE would make adopt_state install a partial store.
  std::string resp;
  char buf[4096];
  while (resp.find('\n') == std::string::npos) {
    ssize_t n = read(fd, buf, sizeof(buf));
    if (n <= 0) break;
    resp.append(buf, n);
  }
  close(fd);
  if (resp.find('\n') == std::string::npos) return "";
  resp.resize(resp.find('\n'));
  while (!resp.empty() && resp.back() == '\r') resp.pop_back();
  return resp;
}

// Serialize the whole store (step-down adoption + DUMP).  Values are
// the workload's integers, so the ,=: framing never collides.
std::string state_str() {
  std::lock_guard<std::mutex> l(g_mu);
  std::ostringstream out;
  bool first = true;
  for (auto& e : g_kv) {
    if (!first) out << ",";
    out << e.first << "=" << e.second.ts << ":" << e.second.wid << ":"
        << e.second.val;
    first = false;
  }
  return out.str();
}

void adopt_state(const std::string& s) {
  std::map<std::string, Entry> kv;
  std::stringstream ss(s);
  std::string item;
  while (std::getline(ss, item, ',')) {
    if (item.empty()) continue;
    auto eq = item.find('=');
    auto c1 = item.find(':', eq);
    auto c2 = item.find(':', c1 + 1);
    Entry e;
    e.ts = atoll(item.substr(eq + 1, c1 - eq - 1).c_str());
    e.wid = atoi(item.substr(c1 + 1, c2 - c1 - 1).c_str());
    e.val = item.substr(c2 + 1);
    kv[item.substr(0, eq)] = e;
  }
  std::lock_guard<std::mutex> l(g_mu);
  // WHOLESALE replacement, not a merge: everything this node accepted
  // while it wrongly led is discarded — the lost-update bug under test.
  g_kv.swap(kv);
}

// Heartbeat + leadership thread.  Every 50 ms: beat every unblocked
// peer; then re-evaluate leadership.  A leader that sees a live
// lower-id peer steps down and adopts that peer's state.
void election_loop() {
  while (true) {
    for (auto& p : g_peers) {
      if (blocked(p.id)) continue;
      std::string resp =
          peer_rpc(p, "HB " + std::to_string(g_id) + "\n");
      if (resp == "HBACK") {
        std::lock_guard<std::mutex> l(g_mu);
        g_last_heard[p.id] = Clock::now();
      }
    }
    if (!g_quorum) {
      int lower_live = -1;
      bool was_leader;
      {
        std::lock_guard<std::mutex> l(g_mu);
        was_leader = g_leader;
        auto now = Clock::now();
        for (auto& p : g_peers) {
          if (p.id >= g_id) continue;
          auto it = g_last_heard.find(p.id);
          if (it != g_last_heard.end() &&
              now - it->second <
                  std::chrono::milliseconds(g_stale_ms)) {
            lower_live = p.id;
            break;
          }
        }
        g_leader = lower_live < 0;
      }
      if (was_leader && lower_live >= 0) {
        // Stepping down on heal: pull the surviving leader's state.
        // The distress line below is the log-file-pattern checker's
        // quarry (checker.clj:863-905's role: server-side events the
        // history can't see) — wholesale adoption is precisely the
        // moment this node's split-brain acks become lies.
        size_t local;
        {
          std::lock_guard<std::mutex> l(g_mu);
          local = g_kv.size();
        }
        if (local > 0) {
          // Gated on actually holding data: distress requires
          // something to lose.  (Boot self-election is already
          // prevented by main()'s heartbeat grace priming; what this
          // gate suppresses is the data-LESS step-down — a follower
          // that briefly self-elected during a heartbeat hiccup or a
          // partition in which it never acked a write.  Cost: a
          // split-brain loser that only served reads steps down
          // silently, so the log evidence is strictly a subset of
          // the history evidence — the checker pair in
          // suites/electd.py treats it as corroboration, not as the
          // primary verdict.)
          fprintf(stderr,
                  "electd id=%d STEPPING DOWN to leader %d: adopting "
                  "remote state wholesale (replacing %zu local "
                  "entries)\n",
                  g_id, lower_live, local);
          fflush(stderr);
        }
        for (auto& p : g_peers) {
          if (p.id != lower_live) continue;
          std::string resp =
              peer_rpc(p, "DUMP " + std::to_string(g_id) + "\n");
          if (resp.rfind("STATE", 0) == 0)
            adopt_state(resp.size() > 6 ? resp.substr(6) : "");
        }
      }
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

int majority() { return ((int)g_peers.size() + 1) / 2 + 1; }

// ABD phase 1: collect (ts, wid, val) from self + a majority.
// Returns false when too few nodes answered.
bool quorum_read(const std::string& k, Entry* out) {
  Entry best;
  {
    std::lock_guard<std::mutex> l(g_mu);
    auto it = g_kv.find(k);
    if (it != g_kv.end()) best = it->second;
  }
  int heard = 1;  // self
  for (auto& p : g_peers) {
    if (blocked(p.id)) continue;
    std::string resp = peer_rpc(
        p, "QREAD " + std::to_string(g_id) + " " + k + "\n");
    long long ts;
    int wid;
    char val[3900];
    if (sscanf(resp.c_str(), "QVAL %lld %d %3899s", &ts, &wid, val) ==
        3) {
      heard++;
      if (ts > best.ts || (ts == best.ts && wid > best.wid)) {
        best.ts = ts;
        best.wid = wid;
        best.val = strcmp(val, "__nil__") == 0 ? "" : val;
      }
    }
  }
  if (heard < majority()) return false;
  *out = best;
  return true;
}

// Appends one record durably.  Fail-stop on any I/O error: a node
// that cannot log must not ack (or serve) — dying here turns ENOSPC
// into a dead node, which the suite's fault model already covers,
// instead of into silently-volatile "durable" mode.
void wal_append(const std::string& k, long long ts, int wid,
                const std::string& v) {
  std::lock_guard<std::mutex> l(g_wal_mu);
  if (fprintf(g_wal, "%s %lld %d %s\n", k.c_str(), ts, wid,
              v.c_str()) < 0 ||
      fflush(g_wal) != 0 || fsync(fileno(g_wal)) != 0) {
    perror("electd: wal append failed, stopping");
    _exit(1);
  }
}

void local_store(const std::string& k, long long ts, int wid,
                 const std::string& v) {
  {
    std::lock_guard<std::mutex> l(g_mu);
    Entry& e = g_kv[k];
    if (ts < e.ts || (ts == e.ts && wid <= e.wid)) return;
  }
  // Durable BEFORE visible (and before the QACK/OK leaves this node):
  // once the entry is in g_kv another op can read it and ack the
  // value onward, so crashing after visibility but before the append
  // would lose an observed write even in durable mode.  The fsync
  // happens outside g_mu so a slow disk stalls only writers, not
  // reads/heartbeats.  A newer entry racing in between the append and
  // the apply just makes this record a no-op on disk and in memory —
  // replay applies with the same (ts, wid) precedence.
  if (g_wal) wal_append(k, ts, wid, v);
  std::lock_guard<std::mutex> l(g_mu);
  Entry& e = g_kv[k];
  if (ts > e.ts || (ts == e.ts && wid > e.wid)) {
    e.ts = ts;
    e.wid = wid;
    e.val = v;
  }
  if (ts > g_abd_clock) g_abd_clock = ts;
}

// Boot-time WAL replay: re-applies entries with local_store's own
// precedence (last state wins per key) and restores the clock floor
// so a restarted writer can never reuse a pre-crash timestamp.
// A kill can tear the final record (it was never fsync-acked, so
// dropping it is correct); the file is then TRUNCATED at the tear so
// the next append starts on a clean line boundary — otherwise a
// second incarnation's entries would glue onto the torn tail and a
// later replay would stop there, forgetting acked writes.
void wal_replay(const std::string& path) {
  FILE* f = fopen(path.c_str(), "r");
  if (!f) return;  // first boot: nothing yet
  char line[4600];
  char k[256], v[3900];
  long long ts;
  int wid;
  int applied = 0;
  long good_end = 0;
  while (fgets(line, sizeof(line), f)) {
    size_t n = strlen(line);
    if (n == 0 || line[n - 1] != '\n' ||
        sscanf(line, "%255s %lld %d %3899s", k, &ts, &wid, v) != 4)
      break;  // torn tail: everything before it was fsync'd whole
    good_end = ftell(f);
    std::lock_guard<std::mutex> l(g_mu);
    Entry& e = g_kv[k];
    if (ts > e.ts || (ts == e.ts && wid > e.wid)) {
      e.ts = ts;
      e.wid = wid;
      e.val = v;
    }
    if (ts > g_abd_clock) g_abd_clock = ts;
    applied++;
  }
  fclose(f);
  if (truncate(path.c_str(), good_end) != 0) {
    perror("electd: wal truncate failed, stopping");
    _exit(1);
  }
  fprintf(stderr, "electd id=%d wal replay: %d entries, clock %lld\n",
          g_id, applied, g_abd_clock);
}

// ABD phase 2: store (ts, wid, v) on self + a majority.
bool quorum_store(const std::string& k, long long ts, int wid,
                  const std::string& v) {
  local_store(k, ts, wid, v);
  int acked = 1;  // self
  for (auto& p : g_peers) {
    if (blocked(p.id)) continue;
    std::ostringstream req;
    req << "QSTORE " << g_id << " " << k << " " << ts << " " << wid
        << " " << v << "\n";
    if (peer_rpc(p, req.str()) == "QACK") acked++;
  }
  return acked >= majority();
}

void serve(int fd) {
  FILE* rf = fdopen(fd, "r");
  if (!rf) {
    close(fd);
    return;
  }
  char buf[4096];
  while (fgets(buf, sizeof(buf), rf)) {
    std::istringstream in(buf);
    std::string cmd;
    in >> cmd;
    std::string resp;
    if (cmd == "PING") {
      resp = "PONG";
    } else if (cmd == "ROLE") {
      if (g_quorum) {
        resp = "QUORUM";
      } else {
        std::lock_guard<std::mutex> l(g_mu);
        resp = g_leader ? "LEADER" : "FOLLOWER";
      }
    } else if (cmd == "HB") {
      int from;
      in >> from;
      if (blocked(from)) continue;  // partitioned: swallow, no reply
      {
        // Hearing a beat proves the sender alive — symmetric evidence
        // to getting our own beat acked.
        std::lock_guard<std::mutex> l(g_mu);
        g_last_heard[from] = Clock::now();
      }
      resp = "HBACK";
    } else if (cmd == "QREAD") {
      int from;
      std::string k;
      in >> from >> k;
      if (blocked(from)) continue;
      std::lock_guard<std::mutex> l(g_mu);
      auto it = g_kv.find(k);
      if (it == g_kv.end()) {
        resp = "QVAL 0 0 __nil__";
      } else {
        resp = "QVAL " + std::to_string(it->second.ts) + " " +
               std::to_string(it->second.wid) + " " +
               (it->second.val.empty() ? "__nil__" : it->second.val);
      }
    } else if (cmd == "QSTORE") {
      int from, wid;
      long long ts;
      std::string k, v;
      in >> from >> k >> ts >> wid >> v;
      if (blocked(from)) continue;
      local_store(k, ts, wid, v);
      resp = "QACK";
    } else if (cmd == "DUMP") {
      int from;
      in >> from;
      if (blocked(from)) continue;
      resp = "STATE " + state_str();
    } else if (cmd == "CLOCK") {
      // Admin observability: the ABD timestamp floor (replay must
      // restore it or a restarted writer can reuse pre-crash
      // timestamps and diverge replicas).
      std::lock_guard<std::mutex> l(g_mu);
      resp = "CLOCK " + std::to_string(g_abd_clock);
    } else if (cmd == "GET") {
      std::string k;
      in >> k;
      if (g_quorum) {
        Entry e;
        if (!quorum_read(k, &e)) {
          resp = "ERR noquorum";
        } else if (e.ts == 0) {
          resp = "NIL";
        } else if (!quorum_store(k, e.ts, e.wid, e.val)) {
          // Write-back failed: the read's value is not yet stable at
          // a majority, so exposing it would break atomicity.
          resp = "ERR noquorum";
        } else {
          resp = "VAL " + e.val;
        }
      } else {
        std::lock_guard<std::mutex> l(g_mu);
        if (!g_leader) {
          resp = "ERR notleader";
        } else {
          auto it = g_kv.find(k);
          resp = it == g_kv.end() || it->second.ts == 0
                     ? "NIL"
                     : ("VAL " + it->second.val);
        }
      }
    } else if (cmd == "SET") {
      std::string k, v;
      in >> k >> v;
      if (g_quorum) {
        Entry e;
        if (!quorum_read(k, &e)) {
          resp = "ERR noquorum";
        } else {
          // The new (ts, wid) pair must be UNIQUE per write: two
          // concurrent SETs through this same node share g_id, so a
          // plain e.ts + 1 would collide and leave replicas holding
          // different values under one timestamp (arrival order
          // would then decide each replica's winner — divergence).
          // A node-local monotonic clock merged with the read-phase
          // max keeps same-node writes distinct; wid breaks
          // cross-node ties.
          long long ts_new;
          {
            std::lock_guard<std::mutex> l(g_mu);
            ts_new = (e.ts > g_abd_clock ? e.ts : g_abd_clock) + 1;
            g_abd_clock = ts_new;
          }
          resp = quorum_store(k, ts_new, g_id, v) ? "OK"
                                                  : "ERR noquorum";
        }
      } else {
        std::lock_guard<std::mutex> l(g_mu);
        if (!g_leader) {
          resp = "ERR notleader";
        } else {
          Entry& e = g_kv[k];
          e.ts++;
          e.wid = g_id;
          e.val = v;
          resp = "OK";
        }
      }
    } else if (cmd == "CAS") {
      std::string k, oldv, newv;
      in >> k >> oldv >> newv;
      if (g_quorum) {
        // ABD has no conditional write: CAS requires consensus, which
        // electd does not implement.  The quorum workload is rw-only.
        resp = "ERR nocas";
      } else {
        std::lock_guard<std::mutex> l(g_mu);
        if (!g_leader) {
          resp = "ERR notleader";
        } else {
          auto it = g_kv.find(k);
          if (it == g_kv.end() || it->second.ts == 0) {
            resp = "NIL";
          } else if (it->second.val != oldv) {
            resp = "FAIL";
          } else {
            it->second.ts++;
            it->second.wid = g_id;
            it->second.val = newv;
            resp = "OK";
          }
        }
      }
    } else if (cmd == "BLOCK") {
      int id;
      in >> id;
      std::lock_guard<std::mutex> l(g_mu);
      g_blocked.insert(id);
      resp = "OK";
    } else if (cmd == "UNBLOCK") {
      std::string id;
      in >> id;
      std::lock_guard<std::mutex> l(g_mu);
      if (id == "*") g_blocked.clear();
      else g_blocked.erase(atoi(id.c_str()));
      resp = "OK";
    } else {
      resp = "ERR badcmd";
    }
    resp += "\n";
    if (write(fd, resp.data(), resp.size()) != (ssize_t)resp.size())
      break;
  }
  fclose(rf);
}

}  // namespace

int main(int argc, char** argv) {
  int port = 7400;
  std::string listen_addr = "127.0.0.1";
  std::string wal_path;
  std::string peers;  // "id@host:port,id@host:port"
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() { return std::string(argv[++i]); };
    if (a == "--port") port = atoi(next().c_str());
    else if (a == "--listen") listen_addr = next();
    else if (a == "--id") g_id = atoi(next().c_str());
    else if (a == "--peers") peers = next();
    else if (a == "--quorum") g_quorum = true;
    else if (a == "--wal") wal_path = next();
    else if (a == "--stale-ms") g_stale_ms = atoi(next().c_str());
    else if (a == "--peer-timeout-ms")
      g_peer_timeout_ms = atoi(next().c_str());
  }
  signal(SIGPIPE, SIG_IGN);

  if (!wal_path.empty()) {
    wal_replay(wal_path);
    g_wal = fopen(wal_path.c_str(), "a");
    if (!g_wal) {
      perror("wal");
      return 1;
    }
  }

  std::stringstream ps(peers);
  std::string item;
  while (std::getline(ps, item, ',')) {
    if (item.empty()) continue;
    auto at = item.find('@');
    auto colon = item.rfind(':');
    PeerAddr p;
    p.id = atoi(item.substr(0, at).c_str());
    p.host = item.substr(at + 1, colon - at - 1);
    p.port = atoi(item.substr(colon + 1).c_str());
    g_peers.push_back(p);
  }
  {
    // Boot grace: treat every lower peer as alive until proven dead,
    // so a follower doesn't claim leadership in the first beat gap.
    std::lock_guard<std::mutex> l(g_mu);
    auto now = Clock::now();
    for (auto& p : g_peers) g_last_heard[p.id] = now;
    g_leader = !g_quorum;
    for (auto& p : g_peers)
      if (p.id < g_id) g_leader = false;
  }
  std::thread(election_loop).detach();

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  inet_pton(AF_INET, listen_addr.c_str(), &addr.sin_addr);
  if (bind(srv, (sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  listen(srv, 64);
  fprintf(stderr, "electd id=%d on %s:%d (%s)\n", g_id,
          listen_addr.c_str(), port, g_quorum ? "quorum" : "unsafe");
  while (true) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    int nd = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
    std::thread(serve, fd).detach();
  }
}
