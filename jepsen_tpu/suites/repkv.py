"""repkv suite: the framework against a real REPLICATED system.

The multi-node analog of suites/kvdb.py, in the reference's canonical
suite shape (zookeeper/src/jepsen/zookeeper.clj:40-145): compile the
C++ primary/backup store (jepsen_tpu/demo/repkv.cpp) on every node, boot
the group, run a register workload where writes go to the primary and
reads go to each client's own node, inject partitions + kills, and
check linearizability on the device.

The interesting physics: repkv's replication is asynchronous (or
"sync until a peer times out" with --sync), so a partitioned backup
serves stale reads — a real, checker-visible linearizability
violation produced by a real distributed system, not a seeded fake.
`--safe-reads` routes reads to the primary too, which restores
linearizability under the same faults (the demo's control group).

Partitions use the suite's RepkvNet: the `Net` protocol implemented
with repkv's BLOCK/UNBLOCK admin commands instead of iptables — the
same declarative partition packages drive either transport.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Optional

from .. import cli as jcli
from .. import demo as _demo
from .. import client as jc
from .. import db as jdb
from .. import net as jnet
from ..checker import core as chk
from ..checker.linearizable import Linearizable
from ..checker.timeline import Timeline
from ..control import Session
from ..control import util as cutil
from ..generator.core import nemesis as gen_nemesis, phases, stagger, time_limit
from ._common import register_workload_gen
from ..history import FAIL, OK, Op
from ..models import cas_register
from ..nemesis.combined import nemesis_package

REPKV_SRC = _demo.source("repkv")
BASE_PORT = 7300


def node_index(test: dict, node: str) -> int:
    return (test.get("nodes") or []).index(node)


def _derived_base(test: dict, key: str, fallback: int) -> int:
    """Per-run base port: explicit test[key] wins; else derive
    from the store dir via the shared hashed_base_port formula
    (stable per run, distinct across concurrent runs, below the
    Linux ephemeral range — round 5: two builders sharing a
    BASE_PORT constant convicted a healthy run)."""
    explicit = test.get(key)
    if explicit is not None:
        return explicit
    seed = test.get("store-dir")
    if not seed:
        return fallback
    return cutil.hashed_base_port(seed, fallback)


def node_port(test: dict, node: str) -> int:
    return _derived_base(test, "repkv-base-port",
                         BASE_PORT) + 1 + node_index(test, node)


def node_dir(test: dict, node: str) -> str:
    root = test.get("repkv-dir", "/tmp/jepsen-repkv")
    return f"{root}/{node}"


def primary_node(test: dict) -> str:
    return (test.get("nodes") or ["n1"])[0]


def node_host(test: dict, node: str) -> str:
    """Where clients/peers dial this node: an explicit in-cluster
    address when the topology declares one (netns clusters — the
    net.py node-addresses convention), loopback in the default local
    topology, else the node's host part against real machines
    (test["repkv-local"] = False) — the kvdb-local pattern
    (suites/kvdb.py:150-158)."""
    if test.get("repkv-local", True):
        # Local topology always dials loopback, even when
        # node-addresses exist for a net implementation — in-cluster
        # aliases need not resolve from the control process.
        return "127.0.0.1"
    alias = (test.get("node-addresses") or {}).get(node)
    if alias:
        return alias
    from ..control.core import split_host_port

    host, _ = split_host_port(node)
    return host


class RepkvDB(jdb.DB):
    """Compile + daemonize one group member per node."""

    def _paths(self, test: dict, node: str) -> dict:
        d = node_dir(test, node)
        return {
            "dir": d,
            "src": f"{d}/repkv.cpp",
            "bin": f"{d}/repkv",
            "pid": f"{d}/repkv.pid",
            "log": f"{d}/repkv.log",
        }

    def setup(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec("mkdir", "-p", p["dir"])
        sess.upload(os.path.abspath(REPKV_SRC), p["src"])
        sess.exec("g++", "-O2", "-pthread", "-o", p["bin"], p["src"])
        # An interrupted earlier run leaks its daemon; a stale server
        # on our port serves foreign data -> false convictions
        # (grepkill! on setup, control/util.clj pattern).
        cutil.grepkill(sess, f"repkv --port {node_port(test, node)} ")
        # Retry the start+probe cycle (see kvdb.py setup).
        cutil.retrying_daemon_start(
            sess, lambda: self.start(test, sess, node),
            node_port(test, node), await_timeout_s=10, interval_s=0.1,
        )

    def start(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        nodes = test.get("nodes") or []
        me = node_index(test, node)
        peers = ",".join(
            f"{i}@{node_host(test, n)}:{node_port(test, n)}"
            for i, n in enumerate(nodes)
            if n != node
        )
        args = [
            "--id", str(me),
            "--port", str(node_port(test, node)),
            "--peers", peers,
        ]
        if not test.get("repkv-local", True):
            # Wildcard listen needs a routable advertised address for
            # membership views (what peers dial after failover).
            args += [
                "--listen", "0.0.0.0",
                "--advertise",
                f"{node_host(test, node)}:{node_port(test, node)}",
            ]
        if node == primary_node(test):
            args.append("--primary")
        if test.get("repkv-sync", True):
            args.append("--sync")
        cutil.start_daemon(
            sess, p["bin"], *args, pidfile=p["pid"], logfile=p["log"]
        )
        try:
            cutil.await_tcp_port(
                sess, node_port(test, node), timeout_s=10, interval_s=0.05
            )
        except Exception:  # noqa: BLE001 — best-effort, like kvdb
            pass

    def kill(self, test: dict, sess: Session, node: str) -> None:
        cutil.stop_daemon(sess, self._paths(test, node)["pid"],
                          signal="KILL")

    def pause(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec_star("bash", "-c", f"kill -STOP $(cat {p['pid']})")

    def resume(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec_star("bash", "-c", f"kill -CONT $(cat {p['pid']})")

    def primaries(self, test: dict):
        """Ask every node its ROLE (db.clj Primary, :35-42)."""
        out = []
        for node in test.get("nodes") or []:
            try:
                if _admin_round_trip(test, node, "ROLE") == "PRIMARY":
                    out.append(node)
            except OSError:
                continue
        return out

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        cutil.stop_daemon(sess, p["pid"])
        if not test.get("leave-db-running"):
            sess.exec("rm", "-rf", p["dir"])

    def log_files(self, test: dict, sess: Session, node: str):
        return [self._paths(test, node)["log"]]


class RepkvNet(jnet.Net):
    """The Net protocol over repkv's BLOCK/UNBLOCK admin commands:
    partition packages work unchanged, no iptables required."""

    def drop(self, test: dict, src: str, dest: str) -> None:
        _admin_round_trip(test, dest, f"BLOCK {node_index(test, src)}",
                          timeout=2.0)

    def heal(self, test: dict) -> None:
        for node in test.get("nodes") or []:
            try:
                _admin_round_trip(test, node, "UNBLOCK *", timeout=2.0)
            except OSError:
                continue  # killed node: nothing to heal


def _admin_round_trip(test: dict, node: str, line: str,
                      timeout: float = 1.0) -> str:
    with socket.create_connection(
        (node_host(test, node), node_port(test, node)), timeout=timeout
    ) as s:
        f = s.makefile("rw", newline="\n")
        f.write(line + "\n")
        f.flush()
        return (f.readline() or "").strip()


def discover_primary(test: dict) -> str:
    """The first node whose ROLE is PRIMARY, else the static first
    node (clients rediscover after failover)."""
    for node in test.get("nodes") or []:
        try:
            if _admin_round_trip(test, node, "ROLE") == "PRIMARY":
                return node
        except OSError:
            continue
    return primary_node(test)


class RepkvMembership:
    """Failover state machine for the membership nemesis
    (nemesis/membership.py): node views are each node's ROLE; when the
    merged view shows no live primary, propose promoting the first
    live backup; the op resolves once that node reports PRIMARY."""

    def node_view(self, test, session, node):
        try:
            return _admin_round_trip(test, node, "ROLE")
        except OSError:
            return "DOWN"

    def merge_views(self, test):
        return dict(self.node_views)

    def fs(self):
        return {"promote"}

    def setup(self, test):
        return self

    def op(self, test):
        from ..generator.core import PENDING

        view = self.view or {}
        if "PRIMARY" in view.values():
            return PENDING
        backups = [n for n, r in view.items() if r == "BACKUP"]
        if not backups or self.pending:
            return PENDING
        return {"type": "info", "f": "promote", "value": backups[0]}

    def invoke(self, test, op):
        try:
            resp = _admin_round_trip(test, op.value, "PROMOTE")
        except OSError as e:
            resp = f"error: {e}"
        return op.replace(ext=dict(op.ext, resp=resp))

    def resolve(self, test):
        return False

    def resolve_op(self, test, pair):
        inv, _ = pair
        return (self.view or {}).get(inv.value) == "PRIMARY"

    def teardown(self, test):
        pass


class RepkvGrowShrink:
    """Grow/shrink membership state machine over repkv's real
    JOIN/LEAVE (the reference's core membership use,
    nemesis/membership.clj:1-47 + membership/state.clj:20-57): node
    views are each node's VIEW+ROLE response; the merged view is the
    highest view id seen; ops alternate naturally — re-join whichever
    node the group lost, else shrink by removing a live backup.

    The physics this drives (jepsen_tpu/demo/repkv.cpp): a LEAVEd backup is
    never told, keeps its stale view, and serves reads frozen at
    removal time — under unsafe reads the checker convicts those, and
    the stale-read screen (checker/refute.py) names the exact read."""

    def __init__(self, min_members: int = 2):
        self.min_members = min_members

    # -- MembershipState protocol -----------------------------------------

    def setup(self, test):
        return self

    def node_view(self, test, session, node):
        try:
            resp = _admin_round_trip(test, node, "VIEW")
            parts = resp.split()
            if not parts or parts[0] != "VIEW":
                return None
            members = parts[2] if len(parts) > 2 else ""
            role = _admin_round_trip(test, node, "ROLE")
            return {
                "view-id": int(parts[1]),
                "members": tuple(sorted(m for m in members.split(",") if m)),
                "role": role,
            }
        except (OSError, ValueError):
            return None

    def merge_views(self, test):
        best = None
        for v in self.node_views.values():
            if v and (best is None or v["view-id"] > best["view-id"]):
                best = v
        return best

    def fs(self):
        return {"join", "leave"}

    def _member_ids(self, view) -> dict:
        return {
            m.split("@", 1)[0]: m.split("@", 1)[1]
            for m in (view.get("members") or ())
            if m
        }

    def op(self, test):
        from ..generator.core import PENDING

        view = self.view
        if view is None or self.pending:
            return PENDING
        members = self._member_ids(view)
        nodes = test.get("nodes") or []
        all_ids = {str(i): n for i, n in enumerate(nodes)}
        removed = sorted(i for i in all_ids if i not in members)
        if removed:
            i = removed[0]
            node = all_ids[i]
            addr = f"{node_host(test, node)}:{node_port(test, node)}"
            return {"type": "info", "f": "join", "value": (int(i), addr)}
        if len(members) <= self.min_members:
            return PENDING
        primary_ids = {
            str(node_index(test, n))
            for n, v in self.node_views.items()
            if v and v.get("role") == "PRIMARY"
        }
        cands = sorted(i for i in members if i not in primary_ids)
        if not cands:
            return PENDING
        return {"type": "info", "f": "leave", "value": int(cands[-1])}

    def invoke(self, test, op):
        primary = discover_primary(test)
        try:
            if op.f == "join":
                i, addr = op.value
                resp = _admin_round_trip(test, primary,
                                         f"JOIN {i} {addr}", timeout=2.0)
            else:
                resp = _admin_round_trip(test, primary,
                                         f"LEAVE {op.value}", timeout=2.0)
        except OSError as e:
            resp = f"error: {e}"
        return op.replace(ext=dict(op.ext, resp=resp))

    def resolve(self, test):
        return False

    def resolve_op(self, test, pair):
        inv, comp = pair
        resp = (comp.ext or {}).get("resp", "")
        if not resp or resp.startswith("error") or resp.startswith("ERR"):
            # Rejected, unreachable, or the server died mid-round-trip
            # (empty reply): the change never applied, so no future
            # view can confirm it — abandon rather than wedge pending.
            return True
        view = self.view
        if view is None:
            return False
        members = self._member_ids(view)
        if inv.f == "join":
            return str(inv.value[0]) in members
        return str(inv.value) not in members

    def teardown(self, test):
        pass


class RepkvClient(jc.Client):
    """One connection to the client's own node (reads) and one to the
    primary (writes), unless safe-reads routes everything primary-ward.
    Writes rediscover the primary on open (failover support)."""

    def __init__(self, key: str = "x"):
        self.key = key
        self.read_sock = None
        self.write_sock = None
        self.node: Any = None

    def open(self, test, node):
        c = type(self)(self.key)
        c.node = node
        primary = (
            discover_primary(test)
            if test.get("repkv-failover")
            else primary_node(test)
        )
        read_node = (
            primary if test.get("repkv-safe-reads") else node
        )
        c.read_sock = self._dial(test, read_node)
        c.write_sock = self._dial(test, primary)
        return c

    def _dial(self, test, node):
        s = socket.create_connection(
            (node_host(test, node), node_port(test, node)), timeout=2.0
        )
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s.makefile("rw", newline="\n")

    def _round_trip(self, f, line: str) -> str:
        f.write(line + "\n")
        f.flush()
        resp = f.readline()
        if not resp:
            raise ConnectionError("repkv closed the connection")
        return resp.strip()

    def invoke(self, test, op):
        if op.f == "read":
            resp = self._round_trip(self.read_sock, f"GET {self.key}")
            if resp == "NIL":
                return op.complete(OK, value=None)
            return op.complete(OK, value=int(resp.split(" ", 1)[1]))
        if op.f == "write":
            resp = self._round_trip(
                self.write_sock, f"SET {self.key} {op.value}"
            )
            if resp == "OK":
                return op.complete(OK)
            return op.complete(FAIL, error=resp)
        # cas
        old, new = op.value
        resp = self._round_trip(
            self.write_sock, f"CAS {self.key} {old} {new}"
        )
        if resp == "OK":
            return op.complete(OK)
        if resp in ("FAIL", "NIL"):
            return op.complete(FAIL)
        return op.complete(FAIL, error=resp)

    def close(self, test):
        for f in (self.read_sock, self.write_sock):
            try:
                if f is not None:
                    f.close()
            except OSError:
                pass


class RepkvSetClient(RepkvClient):
    """Set face: atomic ADDs at the primary, MEMBERS reads from the
    client's own node.  A partitioned backup's list freezes, so its
    reads omit acknowledged elements — exactly the stale reads the
    set-full checker's per-element lifecycle analysis measures
    (checker.clj:487-612), and convicts when linearizable=True."""

    def __init__(self, key: str = "s"):
        super().__init__(key)

    def invoke(self, test, op):
        if op.f == "add":
            resp = self._round_trip(self.write_sock,
                                    f"ADD {self.key} {op.value}")
            if resp == "OK":
                return op.complete(OK)
            return op.complete(FAIL, error=resp)
        resp = self._round_trip(self.read_sock, f"MEMBERS {self.key}")
        if resp == "NIL":
            return op.complete(OK, value=[])
        vals = resp.split(" ", 1)[1]
        return op.complete(
            OK, value=[int(v) for v in vals.split(",") if v]
        )


def repkv_test(opts: dict) -> dict:
    """Test-map assembly (zookeeper.clj:112-137 shape)."""
    import random

    nodes = (opts.get("nodes") or ["n1", "n2", "n3"])[:5]
    # NB: an explicit empty list means "no faults" — `or` would
    # silently substitute the default (the logd bug, round 3).
    faults = set(
        opts["faults"] if opts.get("faults") is not None
        else ["partition"]
    )
    rng = random.Random(opts.get("seed"))
    workload_name = opts.get("workload", "register")
    if workload_name == "set":
        from ..workloads import register_set

        def workload_gen():
            return register_set.generator(
                full=True, read_fraction=0.5, rng=rng
            )

        client = RepkvSetClient()
        checkers = {
            # linearizable=True: a read invoked after an add completed
            # that omits the element is a violation — which is what
            # unsafe (own-node) reads against lagging replication
            # produce.  The safe-reads control passes the same bar.
            "set-full": chk.SetFull(linearizable=True),
        }
    else:
        workload_gen = register_workload_gen(rng)
        client = RepkvClient()
        checkers = {
            "linear": Linearizable(
                algorithm=opts.get("algorithm", "wgl-tpu"),
                time_limit_s=60.0,
            ),
        }

    pkg_opts = {
        "faults": faults,
        "interval": opts.get("interval", 3.0),
        "partition": {"targets": opts.get("partition-targets",
                                          ["one", "majority"])},
    }
    if "membership" in faults:
        # Failover: the membership state machine watches node ROLEs and
        # promotes a live backup whenever the primary disappears.
        pkg_opts["membership"] = {
            "state": RepkvMembership(),
            "view-interval": opts.get("view-interval", 0.5),
        }
    pkg = nemesis_package(pkg_opts)
    if "grow-shrink" in faults:
        # Real JOIN/LEAVE against the process group, composed with
        # whatever other faults run (membership.clj's core use).
        from ..nemesis.combined import compose_packages
        from ..nemesis.membership import membership_package

        gs = membership_package({
            "faults": {"membership"},
            "interval": opts.get("interval", 3.0),
            "membership": {
                "state": RepkvGrowShrink(
                    min_members=opts.get("min-members", 2)
                ),
                "view-interval": opts.get("view-interval", 0.5),
            },
        })
        pkg = compose_packages([pkg, gs])
    generator = time_limit(
        opts.get("time-limit", 15.0),
        gen_nemesis(
            pkg["generator"],
            stagger(1.0 / opts.get("rate", 100), workload_gen()),
        ),
    )
    if pkg.get("final-generator"):
        generator = phases(generator, gen_nemesis(pkg["final-generator"]))

    store_root = os.path.abspath(opts.get("store-dir") or "store")
    # Composed with timeline + stats like the reference's canonical
    # test maps (zookeeper.clj:112-137): every run leaves a browsable
    # trail, convicted or not.
    checkers.update({"timeline": Timeline(), "stats": chk.Stats()})
    test = {
        "name": f"repkv-{workload_name}",
        "nodes": nodes,
        "db": RepkvDB(),
        "net": RepkvNet(),
        "client": client,
        "nemesis": pkg["nemesis"],
        "generator": generator,
        "model": cas_register(),
        "checker": chk.compose(checkers),
        "repkv-sync": opts.get("sync", True),
        "repkv-safe-reads": opts.get("safe-reads", False),
        "repkv-failover": "membership" in faults,
        "repkv-dir": opts.get("repkv-dir") or os.path.join(
            store_root, "repkv-data"
        ),
        "repkv-base-port": cutil.hashed_base_port(store_root,
                                                  BASE_PORT),
    }
    if workload_name == "set":
        # set-full needs reads AFTER the last add to witness every
        # element's fate (trailing adds otherwise leave the verdict
        # unknown) — register_set's until-ok final-read
        # (generator.clj:1470).
        from ..workloads import register_set

        test["final-generator"] = time_limit(
            opts.get("final-time-limit", 20.0),
            stagger(0.05, register_set.final_generator()),
        )
    return test


def live_suite() -> dict:
    """Adapter for `jepsen monitor --suite repkv` (monitor/live.py).
    Safe-reads + sync replication — the suite's linearizable control
    configuration — so the standing verdict watches for regressions
    instead of re-demonstrating the known stale-read anomaly."""

    def test(opts: dict) -> dict:
        store_root = os.path.abspath(opts.get("store-dir") or "store")
        return jcli.localize_test({
            "name": "repkv-live",
            "nodes": list(opts.get("nodes") or ["n1", "n2", "n3"])[:5],
            "db": RepkvDB(),
            "net": RepkvNet(),
            "repkv-sync": True,
            "repkv-safe-reads": True,
            "repkv-dir": os.path.join(store_root, "repkv-data"),
            "repkv-base-port": cutil.hashed_base_port(store_root,
                                                      BASE_PORT),
            "store-dir": store_root,
        })

    return {
        "name": "repkv",
        "test": test,
        "client": lambda test, key: RepkvClient(key=f"mon{key}"),
        "node": lambda test, key: test["nodes"][key % len(test["nodes"])],
        "port": node_port,
        "model": cas_register,
        "with_cas": True,
    }


def _extra_opts(p) -> None:
    p.add_argument("--faults", action="append", default=None,
                   choices=["partition", "kill", "pause", "membership",
                            "grow-shrink"])
    p.add_argument("--rate", type=float, default=100.0)
    p.add_argument("--interval", type=float, default=3.0)
    p.add_argument("--workload", default="register",
                   choices=["register", "set"],
                   help="register: linearizable reads/writes/cas; "
                   "set: atomic adds + member reads under the "
                   "set-full lifecycle analysis")
    p.add_argument("--no-sync", dest="sync", action="store_false",
                   help="fully asynchronous replication")
    p.add_argument("--safe-reads", action="store_true",
                   help="route reads to the primary (the control group)")
    p.add_argument("--algorithm", default="wgl-tpu",
                   choices=["cpu", "wgl", "wgl-tpu"])


def main(argv=None) -> int:
    def suite(opt_map: dict) -> dict:
        return jcli.localize_test(repkv_test(opt_map))

    def all_suites(opt_map: dict):
        """test-all: the stale-read conviction run and its safe-reads
        control group (cli.clj:501-529 pattern)."""
        for workload in ("register", "set"):
            for safe in (False, True):
                o = dict(opt_map, workload=workload)
                o["safe-reads"] = safe
                t = jcli.localize_test(repkv_test(o))
                t["name"] = (f"repkv-{workload}-safe-reads" if safe
                             else f"repkv-{workload}-unsafe")
                yield t

    parser = jcli.single_test_cmd(
        suite, name="repkv", extra_opts=_extra_opts,
        tests_fn=all_suites,
    )
    return jcli.run(parser, argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
