"""electd suite: split-brain leader election against a real system.

The classic shape of the reference's published findings (the
partition-induced split-brain write loss its suites were built to
catch): jepsen_tpu/demo/electd.cpp elects a leader by
lowest-reachable-id heartbeats with no terms and no fencing.  A
partition isolating the lowest-id node leaves BOTH sides with a
self-believed leader; both acknowledge writes; on heal the higher-id
leader steps down and adopts the survivor's state wholesale, silently
discarding every write it acked during the split.  The linearizability
checker (checker/linearizable.py — the knossos equivalent,
checker.clj:202-233) convicts those acked-then-lost updates.

The control group (--quorum) ignores leadership entirely and runs ABD
majority reads/writes — linearizable by construction — so the SAME
partition schedule that convicts unsafe mode leaves quorum mode valid.
ABD covers read/write registers only (CAS needs consensus, which
electd deliberately lacks), so the quorum workload is rw-only; the
unsafe workload includes CAS.

Second experiment (crash amnesia): ABD's guarantee assumes replicas
remember their state across failures.  Volatile quorum mode under
kill faults reboots replicas empty, so a majority can later miss an
acked write — the checker convicts.  --durable gives each node a
fsync'd write-ahead log (electd --wal) replayed at boot, and the same
kill schedule stays valid.  The experiment matrix:

    unsafe  + partition            -> split-brain conviction
    quorum  + partition            -> valid       (control for #1)
    quorum  + kill                 -> amnesia conviction
    quorum  + kill + --durable     -> valid       (control for #3)

Partitions use ElectdNet: the `Net` protocol over electd's
BLOCK/UNBLOCK admin commands (the suites/repkv.py pattern) — the same
declarative partition packages drive either transport, and the netns
cluster can substitute kernel-enforced routes.
"""

from __future__ import annotations

import os
import socket
from typing import Any

from .. import cli as jcli
from .. import client as jc
from .. import db as jdb
from .. import demo as _demo
from .. import net as jnet
from ..checker import core as chk
from ..checker.linearizable import Linearizable
from ..checker.timeline import Timeline
from ..control import Session
from ..control import util as cutil
from ..generator.core import (
    nemesis as gen_nemesis,
    phases,
    stagger,
    time_limit,
)
from ._common import register_workload_gen
from ..history import FAIL, INFO, OK
from ..models import cas_register
from ..nemesis.combined import nemesis_package

ELECTD_SRC = _demo.source("electd")
BASE_PORT = 7500


def node_index(test: dict, node: str) -> int:
    return (test.get("nodes") or []).index(node)


def _derived_base(test: dict, key: str, fallback: int) -> int:
    """Per-run base port: explicit test[key] wins; else derive
    from the store dir via the shared hashed_base_port formula
    (stable per run, distinct across concurrent runs, below the
    Linux ephemeral range — round 5: two builders sharing a
    BASE_PORT constant convicted a healthy run)."""
    explicit = test.get(key)
    if explicit is not None:
        return explicit
    seed = test.get("store-dir")
    if not seed:
        return fallback
    return cutil.hashed_base_port(seed, fallback)


def node_port(test: dict, node: str) -> int:
    return _derived_base(test, "electd-base-port",
                         BASE_PORT) + 1 + node_index(test, node)


def node_dir(test: dict, node: str) -> str:
    root = test.get("electd-dir", "/tmp/jepsen-electd")
    return f"{root}/{node}"


def node_host(test: dict, node: str) -> str:
    if test.get("electd-local", True):
        return "127.0.0.1"
    alias = (test.get("node-addresses") or {}).get(node)
    if alias:
        return alias
    from ..control.core import split_host_port

    host, _ = split_host_port(node)
    return host


def _admin_round_trip(test: dict, node: str, line: str,
                      timeout: float = 1.0) -> str:
    with socket.create_connection(
        (node_host(test, node), node_port(test, node)), timeout=timeout
    ) as s:
        f = s.makefile("rw", newline="\n")
        f.write(line + "\n")
        f.flush()
        return (f.readline() or "").strip()


class ElectdDB(jdb.DB):
    """Compile + daemonize one election group member per node."""

    def _paths(self, test: dict, node: str) -> dict:
        d = node_dir(test, node)
        return {
            "dir": d,
            "src": f"{d}/electd.cpp",
            "bin": f"{d}/electd",
            "pid": f"{d}/electd.pid",
            "log": f"{d}/electd.log",
        }

    def setup(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec("mkdir", "-p", p["dir"])
        sess.upload(os.path.abspath(ELECTD_SRC), p["src"])
        sess.exec("g++", "-O2", "-pthread", "-o", p["bin"], p["src"])
        # An interrupted earlier run leaks its daemon; a stale server
        # on our port serves foreign data -> false convictions
        # (grepkill! on setup, control/util.clj pattern).
        cutil.grepkill(sess, f"electd --port {node_port(test, node)} ")
        # Retry the start+probe cycle (see kvdb.py setup).
        cutil.retrying_daemon_start(
            sess, lambda: self.start(test, sess, node),
            node_port(test, node), await_timeout_s=10, interval_s=0.1,
        )

    def start(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        nodes = test.get("nodes") or []
        me = node_index(test, node)
        peers = ",".join(
            f"{i}@{node_host(test, n)}:{node_port(test, n)}"
            for i, n in enumerate(nodes)
            if n != node
        )
        args = [
            "--id", str(me),
            "--port", str(node_port(test, node)),
            "--peers", peers,
            "--stale-ms", str(test.get("electd-stale-ms", 400)),
        ]
        if not test.get("electd-local", True):
            args += ["--listen", "0.0.0.0"]
        if test.get("electd-quorum"):
            args.append("--quorum")
        if test.get("electd-durable"):
            args += ["--wal", f"{p['dir']}/wal"]
        cutil.start_daemon(
            sess, p["bin"], *args, pidfile=p["pid"], logfile=p["log"]
        )
        try:
            cutil.await_tcp_port(
                sess, node_port(test, node), timeout_s=10, interval_s=0.05
            )
        except Exception:  # noqa: BLE001 — best-effort, like kvdb
            pass

    def kill(self, test: dict, sess: Session, node: str) -> None:
        cutil.stop_daemon(sess, self._paths(test, node)["pid"],
                          signal="KILL")

    def primaries(self, test: dict):
        out = []
        for node in test.get("nodes") or []:
            try:
                if _admin_round_trip(test, node, "ROLE") == "LEADER":
                    out.append(node)
            except OSError:
                continue
        return out

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        cutil.stop_daemon(sess, p["pid"])
        if not test.get("leave-db-running"):
            sess.exec("rm", "-rf", p["dir"])

    def log_files(self, test: dict, sess: Session, node: str):
        return [self._paths(test, node)["log"]]


class ElectdNet(jnet.Net):
    """The Net protocol over electd's BLOCK/UNBLOCK admin commands."""

    def drop(self, test: dict, src: str, dest: str) -> None:
        _admin_round_trip(test, dest, f"BLOCK {node_index(test, src)}",
                          timeout=2.0)

    def heal(self, test: dict) -> None:
        for node in test.get("nodes") or []:
            try:
                _admin_round_trip(test, node, "UNBLOCK *", timeout=2.0)
            except OSError:
                continue  # killed node: nothing to heal


class ElectdClient(jc.Client):
    """Talks ONLY to its own node — the reference suites' canonical
    topology (client i bound to node i).  A node that does not
    currently claim leadership answers ERR notleader and the op fails
    cleanly; when a partition makes the client's node promote itself,
    this client's writes land on that side's leader.  That bound-
    client traffic is what turns a split brain into acked-then-lost
    updates the checker can convict — a discovery client that chased
    "the" leader cluster-wide would pile every op onto the surviving
    side and hide the bug.

    Completion semantics: a response is definitive (OK -> ok,
    FAIL/NIL/notleader -> fail: the op certainly did not apply).  A
    quorum timeout on a mutation is indeterminate — ABD phase 2 may
    have stored the value on a minority that a later read write-back
    resurrects — so SET/CAS map noquorum and dead connections to
    info, never fail.  Reads have no effect and may fail freely.
    """

    def __init__(self, key: str = "x"):
        self.key = key
        self.sock = None
        self.node: Any = None

    def open(self, test, node):
        c = ElectdClient(self.key)
        c.node = node
        try:
            c.sock = self._dial(test, node)
        except OSError:
            c.sock = None
        return c

    def _dial(self, test, node):
        s = socket.create_connection(
            (node_host(test, node), node_port(test, node)), timeout=2.0
        )
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s.makefile("rw", newline="\n")

    def _round_trip(self, line: str) -> str:
        if self.sock is None:
            raise ConnectionError("no connection")
        self.sock.write(line + "\n")
        self.sock.flush()
        resp = self.sock.readline()
        if not resp:
            raise ConnectionError("electd closed the connection")
        return resp.strip()

    def _req(self, test, line: str, retry: bool) -> str:
        """One request, optionally with a single redial of the SAME
        node (covers a killed-and-restarted server, never another
        node).  Mutations must NOT retry: the first attempt may have
        applied before the connection died, and a resend that answers
        notleader/FAIL would then misclassify an applied op as failed
        — the retry is for reads only, mutations surface the OSError
        so invoke() completes them info."""
        try:
            return self._round_trip(line)
        except OSError:
            if not retry:
                raise
            self.sock = self._dial(test, self.node)
            return self._round_trip(line)

    def invoke(self, test, op):
        mutation = op.f in ("write", "cas")
        try:
            if op.f == "read":
                resp = self._req(test, f"GET {self.key}", retry=True)
            elif op.f == "write":
                resp = self._req(test, f"SET {self.key} {op.value}",
                                 retry=False)
            else:
                old, new = op.value
                resp = self._req(test, f"CAS {self.key} {old} {new}",
                                 retry=False)
        except OSError as e:
            try:
                # Dead socket: leave a fresh connection for the next op
                # (the interpreter reuses this client after an info).
                self.sock = self._dial(test, self.node)
            except OSError:
                self.sock = None
            if mutation:
                return op.complete(INFO, error=str(e))
            return op.complete(FAIL, error=str(e))

        if op.f == "read":
            if resp == "NIL":
                # The EMPTY register is an observation, not ignorance:
                # the model treats a None read as unconstrained (the
                # knossos convention for "value not recorded"), which
                # would let a post-wipe NIL read linearize anywhere.
                # Encoding empty as the sentinel 0 — with the model's
                # initial value 0 and workload values starting at 1 —
                # makes crash amnesia (NIL after an acked write)
                # convictable.
                return op.complete(OK, value=0)
            if resp.startswith("VAL "):
                return op.complete(OK, value=int(resp.split(" ", 1)[1]))
            return op.complete(FAIL, error=resp)
        if resp == "OK":
            return op.complete(OK)
        if resp in ("FAIL", "NIL") or resp == "ERR notleader":
            return op.complete(FAIL, error=resp)
        if mutation:
            # noquorum / unknown: phase 2 may have partially stored.
            return op.complete(INFO, error=resp)
        return op.complete(FAIL, error=resp)

    def close(self, test):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass


def electd_test(opts: dict) -> dict:
    """Test-map assembly (zookeeper.clj:112-137 shape)."""
    import random

    nodes = (opts.get("nodes") or ["n1", "n2", "n3"])[:5]
    faults = set(
        opts["faults"] if opts.get("faults") is not None
        else ["partition"]
    )
    quorum = bool(opts.get("quorum"))
    if opts.get("durable") and not quorum:
        # The WAL logs the quorum path (local_store); unsafe-mode
        # writes mutate directly and step-down adoption discards
        # entries an append-only log cannot un-write.  Refuse rather
        # than hand out a durability flag that logs nothing.
        raise ValueError("--durable requires --quorum (the WAL covers "
                         "the ABD path; unsafe mode is volatile by "
                         "design)")
    rng = random.Random(opts.get("seed"))
    # ABD is rw-only (CAS needs consensus); CAS exercises the unsafe
    # leader path.
    workload_gen = register_workload_gen(rng, with_cas=not quorum)

    pkg = nemesis_package({
        "faults": faults,
        "interval": opts.get("interval", 3.0),
        # isolate-one partitions: the split-brain trigger is the
        # lowest-id node landing alone, which "one" hits 1/n of the
        # time per cycle.
        "partition": {"targets": opts.get("partition-targets",
                                          ["one", "majority"])},
    })
    generator = time_limit(
        opts.get("time-limit", 15.0),
        gen_nemesis(
            pkg["generator"],
            stagger(1.0 / opts.get("rate", 100), workload_gen()),
        ),
    )
    if pkg.get("final-generator"):
        generator = phases(generator, gen_nemesis(pkg["final-generator"]))

    store_root = os.path.abspath(opts.get("store-dir") or "store")
    return {
        "name": "electd-register",
        "nodes": nodes,
        "db": ElectdDB(),
        "net": ElectdNet(),
        "client": ElectdClient(),
        "nemesis": pkg["nemesis"],
        "generator": generator,
        # Initial value 0 = the sentinel the client reports for NIL
        # reads (see ElectdClient.invoke): an empty register is a
        # checkable observation, not an unconstrained read.
        "model": cas_register(0),
        # The reference's canonical test-map shape composes the safety
        # checker with timeline + stats renders (zookeeper.clj:112-137)
        # so every run leaves a browsable trail, convicted or not.
        "checker": chk.compose({
            "linear": Linearizable(
                algorithm=opts.get("algorithm", "wgl-tpu"),
                time_limit_s=60.0,
            ),
            # Server-side evidence the history can't carry
            # (checker.clj:863-905's role): a step-down's wholesale
            # state adoption is the moment split-brain acks become
            # lies, and electd logs it.  Quorum mode never elects, so
            # the control group can't match.
            "log-step-down": chk.LogFilePattern(
                r"STEPPING DOWN .* wholesale", "electd.log"
            ),
            "timeline": Timeline(),
            "stats": chk.Stats(),
        }),
        "electd-quorum": quorum,
        "electd-durable": bool(opts.get("durable")),
        "electd-stale-ms": opts.get("stale-ms", 400),
        "electd-dir": opts.get("electd-dir") or os.path.join(
            store_root, "electd-data"
        ),
        "electd-base-port": cutil.hashed_base_port(store_root, BASE_PORT),
    }


def live_suite() -> dict:
    """Adapter for `jepsen monitor --suite electd` (monitor/live.py).
    Quorum + durable mode: ABD majority reads/writes over a fsync'd
    WAL are linearizable by construction, so the standing verdict
    should stay proven across partitions and kills — the monitor is
    watching for regressions, not demonstrating the known split-brain.
    ABD has no CAS, and values must stay >= 1 (the client reports an
    empty register as the sentinel 0; a written 0 would alias it)."""

    def test(opts: dict) -> dict:
        store_root = os.path.abspath(opts.get("store-dir") or "store")
        return jcli.localize_test({
            "name": "electd-live",
            "nodes": list(opts.get("nodes") or ["n1", "n2", "n3"])[:5],
            "db": ElectdDB(),
            "net": ElectdNet(),
            "electd-quorum": True,
            "electd-durable": True,
            "electd-dir": os.path.join(store_root, "electd-data"),
            "electd-base-port": cutil.hashed_base_port(store_root,
                                                       BASE_PORT),
            "store-dir": store_root,
        })

    return {
        "name": "electd",
        "test": test,
        "client": lambda test, key: ElectdClient(key=f"mon{key}"),
        "node": lambda test, key: test["nodes"][key % len(test["nodes"])],
        "port": node_port,
        "model": lambda: cas_register(0),
        "with_cas": False,
        "values": (1, 6),
    }


def _extra_opts(p) -> None:
    p.add_argument("--faults", action="append", default=None,
                   choices=["partition", "kill"])
    p.add_argument("--rate", type=float, default=100.0)
    p.add_argument("--interval", type=float, default=3.0)
    p.add_argument("--quorum", action="store_true",
                   help="ABD majority reads/writes (the partition "
                        "control group; volatile under kill faults)")
    p.add_argument("--durable", action="store_true",
                   help="fsync'd per-node WAL replayed at boot (the "
                        "kill-fault control group for --quorum)")
    p.add_argument("--stale-ms", type=int, default=400)
    p.add_argument("--algorithm", default="wgl-tpu",
                   choices=["cpu", "wgl", "wgl-tpu"])


def main(argv=None) -> int:
    def suite(opt_map: dict) -> dict:
        return jcli.localize_test(electd_test(opt_map))

    def all_suites(opt_map: dict):
        """test-all: the split-brain conviction run and its ABD quorum
        control group (cli.clj:501-529 pattern)."""
        for quorum in (False, True):
            o = dict(opt_map, quorum=quorum)
            t = jcli.localize_test(electd_test(o))
            t["name"] = ("electd-register-quorum" if quorum
                         else "electd-register-unsafe")
            yield t

    parser = jcli.single_test_cmd(
        suite, name="electd", extra_opts=_extra_opts,
        tests_fn=all_suites,
    )
    return jcli.run(parser, argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
