"""End-to-end test suites against real systems.

Equivalent of the reference's per-database projects (SURVEY.md §2.5 —
zookeeper/, etcd/, ...): each suite module provides a DB
implementation, a network client, workload assembly, and a CLI `main`,
following the zookeeper/src/jepsen/zookeeper.clj shape.
"""

from . import kvdb

__all__ = ["electd", "kvdb", "logd", "repkv", "txnd"]


def __getattr__(name):
    # Lazy: electd/repkv/logd/txnd pull in checker stacks; importing
    # the package should not.
    if name in ("electd", "logd", "repkv", "txnd"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
