"""logd suite: the kafka workload against a real C++ log broker.

The reference's hardest checker was built against real Kafka
(jepsen/src/jepsen/tests/kafka.clj:24-180); round 2's port fed it only
an in-memory log with injected fault modes.  This suite closes that
gap (VERDICT r2 "missing" #5): jepsen_tpu/demo/logd.cpp is a real process
with a real write-behind WAL, compiled on the node through the control
plane, daemonized, and killed mid-run — and the kill itself
manufactures the anomalies (acked-but-unflushed records vanish; their
offsets get reused after restart), so the checker's lost-write and
inconsistent-offsets findings come from genuine crash physics, not
seeded faults.  --sync (logd-sync) is the control group: inline WAL
flush before ack, kills lose nothing, the checker passes.

Suite shape follows suites/kvdb.py; the workload (generator, op
grammar, checker) is workloads/kafka.py unchanged — only the client is
new, speaking logd's line protocol with Kafka consumer semantics
(client-side positions, subscribe/assign, txn COMMIT markers).
"""

from __future__ import annotations

import logging
import os
import socket
from typing import Any

from .. import cli as jcli
from .. import demo as _demo
from .. import client as jc
from .. import db as jdb
from ..control import Session
from ..control import util as cutil
from ..generator.core import time_limit
from ..history import FAIL, INFO, OK
from ..workloads import kafka as kafka_wl
from ..workloads import queue as queue_wl

log = logging.getLogger(__name__)

LOGD_SRC = _demo.source("logd")
BASE_PORT = 7520


def _derived_base(test: dict, key: str, fallback: int) -> int:
    """Per-run base port: explicit test[key] wins; else derive
    from the store dir via the shared hashed_base_port formula
    (stable per run, distinct across concurrent runs, below the
    Linux ephemeral range — round 5: two builders sharing a
    BASE_PORT constant convicted a healthy run)."""
    explicit = test.get(key)
    if explicit is not None:
        return explicit
    seed = test.get("store-dir")
    if not seed:
        return fallback
    return cutil.hashed_base_port(seed, fallback)


def node_port(test: dict) -> int:
    return _derived_base(test, "logd-port", BASE_PORT)


def node_dir(test: dict, node: str) -> str:
    root = test.get("logd-dir", "/tmp/jepsen-logd")
    return f"{root}/{node}"


class LogdDB(jdb.DB):
    """Compile + daemonize the broker; kill/restart support for the DB
    nemesis (the fault that makes this suite interesting)."""

    def _paths(self, test: dict, node: str) -> dict:
        d = node_dir(test, node)
        return {
            "dir": d,
            "data": f"{d}/data",
            "src": f"{d}/logd.cpp",
            "bin": f"{d}/logd",
            "pid": f"{d}/logd.pid",
            "log": f"{d}/logd.log",
        }

    def setup(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec("mkdir", "-p", p["dir"])
        sess.upload(os.path.abspath(LOGD_SRC), p["src"])
        sess.exec("g++", "-O2", "-pthread", "-o", p["bin"], p["src"])
        # An interrupted earlier run leaks its daemon; a stale server
        # on our port serves foreign data -> false convictions
        # (grepkill! on setup, control/util.clj pattern).
        cutil.grepkill(sess, f"logd --port {node_port(test)} ")
        # Retry the start+probe cycle (see kvdb.py setup).
        cutil.retrying_daemon_start(
            sess, lambda: self.start(test, sess, node),
            node_port(test), await_timeout_s=10, interval_s=0.1,
        )

    def start(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        args = [
            "--port", str(node_port(test)),
            "--dir", p["data"],
            "--flush-ms", str(test.get("logd-flush-ms", 75)),
        ]
        if test.get("logd-sync"):
            args.append("--sync")
        cutil.start_daemon(
            sess, p["bin"], *args, pidfile=p["pid"], logfile=p["log"]
        )
        try:
            cutil.await_tcp_port(
                sess, node_port(test), timeout_s=10, interval_s=0.05
            )
        except Exception:  # noqa: BLE001 — best-effort, like kvdb
            pass

    def kill(self, test: dict, sess: Session, node: str) -> None:
        cutil.stop_daemon(sess, self._paths(test, node)["pid"],
                          signal="KILL")

    def pause(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec_star("bash", "-c", f"kill -STOP $(cat {p['pid']})")

    def resume(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec_star("bash", "-c", f"kill -CONT $(cat {p['pid']})")

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        cutil.stop_daemon(sess, p["pid"])
        if not test.get("leave-db-running"):
            sess.exec("rm", "-rf", p["dir"])

    def log_files(self, test: dict, sess: Session, node: str):
        return [self._paths(test, node)["log"]]


class LogdClient(jc.Client):
    """workloads/kafka.py's op grammar over logd's wire protocol.

    Kafka consumer semantics live here: subscribe/assign set the
    partition set, per-partition positions advance with polls and
    reset on assignment with seek-to-beginning.  A txn op's sends are
    followed by a COMMIT marker over every touched partition (Kafka's
    commit-marker offset burn).  Connection errors raise — the
    interpreter records :info and reopens, like the reference client.
    """

    def __init__(self):
        self.sock = None
        self.f = None
        self.assigned: list = []
        self.positions: dict[Any, int] = {}

    def open(self, test, node):
        c = type(self)()
        c.sock = socket.create_connection(
            ("127.0.0.1", node_port(test)), timeout=2.0
        )
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        c.f = c.sock.makefile("rw", encoding="utf-8", newline="\n")
        return c

    def _round_trip(self, line: str) -> str:
        self.f.write(line + "\n")
        self.f.flush()
        resp = self.f.readline()
        if not resp:
            raise ConnectionError("logd closed the connection")
        return resp.strip()

    def invoke(self, test, op):
        if op.f in ("subscribe", "assign"):
            self.assigned = list(op.value or [])
            seek = op.ext.get("seek-to-beginning?")
            self.positions = {
                k: 0 if seek else self.positions.get(k, 0)
                for k in self.assigned
            }
            return op.complete(OK)
        out = []
        touched: list = []
        try:
            for mop in op.value or []:
                if mop[0] == "send":
                    _, k, v = mop
                    resp = self._round_trip(f"SEND {k} {v}")
                    if not resp.startswith("OFF "):
                        return op.complete(INFO, error=resp)
                    off = int(resp.split(" ", 1)[1])
                    out.append(["send", k, [off, v]])
                    if k not in touched:
                        touched.append(k)
                else:
                    polled: dict = {}
                    for k in self.assigned:
                        pos = self.positions.get(k, 0)
                        resp = self._round_trip(f"POLL {k} {pos} 32")
                        parts = resp.split()
                        if parts[0] != "MSGS":
                            return op.complete(INFO, error=resp)
                        self.positions[k] = int(parts[1])
                        pairs = []
                        for item in parts[2:]:
                            o, v = item.split(":", 1)
                            pairs.append([int(o), int(v)])
                        if pairs:
                            polled[k] = pairs
                    out.append(["poll", polled])
            if op.f == "txn" and touched:
                # Commit marker: burns one offset per touched
                # partition, like Kafka's transactional markers.
                self._round_trip("COMMIT " + ",".join(str(k)
                                                      for k in touched))
        except (socket.timeout, TimeoutError) as e:
            return op.complete(INFO, error=f"timeout: {e}",
                               value=op.value)
        return op.complete(OK, value=out)

    def close(self, test):
        try:
            if self.sock is not None:
                self.sock.close()
        except OSError:
            pass


class LogdQueueClient(LogdClient):
    """workloads/queue.py ops over logd's DEQ face: enqueue = SEND to
    one partition, dequeue = one record off the server-side shared
    cursor.  EMPTY completes :fail (definitely took nothing) —
    total-queue only counts :ok dequeues."""

    QUEUE_KEY = "q0"

    def invoke(self, test, op):
        try:
            if op.f == "enqueue":
                resp = self._round_trip(
                    f"SEND {self.QUEUE_KEY} {op.value}"
                )
                if not resp.startswith("OFF "):
                    return op.complete(INFO, error=resp)
                return op.complete(OK)
            resp = self._round_trip(f"DEQ {self.QUEUE_KEY} 1")
            if resp == "EMPTY":
                return op.complete(FAIL, error="empty")
            if not resp.startswith("DEQD "):
                return op.complete(INFO, error=resp)
            return op.complete(OK, value=int(resp.split()[1]))
        except (socket.timeout, TimeoutError) as e:
            return op.complete(INFO, error=f"timeout: {e}")


class LogdRegisterClient(jc.Client):
    """Register face over the broker for the standing monitor: write =
    SEND (append; the register's value is the last record appended),
    read = drain POLLs from this client's cursor to the log end at
    invoke time.  Appends are atomic and reads observe the tail as of
    the drain, so against a healthy single broker the face is
    linearizable; write-behind loss (an unsynced kill) surfaces as the
    real anomaly it is."""

    DRAIN_CAP = 64

    def __init__(self, key: str = "m0"):
        self.key = key
        self.sock = None
        self.f = None
        self.pos = 0
        self.last: Any = None

    def open(self, test, node):
        c = type(self)(self.key)
        c.sock = socket.create_connection(
            ("127.0.0.1", node_port(test)), timeout=2.0
        )
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        c.f = c.sock.makefile("rw", encoding="utf-8", newline="\n")
        return c

    _round_trip = LogdClient._round_trip

    def invoke(self, test, op):
        try:
            if op.f == "write":
                resp = self._round_trip(f"SEND {self.key} {op.value}")
                if not resp.startswith("OFF "):
                    return op.complete(INFO, error=resp)
                return op.complete(OK)
            if op.f != "read":
                raise ValueError(f"unknown f {op.f!r} (no CAS on a log)")
            # Drain to the log end: each POLL returns at most 32
            # records, so loop until a poll comes back short/empty —
            # stopping early would serve a stale tail and falsely
            # convict the broker.
            for _ in range(self.DRAIN_CAP):
                resp = self._round_trip(f"POLL {self.key} {self.pos} 32")
                parts = resp.split()
                if parts[0] != "MSGS":
                    return op.complete(INFO, error=resp)
                new_pos = int(parts[1])
                pairs = parts[2:]
                if pairs:
                    _off, v = pairs[-1].split(":", 1)
                    self.last = int(v)
                drained = new_pos == self.pos and not pairs
                self.pos = new_pos
                if drained or len(pairs) < 32:
                    return op.complete(OK, value=self.last)
            return op.complete(INFO, error="drain cap exceeded")
        except (socket.timeout, TimeoutError) as e:
            return op.complete(INFO, error=f"timeout: {e}")

    def close(self, test):
        try:
            if self.sock is not None:
                self.sock.close()
        except OSError as e:
            log.debug("logd register client close failed: %r", e)


def live_suite() -> dict:
    """Adapter for `jepsen monitor --suite logd` (monitor/live.py).
    Sync WAL mode — the suite's control configuration, so kills lose
    nothing by design and the standing verdict watches for
    regressions.  Reads/writes only: a log has no CAS."""

    def test(opts: dict) -> dict:
        store_root = os.path.abspath(opts.get("store-dir") or "store")
        return jcli.localize_test({
            "name": "logd-live",
            "nodes": ["n1"],
            "db": LogdDB(),
            "logd-sync": True,
            "logd-flush-ms": 75,
            "logd-dir": os.path.join(store_root, "logd-data"),
            "logd-port": cutil.hashed_base_port(store_root, BASE_PORT,
                                                stride=3),
            "store-dir": store_root,
        })

    return {
        "name": "logd",
        "test": test,
        "client": lambda test, key: LogdRegisterClient(key=f"mon{key}"),
        "node": lambda test, key: test["nodes"][key % len(test["nodes"])],
        "port": lambda test, node: node_port(test),
        "model": _register_model,
        "with_cas": False,
    }


def _register_model():
    from ..models import cas_register

    return cas_register()


def logd_test(opts: dict) -> dict:
    """Test-map assembly: workloads/kafka.py workload + real broker +
    DB-kill nemesis (kvdb_test shape)."""
    from ..generator.core import nemesis as gen_nemesis, phases, stagger
    from ..nemesis.combined import nemesis_package

    opts = dict(opts or {})
    store_root = os.path.abspath(opts.get("store-dir") or "store")
    is_queue = opts.get("workload", "kafka") == "queue"
    if is_queue:
        # Queue face (DEQ's server-side shared cursor): total-queue
        # convicts write-behind loss; at-least-once redelivery after
        # restarts shows up as duplicates, which is reported, not
        # convicted.  Kill faults only: a paused broker can consume a
        # record whose reply the timed-out client never reads — real
        # at-most-once delivery loss, but not the bug under test.
        wl = queue_wl.workload({
            "rate": 0,  # the suite staggers below
            "drain-ops": opts.get("drain-ops", 8000),
        })
        wl["client"] = LogdQueueClient()
        name = "logd-queue"
    else:
        wl = kafka_wl.workload({
            "key-count": opts.get("key-count", 4),
            "max-txn-length": opts.get("max-txn-length", 4),
            # Keys must outlive a kill+restart cycle for the broker's
            # offset reuse to land on a still-active key (that's what
            # turns crash loss into inconsistent-offsets/lost-write
            # findings); the default 128-write retirement is ~1s at the
            # suite's default rate — too short.
            "max-writes-per-key": opts.get("max-writes-per-key", 1024),
            "seed": opts.get("seed", 45100),
            "final-polls": opts.get("final-polls", 16),
            # No injected faults: the REAL broker supplies the anomalies.
            "faults": set(),
        })
        wl["client"] = LogdClient()
        name = "logd-kafka"

    # NB: an explicit empty list means "no faults" — `or` would
    # silently turn it into the kill default.
    faults = set(
        opts["faults"] if opts.get("faults") is not None else ["kill"]
    )
    if is_queue and "pause" in faults:
        # Enforce the queue branch's kill-only requirement (comment
        # above): a paused broker consumes a record whose reply the
        # timed-out client never reads, and with no restart the cursor
        # never rewinds — a false "lost" conviction even under --sync.
        # Loudly: silently dropping the fault would turn a requested
        # fault-injection run into a smoke test.
        raise ValueError(
            "the queue workload supports kill faults only; pause "
            "causes delivery loss the total-queue checker would "
            "misattribute to durability"
        )
    pkg = nemesis_package({
        "faults": faults,
        "interval": opts.get("interval", 2.0),
    })
    generator = time_limit(
        opts.get("time-limit", 10.0),
        gen_nemesis(
            pkg["generator"],
            stagger(1.0 / opts.get("rate", 150), wl["generator"]),
        ),
    )
    # Package final generator heals (restarts killed brokers) before
    # the workload's final polls; the workload's final generator rides
    # test["final-generator"], which core.run phases after the main
    # run (core.clj:302-320 shape, as in kvdb_test).
    if pkg.get("final-generator"):
        generator = phases(generator, gen_nemesis(pkg["final-generator"]))

    test = {
        "name": name,
        "nodes": (opts.get("nodes") or ["n1"])[:1],
        "db": LogdDB(),
        "client": wl["client"],
        "nemesis": pkg["nemesis"],
        "generator": generator,
        "checker": wl["checker"],
        "sub-via": wl.get("sub-via"),
        "logd-sync": opts.get("sync", False),
        "logd-flush-ms": opts.get("flush-ms", 75),
        "logd-dir": opts.get("logd-dir") or os.path.join(
            store_root, "logd-data"
        ),
        "logd-port": cutil.hashed_base_port(store_root, BASE_PORT,
                                            stride=3),
    }
    if wl.get("final-generator") is not None:
        test["final-generator"] = wl["final-generator"]
    return test


def _extra_opts(p) -> None:
    p.add_argument("--faults", action="append", default=None,
                   choices=["kill", "pause"])
    p.add_argument("--rate", type=float, default=150.0)
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--flush-ms", type=int, default=75)
    p.add_argument("--workload", default="kafka",
                   choices=["kafka", "queue"],
                   help="kafka: transactional log checker; queue: "
                   "total-queue over the DEQ shared cursor")
    p.add_argument("--drain-ops", type=int, default=8000)
    p.add_argument("--sync", action="store_true",
                   help="flush the WAL before acking (control group)")


def main(argv=None) -> int:
    def suite(opt_map: dict) -> dict:
        return jcli.localize_test(logd_test(opt_map))

    def all_suites(opt_map: dict):
        """test-all: each workload's write-behind conviction run and
        its --sync control group (cli.clj:501-529 pattern)."""
        for workload in ("kafka", "queue"):
            for sync in (False, True):
                o = dict(opt_map, sync=sync, workload=workload)
                if workload == "queue":
                    # The queue pair is kill-only by design (see
                    # logd_test); a matrix-wide --faults pause must
                    # not abort the whole test-all run.
                    o["faults"] = ["kill"]
                t = jcli.localize_test(logd_test(o))
                t["name"] = (f"logd-{workload}-sync" if sync
                             else f"logd-{workload}")
                yield t

    parser = jcli.single_test_cmd(
        suite, name="logd", extra_opts=_extra_opts,
        tests_fn=all_suites,
    )
    return jcli.run(parser, argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
