"""Shared workload plumbing for the demo suites.

The register suites (repkv, electd) drive the same op mix: unique
monotonically increasing write values (a stale read of an old value is
then unambiguous — with a small value space a re-write of the same
value could legitimately explain it) and CAS expected-old values drawn
from the recent write window so a fraction of CAS ops actually succeed
and constrain the history (an old value the register never held would
make every CAS a no-signal FAIL, and the composed stats checker would
flag the starved op class).
"""

from __future__ import annotations

import itertools
import random
from typing import Callable

from ..generator.core import mix

#: CAS expected-old values come from the last this-many writes.
CAS_WINDOW = 10


def register_workload_gen(
    rng: random.Random,
    *,
    with_cas: bool = True,
) -> Callable[[], object]:
    """() -> generator for the read/write[/cas] register mix.  Returns
    a zero-arg factory because a bare map is one-shot
    (generator.clj:566-570) — every element must be a fn-generator."""
    counter = itertools.count(1)
    last_write = {"v": 1}

    def write():
        v = next(counter)
        last_write["v"] = v
        return {"f": "write", "value": v}

    def cas():
        hi = last_write["v"]
        return {"f": "cas",
                "value": (rng.randrange(max(1, hi - CAS_WINDOW),
                                        hi + 1),
                          next(counter))}

    gens: list = [lambda: {"f": "read", "value": None}, write]
    if with_cas:
        gens.append(cas)

    def factory():
        return mix(gens)

    return factory


def live_register_mix(
    rng: random.Random,
    *,
    with_cas: bool = True,
    lo: int = 0,
    hi: int = 5,
) -> Callable[[], tuple]:
    """() -> (f, value) for the monitor's standing register workload.

    Unlike `register_workload_gen`, the value space is a small *bounded*
    range [lo, hi): a standing run is open-ended, and the rolling
    checker's packed-model interner grows with every distinct value it
    sees — unique monotonically increasing writes would leak memory
    over a week.  The verdict cost is acceptable here because the
    monitor checks online against a live implementation (a stale read
    still has to linearize against the pending writes), mirroring the
    in-process `_OpSource`'s rng.randrange(5) value space."""

    def next_op() -> tuple:
        f = rng.choice(("read", "write", "cas") if with_cas
                       else ("read", "write"))
        if f == "read":
            return "read", None
        if f == "write":
            return "write", rng.randrange(lo, hi)
        return "cas", (rng.randrange(lo, hi), rng.randrange(lo, hi))

    return next_op
