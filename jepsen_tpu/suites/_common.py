"""Shared workload plumbing for the demo suites.

The register suites (repkv, electd) drive the same op mix: unique
monotonically increasing write values (a stale read of an old value is
then unambiguous — with a small value space a re-write of the same
value could legitimately explain it) and CAS expected-old values drawn
from the recent write window so a fraction of CAS ops actually succeed
and constrain the history (an old value the register never held would
make every CAS a no-signal FAIL, and the composed stats checker would
flag the starved op class).
"""

from __future__ import annotations

import itertools
import random
from typing import Callable

from ..generator.core import mix

#: CAS expected-old values come from the last this-many writes.
CAS_WINDOW = 10


def register_workload_gen(
    rng: random.Random,
    *,
    with_cas: bool = True,
) -> Callable[[], object]:
    """() -> generator for the read/write[/cas] register mix.  Returns
    a zero-arg factory because a bare map is one-shot
    (generator.clj:566-570) — every element must be a fn-generator."""
    counter = itertools.count(1)
    last_write = {"v": 1}

    def write():
        v = next(counter)
        last_write["v"] = v
        return {"f": "write", "value": v}

    def cas():
        hi = last_write["v"]
        return {"f": "cas",
                "value": (rng.randrange(max(1, hi - CAS_WINDOW),
                                        hi + 1),
                          next(counter))}

    gens: list = [lambda: {"f": "read", "value": None}, write]
    if with_cas:
        gens.append(cas)

    def factory():
        return mix(gens)

    return factory
