"""txnd suite: the framework against a real TRANSACTIONAL system.

The fourth demo system (after kvdb: durability, repkv: replication,
logd: logs) and the one that aims the elle-equivalent transactional
checkers at a real server — the reference project's headline use of
elle against tidb/cockroachdb/yugabyte (SURVEY.md §2.5), in the
canonical zookeeper.clj suite shape.

The physics under test: txnd (jepsen_tpu/demo/txnd.cpp) implements textbook
snapshot isolation — MVCC versions, snapshot reads, first-committer-
wins on write-write conflicts.  SI admits *write skew* (Adya's G2):
two transactions read overlapping keys, write disjoint ones, and both
commit even though no serial order explains them.  No nemesis is
needed; the anomaly is the isolation level itself, surfaced by plain
concurrency.  The rw-register workload (checker/elle/wr.py) with
`sequential_keys=True` convicts it — per-key write order IS realtime
order under first-committer-wins, so the declared-semantics inference
is sound here.  `--serializable` makes txnd validate read sets too
(backward OCC), closing the window: the control group passes under
the identical workload.

A second workload aims one level lower: `--workload bank` runs the
conserved-total transfer test (workloads/bank.py, tests/bank.clj)
against txnd in `--read-committed` mode, where per-statement reads
and blind writes admit read skew and lost updates — reads see totals
that don't add up, and concurrent transfers permanently corrupt the
ledger.  Snapshot isolation is bank's CONTROL group (SI's consistent
snapshots and first-committer-wins preserve the total), which is the
textbook hierarchy in one binary: read committed fails bank, SI
passes bank but fails rw-register, serializable passes both.
"""

from __future__ import annotations

import os
import random
import socket
from typing import Any, Optional

from .. import cli as jcli
from .. import demo as _demo
from .. import client as jc
from .. import db as jdb
from ..checker import core as chk
from ..checker.elle import WrChecker
from ..checker.elle.wr import WrGen
from ..checker.timeline import Timeline
from ..control import Session
from ..control import util as cutil
from ..generator.core import FnGen, clients, stagger, time_limit
from ..generator import nemesis as gen_nemesis
from ..history import FAIL, INFO, OK, Op
from ..nemesis.combined import nemesis_package
from ..workloads import bank

TXND_SRC = _demo.source("txnd")

BASE_PORT = 7550


def _derived_base(test: dict, key: str, fallback: int) -> int:
    """Per-run base port: explicit test[key] wins; else derive
    from the store dir via the shared hashed_base_port formula
    (stable per run, distinct across concurrent runs, below the
    Linux ephemeral range — round 5: two builders sharing a
    BASE_PORT constant convicted a healthy run)."""
    explicit = test.get(key)
    if explicit is not None:
        return explicit
    seed = test.get("store-dir")
    if not seed:
        return fallback
    return cutil.hashed_base_port(seed, fallback)


def node_port(test: dict, node: str) -> int:
    nodes = test.get("nodes") or []
    if test.get("txnd-local", True):
        return _derived_base(test, "txnd-base-port",
                             BASE_PORT) + 1 + nodes.index(node)
    return test.get("txnd-port", BASE_PORT)


def node_dir(test: dict, node: str) -> str:
    root = test.get("txnd-dir", "/tmp/jepsen-txnd")
    return f"{root}/{node}"


class TxndDB(jdb.DB):
    """Compile-from-source lifecycle (zookeeper.clj:40-73 shape)."""

    def _paths(self, test: dict, node: str) -> dict:
        d = node_dir(test, node)
        return {
            "dir": d,
            "src": f"{d}/txnd.cpp",
            "bin": f"{d}/txnd",
            "pid": f"{d}/txnd.pid",
            "log": f"{d}/txnd.log",
        }

    def setup(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec("mkdir", "-p", p["dir"])
        sess.upload(os.path.abspath(TXND_SRC), p["src"])
        sess.exec("g++", "-O2", "-pthread", "-o", p["bin"], p["src"])
        # An interrupted earlier run leaks its daemon; a stale server
        # on our port serves foreign data -> false convictions
        # (grepkill! on setup, control/util.clj pattern).
        cutil.grepkill(sess, f"txnd --port {node_port(test, node)} ")
        # Retry the start+probe cycle (see kvdb.py setup).
        cutil.retrying_daemon_start(
            sess, lambda: self.start(test, sess, node),
            node_port(test, node), await_timeout_s=10, interval_s=0.1,
        )

    def start(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        args = ["--port", str(node_port(test, node)),
                "--think-us", str(test.get("txnd-think-us", 2000))]
        if not test.get("txnd-local", True):
            args += ["--listen", "0.0.0.0"]
        if test.get("txnd-serializable"):
            args.append("--serializable")
        if test.get("txnd-read-committed"):
            args.append("--read-committed")
        for key, value in sorted((test.get("txnd-init") or {}).items()):
            args += ["--init", str(key), str(value)]
        cutil.start_daemon(
            sess, p["bin"], *args, pidfile=p["pid"], logfile=p["log"]
        )
        try:
            cutil.await_tcp_port(
                sess, node_port(test, node), timeout_s=10,
                interval_s=0.05,
            )
        except Exception:  # noqa: BLE001 — best-effort, like kvdb
            pass

    def kill(self, test: dict, sess: Session, node: str) -> None:
        cutil.stop_daemon(sess, self._paths(test, node)["pid"],
                          signal="KILL")

    def pause(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec_star("bash", "-c", f"kill -STOP $(cat {p['pid']})")

    def resume(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec_star("bash", "-c", f"kill -CONT $(cat {p['pid']})")

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        cutil.stop_daemon(sess, p["pid"])
        if not test.get("leave-db-running"):
            sess.exec("rm", "-rf", p["dir"])

    def log_files(self, test: dict, sess: Session, node: str):
        return [self._paths(test, node)["log"]]


class TxndClient(jc.Client):
    """One-shot transactions over the line protocol.  op.value is the
    elle micro-op list [["r", k, None]|["w", k, v], ...]; reads come
    back filled in protocol order."""

    def __init__(self):
        self.sock: Optional[socket.socket] = None
        self.f: Optional[Any] = None

    def open(self, test: dict, node: Any) -> "TxndClient":
        c = type(self)()
        if test.get("txnd-local", True):
            host = "127.0.0.1"
        else:
            from ..control.core import split_host_port

            host, _ = split_host_port(node)
        c.sock = socket.create_connection(
            (host, node_port(test, node)), timeout=5.0
        )
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        c.f = c.sock.makefile("rw", encoding="utf-8", newline="\n")
        return c

    def _roundtrip(self, line: str, op: Op):
        """One request/response cycle with the shared error
        classification: io trouble / truncation -> INFO (outcome
        unknown), server-side rejection before any write applied
        (ABORT/NSF) -> FAIL, anything else unrecognized -> INFO.
        Returns the response string, or a completed Op."""
        try:
            self.f.write(line + "\n")
            self.f.flush()
            resp = self.f.readline()
        except (socket.timeout, TimeoutError, OSError) as e:
            return op.complete(INFO, error=f"io: {e}")
        if not resp:
            return op.complete(INFO, error="connection closed")
        resp = resp.strip()
        if resp in ("ABORT", "NSF"):
            # Rejected before applying anything: definitely did not
            # happen.
            return op.complete(
                FAIL,
                error="insufficient funds" if resp == "NSF" else None,
            )
        if not resp.startswith("OK"):
            return op.complete(INFO, error=resp)
        return resp

    #: Protocol verb for non-read mops; the append subclass swaps it.
    WRITE_VERB = "w"

    def _parse_read(self, raw: str):
        return None if raw == "NIL" else int(raw)

    def invoke(self, test: dict, op: Op) -> Op:
        parts = ["TXN"]
        for mop in op.value or []:
            if mop[0] == "r":
                parts += ["r", f"k{mop[1]}"]
            else:
                parts += [self.WRITE_VERB, f"k{mop[1]}", str(mop[2])]
        resp = self._roundtrip(" ".join(parts), op)
        if isinstance(resp, Op):
            return resp
        reads = resp.split()[1:]
        filled = []
        i = 0
        for mop in op.value or []:
            if mop[0] == "r":
                raw = reads[i] if i < len(reads) else "NIL"
                i += 1
                filled.append(["r", mop[1], self._parse_read(raw)])
            else:
                filled.append(mop)
        return op.complete(OK, value=filled)

    def close(self, test: dict) -> None:
        try:
            if self.sock is not None:
                self.sock.close()
        except OSError:
            pass


class TxndAppendClient(TxndClient):
    """elle list-append mops over the `a` protocol verb: appends ride
    the server's MVCC read-modify-write, reads come back comma-joined
    and are filled into the mop list the AppendChecker consumes."""

    WRITE_VERB = "a"

    def _parse_read(self, raw: str):
        return [] if raw == "NIL" else [int(x) for x in raw.split(",")]


class TxndBankClient(TxndClient):
    """Bank ops over the same line protocol: reads are one TXN over
    every account (snapshot-consistent under SI, per-statement under
    --read-committed); transfers are the server-side TRANSFER
    read-modify-write.  tests/bank.clj's client shape."""

    def invoke(self, test: dict, op: Op) -> Op:
        accounts = test.get("accounts") or []
        if op.f == "read":
            line = " ".join(["TXN"] + [x for a in accounts
                                       for x in ("r", f"a{a}")])
        else:
            t = op.value
            line = f"TRANSFER a{t['from']} a{t['to']} {t['amount']}"
        resp = self._roundtrip(line, op)
        if isinstance(resp, Op):
            return resp
        if op.f != "read":
            return op.complete(OK)
        raw = resp.split()[1:]
        balances = {
            a: int(raw[i])
            for i, a in enumerate(accounts)
            if i < len(raw) and raw[i] != "NIL"
        }
        return op.complete(OK, value=balances)


class TxndRegisterClient(TxndClient):
    """Register face for the standing monitor: one-mop transactions on
    a single key.  A single-statement txn's snapshot is taken at
    begin, so reads observe the latest committed value at invoke time
    — linearizable for one register even under plain SI."""

    def __init__(self, key: str = "m0"):
        super().__init__()
        self.key = key

    def open(self, test: dict, node: Any) -> "TxndRegisterClient":
        c = super().open(test, node)
        c.key = self.key
        return c

    def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "read":
            resp = self._roundtrip(f"TXN r {self.key}", op)
            if isinstance(resp, Op):
                return resp
            reads = resp.split()[1:]
            raw = reads[0] if reads else "NIL"
            return op.complete(OK, value=self._parse_read(raw))
        if op.f != "write":
            raise ValueError(f"unknown f {op.f!r} (no CAS verb on txnd)")
        resp = self._roundtrip(f"TXN w {self.key} {op.value}", op)
        if isinstance(resp, Op):
            return resp
        return op.complete(OK)


def live_suite() -> dict:
    """Adapter for `jepsen monitor --suite txnd` (monitor/live.py).
    Serializable mode (the suite's control group), single node; no
    kill faults — txnd is deliberately memoryless across SIGKILL, so
    the live driver should stick to pause windows."""

    def test(opts: dict) -> dict:
        store_root = os.path.abspath(opts.get("store-dir") or "store")
        return jcli.localize_test({
            "name": "txnd-live",
            "nodes": ["n1"],
            "db": TxndDB(),
            "txnd-serializable": True,
            "txnd-think-us": 0,
            "txnd-dir": os.path.join(store_root, "txnd-data"),
            "txnd-base-port": cutil.hashed_base_port(store_root,
                                                     BASE_PORT),
            "store-dir": store_root,
        })

    from ..models import cas_register

    return {
        "name": "txnd",
        "test": test,
        "client": lambda test, key: TxndRegisterClient(key=f"m{key}"),
        "node": lambda test, key: test["nodes"][key % len(test["nodes"])],
        "port": node_port,
        "model": cas_register,
        "with_cas": False,
        "families": ("pause",),
    }


def txnd_test(opts: dict) -> dict:
    """Test-map assembly (zookeeper.clj:112-137 shape)."""
    nodes = (opts.get("nodes") or ["n1"])[:1]  # single-node system
    faults = set(
        opts["faults"] if opts.get("faults") is not None else []
    )
    workload = opts.get("workload", "wr")
    extra: dict = {}
    if workload == "append":
        from ..checker.elle import AppendChecker, AppendGen

        base_gen = FnGen(AppendGen(
            key_count=opts.get("key-count", 10),
            max_txn_length=opts.get("max-txn-length", 4),
            rng=random.Random(opts.get("seed")),
        ))
        client: jc.Client = TxndAppendClient()
        checkers: dict = {
            "elle-append": AppendChecker(
                opts.get("consistency-model", "serializable")
            ),
        }
        name = "txnd-append"
    elif workload == "long-fork":
        from ..workloads import long_fork

        # Plain r/w mops — TxndClient speaks them as-is.  The
        # conviction target is --read-committed: per-statement reads
        # observe two writers' commits in contradictory orders (the
        # long fork, long_fork.clj:1-60); SI's consistent snapshots
        # forbid it, so the DEFAULT mode is this workload's control.
        base_gen = long_fork.generator(
            opts.get("group-size", 2),
            random.Random(opts.get("seed")),
        )
        client = TxndClient()
        checkers = {"long-fork": long_fork.LongForkChecker()}
        name = "txnd-long-fork"
    elif workload == "bank":
        accounts = list(range(opts.get("accounts", 8)))
        total = opts.get("total-amount", bank.DEFAULT_TOTAL)
        base_gen = bank.generator(
            accounts, rng=random.Random(opts.get("seed"))
        )
        client: jc.Client = TxndBankClient()
        checkers: dict = {"bank": bank.BankChecker()}
        name = "txnd-bank"
        extra = {
            "accounts": accounts,
            "total-amount": total,
            # All funds start on account 0 (tests/bank.clj's shape);
            # seeded server-side before the listener opens, so every
            # read sees a full ledger.
            "txnd-init": {f"a{a}": (total if a == accounts[0] else 0)
                          for a in accounts},
        }
    else:
        base_gen = FnGen(WrGen(
            key_count=opts.get("key-count", 4),
            min_txn_length=2,
            max_txn_length=opts.get("max-txn-length", 4),
            rng=random.Random(opts.get("seed")),
        ))
        client = TxndClient()
        checkers = {
            "elle-wr": WrChecker(
                consistency_model=opts.get("consistency-model",
                                           "serializable"),
                sequential_keys=True,
            ),
        }
        name = "txnd-wr"
    workload_gen = stagger(1.0 / opts.get("rate", 150), base_gen)
    if faults:
        pkg = nemesis_package({
            "faults": faults,
            "interval": opts.get("interval", 3.0),
        })
        # Routes the fault schedule to the nemesis process and the
        # workload to client processes only.
        generator = time_limit(
            opts.get("time-limit", 10.0),
            gen_nemesis(pkg["generator"], workload_gen),
        )
        if pkg.get("final-generator"):
            # Heal whatever the last interval broke (resume a paused
            # server) before the run ends — the sibling-suite pattern.
            from ..generator.core import phases

            generator = phases(
                generator, gen_nemesis(pkg["final-generator"])
            )
        nemesis = pkg["nemesis"]
    else:
        from ..nemesis.core import NoopNemesis

        # clients(): without it a bare generator also feeds the
        # nemesis process, which silently info-completes txns.
        generator = time_limit(
            opts.get("time-limit", 10.0), clients(workload_gen)
        )
        nemesis = NoopNemesis()

    store_root = os.path.abspath(opts.get("store-dir") or "store")
    checkers.update({"timeline": Timeline(), "stats": chk.Stats()})
    return {
        "name": name,
        "nodes": nodes,
        "db": TxndDB(),
        "client": client,
        "nemesis": nemesis,
        "generator": generator,
        "checker": chk.compose(checkers),
        "txnd-serializable": bool(opts.get("serializable")),
        "txnd-read-committed": bool(opts.get("read-committed")),
        # Per-workload think default — long-fork needs a wide
        # inter-statement gap (a fork requires one reader's gap to
        # straddle BOTH write commits while another reader lands
        # between them; at 2 ms the straddle never happens in a short
        # run).  The CLI flag leaves it None so this default applies
        # through both entry paths.
        "txnd-think-us": (
            opts.get("think-us")
            if opts.get("think-us") is not None
            else (20000 if workload == "long-fork" else 2000)
        ),
        "txnd-dir": opts.get("txnd-dir") or os.path.join(
            store_root, "txnd-data"
        ),
        "txnd-base-port": cutil.hashed_base_port(store_root, BASE_PORT),
        **extra,
    }


def _extra_opts(p) -> None:
    # NB: no "kill" — txnd keeps all state in memory (no WAL), so a
    # SIGKILL wipes acked transactions and would convict even the
    # serializable control group for a reason that has nothing to do
    # with isolation.  Durability bugs are kvdb/logd's department.
    p.add_argument("--faults", action="append", default=None,
                   choices=["pause"])
    p.add_argument("--rate", type=float, default=150.0)
    p.add_argument("--interval", type=float, default=3.0)
    p.add_argument("--key-count", type=int, default=4)
    p.add_argument("--max-txn-length", type=int, default=4)
    p.add_argument("--think-us", type=int, default=None,
                   help="mean transaction think window in us "
                   "(default 2000; 20000 for --workload long-fork)")
    p.add_argument("--workload", default="wr",
                   choices=["wr", "append", "bank", "long-fork"],
                   help="wr: elle rw-register (write skew); append: "
                   "elle list-append over MVCC lists; bank: "
                   "conserved-total transfers (read skew / lost "
                   "updates under --read-committed); long-fork: "
                   "contradictory read orders under --read-committed")
    p.add_argument("--group-size", type=int, default=2)
    p.add_argument("--accounts", type=int, default=8)
    p.add_argument("--serializable", action="store_true",
                   help="validate read sets at commit (the control "
                   "group: closes the write-skew window)")
    p.add_argument("--read-committed", action="store_true",
                   help="per-statement reads, no commit validation "
                   "(the bank workload's conviction target)")
    p.add_argument("--consistency-model", default="serializable",
                   choices=["serializable", "repeatable-read",
                            "read-committed", "read-uncommitted"])


def main(argv=None) -> int:
    def suite(opt_map: dict) -> dict:
        return jcli.localize_test(txnd_test(opt_map))

    def all_suites(opt_map: dict):
        """test-all: each workload's conviction run and its control
        group (cli.clj:501-529 pattern) — wr convicts SI vs the
        serializable control; bank convicts read committed vs the SI
        control."""
        for workload in ("wr", "append"):
            for serializable in (False, True):
                # Force RC off: a stray --read-committed would
                # otherwise override --serializable in the binary and
                # convict the control group for the wrong reason.
                o = dict(opt_map, workload=workload,
                         serializable=serializable,
                         **{"read-committed": False})
                t = jcli.localize_test(txnd_test(o))
                t["name"] = (f"txnd-{workload}-serializable"
                             if serializable else f"txnd-{workload}-si")
                yield t
        for workload in ("bank", "long-fork"):
            for read_committed in (True, False):
                o = dict(opt_map, workload=workload,
                         serializable=False,
                         **{"read-committed": read_committed})
                t = jcli.localize_test(txnd_test(o))
                t["name"] = (f"txnd-{workload}-read-committed"
                             if read_committed
                             else f"txnd-{workload}-si")
                yield t

    parser = jcli.single_test_cmd(
        suite, name="txnd", extra_opts=_extra_opts,
        tests_fn=all_suites,
    )
    return jcli.run(parser, argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
