"""End-to-end suite for kvdb, the demo C++ key-value store.

The canonical whole-framework exercise, shaped like the reference's
zookeeper suite (/root/reference/zookeeper/src/jepsen/zookeeper.clj:
DB reify :40-73, client :79-110, test assembly :112-137, CLI main
:139-145): the DB is *compiled from source on the node* through the
control plane (the reference compiles C helpers on nodes the same way,
nemesis/time.clj:21-40), started as a pidfile daemon, killed and
restarted by the nemesis, and talked to over TCP.

Runs against any Remote.  The default local topology maps each logical
node to its own port + data dir on this machine (LocalRemote) — the
single-machine analog of the reference's docker compose cluster
(docker/README.md) — so the whole suite works with zero external
infrastructure.  Point it at real hosts over ssh and the same code
deploys there.
"""

from __future__ import annotations

import os
import random
import socket
import time
from typing import Any, Optional

from .. import client as jc
from .. import db as jdb
from .. import cli as jcli
from .. import demo as _demo
from ..checker import core as chk
from ..checker.linearizable import linearizable
from ..checker.timeline import Timeline
from ..control import Session
from ..control import util as cutil
from ..generator.core import FnGen, mix, repeat, stagger, time_limit, until_ok
from ..generator import nemesis as gen_nemesis
from ..history import FAIL, INFO, OK, Op
from ..models import cas_register
from ..nemesis.combined import nemesis_package

#: Repo-relative source of the system under test.
KVDB_SRC = _demo.source("kvdb")

BASE_PORT = 7400


def _derived_base(test: dict, key: str, fallback: int) -> int:
    """Per-run base port: explicit test[key] wins; else derive
    from the store dir via the shared hashed_base_port formula
    (stable per run, distinct across concurrent runs, below the
    Linux ephemeral range — round 5: two builders sharing a
    BASE_PORT constant convicted a healthy run)."""
    explicit = test.get(key)
    if explicit is not None:
        return explicit
    seed = test.get("store-dir")
    if not seed:
        return fallback
    return cutil.hashed_base_port(seed, fallback)


def node_port(test: dict, node: str) -> int:
    """Local topology: each node gets its own port in a per-run range
    derived from the store dir, so concurrent runs on one machine don't
    collide; real clusters use one port everywhere (test["kvdb-port"])."""
    nodes = test.get("nodes") or []
    if test.get("kvdb-local", True):
        return _derived_base(test, "kvdb-base-port",
                             BASE_PORT) + 1 + nodes.index(node)
    return test.get("kvdb-port", BASE_PORT)


def node_dir(test: dict, node: str) -> str:
    root = test.get("kvdb-dir", "/tmp/jepsen-kvdb")
    return f"{root}/{node}"


class KvdbDB(jdb.DB):
    """Install-from-source lifecycle (zookeeper.clj:40-73 shape)."""

    def _paths(self, test: dict, node: str) -> dict:
        d = node_dir(test, node)
        return {
            "dir": d,
            "src": f"{d}/kvdb.cpp",
            "bin": f"{d}/kvdb",
            "data": f"{d}/data.log",
            "pid": f"{d}/kvdb.pid",
            "log": f"{d}/kvdb.log",
        }

    def setup(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec("mkdir", "-p", p["dir"])
        sess.upload(os.path.abspath(KVDB_SRC), p["src"])
        # Compile on the node, like the reference compiles its C
        # helpers there.
        sess.exec("g++", "-O2", "-pthread", "-o", p["bin"], p["src"])
        # An interrupted earlier run leaks its daemon; a stale server
        # on our port serves foreign data -> false convictions
        # (grepkill! on setup, control/util.clj pattern).
        cutil.grepkill(sess, f"kvdb --port {node_port(test, node)} ")
        # Retry the start+probe cycle: a slow bind or a daemon that
        # died on startup gets two more attempts before db.cycle pays
        # for a full teardown+setup.
        cutil.retrying_daemon_start(
            sess, lambda: self.start(test, sess, node),
            node_port(test, node), await_timeout_s=10, interval_s=0.1,
        )

    def start(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        args = [
            "--port", str(node_port(test, node)),
            "--data", p["data"],
        ]
        if not test.get("kvdb-local", True):
            args += ["--listen", "0.0.0.0"]
        if test.get("kvdb-fsync", True):
            args.append("--fsync")
        buf = test.get("kvdb-buffer", 0)
        if buf:
            args += ["--buffer", str(buf)]
        cutil.start_daemon(
            sess, p["bin"], *args, pidfile=p["pid"], logfile=p["log"]
        )
        try:
            cutil.await_tcp_port(
                sess, node_port(test, node), timeout_s=10, interval_s=0.05
            )
        except Exception:  # noqa: BLE001 — nemesis may restart a paused
            pass           # node; callers treat readiness as best-effort

    def kill(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        cutil.stop_daemon(sess, p["pid"], signal="KILL")

    def pause(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec_star("bash", "-c", f"kill -STOP $(cat {p['pid']})")

    def resume(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        sess.exec_star("bash", "-c", f"kill -CONT $(cat {p['pid']})")

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        p = self._paths(test, node)
        cutil.stop_daemon(sess, p["pid"])
        if not test.get("leave-db-running"):
            sess.exec("rm", "-rf", p["dir"])

    def log_files(self, test: dict, sess: Session, node: str):
        return [self._paths(test, node)["log"]]


class KvdbClient(jc.Client):
    """Line-protocol TCP client (zookeeper.clj:79-110 shape).  Register
    ops: read/write/cas on one key; set ops: add/read over MEMBERS."""

    def __init__(self, register: str = "reg", set_key: str = "s"):
        self.register = register
        self.set_key = set_key
        self.sock: Optional[socket.socket] = None
        self.f: Optional[Any] = None
        self.node: Any = None

    def open(self, test: dict, node: Any) -> "KvdbClient":
        c = type(self)(self.register, self.set_key)
        c.node = node
        port = node_port(test, node)
        if test.get("kvdb-local", True):
            host = "127.0.0.1"
        else:
            # "host:sshport" node names (localhost clusters) dial the
            # host part; the kvdb port is test["kvdb-port"].
            from ..control.core import split_host_port

            host, _ = split_host_port(node)
        c.sock = socket.create_connection((host, port), timeout=2.0)
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        c.f = c.sock.makefile("rw", encoding="utf-8", newline="\n")
        return c

    def _round_trip(self, line: str) -> str:
        self.f.write(line + "\n")
        self.f.flush()
        resp = self.f.readline()
        if not resp:
            raise ConnectionError("kvdb closed the connection")
        return resp.strip()

    def invoke(self, test: dict, op: Op) -> Op:
        try:
            if op.f == "write":
                resp = self._round_trip(f"SET {self.register} {op.value}")
                return op.complete(OK if resp == "OK" else INFO, error=None)
            if op.f == "read":
                resp = self._round_trip(f"GET {self.register}")
                if resp == "NIL":
                    return op.complete(OK, value=None)
                return op.complete(OK, value=int(resp.split(" ", 1)[1]))
            if op.f == "cas":
                old, new = op.value
                resp = self._round_trip(f"CAS {self.register} {old} {new}")
                if resp == "OK":
                    return op.complete(OK)
                if resp in ("FAIL", "NIL"):
                    return op.complete(FAIL)
                return op.complete(INFO, error=resp)
            if op.f == "add":
                resp = self._round_trip(f"ADD {self.set_key} {op.value}")
                return op.complete(OK if resp == "OK" else INFO)
            if op.f == "members":
                resp = self._round_trip(f"MEMBERS {self.set_key}")
                if resp == "NIL":
                    return op.complete(OK, value=[])
                vals = resp.split(" ", 1)[1]
                return op.complete(
                    OK, value=[int(v) for v in vals.split(",") if v]
                )
            raise ValueError(f"unknown f {op.f!r}")
        except (socket.timeout, TimeoutError) as e:
            # Indeterminate: the op may have applied.
            return op.complete(INFO, error=f"timeout: {e}")

    def close(self, test: dict) -> None:
        try:
            if self.sock is not None:
                self.sock.close()
        except OSError:
            pass


class KvdbCounterClient(KvdbClient):
    """Counter ops on one key.  The conviction arm increments the way
    naive clients actually do — GET, think, SET — whose interleavings
    LOSE concurrent updates; `atomic` uses the server's INCR (one
    round trip under the store's mutex), the control group.  The think
    pause is the honest client-side analog of txnd's --think-us: a
    real deployment's window is its read-modify-write latency, ours is
    just made visible."""

    COUNTER_KEY = "ctr"

    def __init__(self, register: str = "reg", set_key: str = "s"):
        super().__init__(register, set_key)
        self.atomic = False
        self.think_s = 0.002

    def open(self, test: dict, node: Any) -> "KvdbCounterClient":
        c = super().open(test, node)
        c.atomic = bool(test.get("kvdb-atomic-incr"))
        c.think_s = test.get("kvdb-rmw-think-s", 0.002)
        return c

    def _racy_rmw(self, delta: int) -> Optional[int]:
        """The naive GET / think / SET increment.  Returns the value
        written, or None when the SET reply was unrecognized (caller
        completes INFO).  The think pause is the honest client-side
        analog of txnd's --think-us — a real deployment's window is
        its read-modify-write latency, ours is just made visible."""
        resp = self._round_trip(f"GET {self.COUNTER_KEY}")
        cur = 0 if resp == "NIL" else int(resp.split(" ", 1)[1])
        if self.think_s:
            time.sleep(self.think_s)
        nxt = cur + delta
        resp = self._round_trip(f"SET {self.COUNTER_KEY} {nxt}")
        return nxt if resp == "OK" else None

    def _atomic_incr(self, delta: int) -> Optional[int]:
        resp = self._round_trip(f"INCR {self.COUNTER_KEY} {delta}")
        return int(resp.split()[1]) if resp.startswith("VAL ") else None

    def invoke(self, test: dict, op: Op) -> Op:
        k = self.COUNTER_KEY
        try:
            if op.f == "read":
                resp = self._round_trip(f"GET {k}")
                v = 0 if resp == "NIL" else int(resp.split(" ", 1)[1])
                return op.complete(OK, value=v)
            if op.f != "add":
                raise ValueError(f"unknown f {op.f!r}")
            incr = self._atomic_incr if self.atomic else self._racy_rmw
            applied = incr(op.value)
            if applied is None:
                return op.complete(INFO, error="unrecognized reply")
            return op.complete(OK)
        except (socket.timeout, TimeoutError) as e:
            return op.complete(INFO, error=f"timeout: {e}")


class KvdbIdClient(KvdbCounterClient):
    """ID generation on one key (checker.clj:710-747's quarry): the
    conviction arm computes its next id with the naive GET+SET round
    trip and returns it — two racers read the same current value and
    hand out the SAME id.  The atomic arm returns INCR's result,
    unique by construction."""

    COUNTER_KEY = "ids"

    def invoke(self, test: dict, op: Op) -> Op:
        if op.f != "generate":
            raise ValueError(f"unknown f {op.f!r}")
        try:
            incr = self._atomic_incr if self.atomic else self._racy_rmw
            new_id = incr(1)
            if new_id is None:
                return op.complete(INFO, error="unrecognized reply")
            return op.complete(OK, value=new_id)
        except (socket.timeout, TimeoutError) as e:
            return op.complete(INFO, error=f"timeout: {e}")


def ids_workload(opts: dict) -> dict:
    """Every acknowledged generate must return a distinct id."""
    return {
        "client": KvdbIdClient(),
        "generator": FnGen(lambda: {"f": "generate"}),
        "checker": chk.compose({
            "unique-ids": chk.UniqueIds(),
            "timeline": Timeline(),
            "stats": chk.Stats(),
        }),
    }


def counter_workload(opts: dict) -> dict:
    """tests in checker.clj:749-819's shape: positive adds + reads;
    the conviction is lost updates dragging reads below the acked
    lower bound."""
    rng = random.Random(opts.get("seed"))
    return {
        "client": KvdbCounterClient(),
        "generator": mix([
            FnGen(lambda: {"f": "read"}),
            FnGen(lambda: {"f": "add", "value": 1 + rng.randrange(5)}),
            FnGen(lambda: {"f": "add", "value": 1 + rng.randrange(5)}),
        ]),
        "checker": chk.compose({
            "counter": chk.CounterChecker(),
            "timeline": Timeline(),
            "stats": chk.Stats(),
        }),
    }


def register_workload(opts: dict) -> dict:
    rng = random.Random(opts.get("seed"))
    return {
        "client": KvdbClient(),
        "model": cas_register(),
        "generator": mix([
            FnGen(lambda: {"f": "read"}),
            FnGen(lambda: {"f": "write", "value": rng.randrange(5)}),
            FnGen(lambda: {"f": "cas",
                           "value": (rng.randrange(5), rng.randrange(5))}),
        ]),
        "checker": chk.compose({
            "linear": linearizable(
                model=cas_register(),
                algorithm=opts.get("algorithm", "cpu"),
            ),
            "timeline": Timeline(),
            "stats": chk.Stats(),
        }),
    }


def set_workload(opts: dict) -> dict:
    import itertools

    counter = itertools.count()
    return {
        "client": KvdbClient(),
        "generator": FnGen(lambda: {"f": "add", "value": next(counter)}),
        # repeat: a bare dict is one-shot, and the final read must retry
        # until the restarted DB answers (until-ok, generator.clj:1470).
        "final-generator": time_limit(
            opts.get("final-time-limit", 30.0),
            stagger(0.05, until_ok(repeat({"f": "members"}))),
        ),
        "checker": chk.SetChecker(read_f="members"),
    }


def kvdb_test(opts: dict) -> dict:
    """Test-map assembly (zookeeper.clj:112-137)."""
    workload_name = opts.get("workload", "register")
    wl = {"register": register_workload, "set": set_workload,
          "counter": counter_workload,
          "ids": ids_workload}[workload_name](opts)
    # NB: an explicit empty list means "no faults" — `or` would
    # silently substitute the default (the logd bug, round 3).
    # Counter defaults faultless: its anomaly is the client's RMW
    # race, surfaced by plain concurrency (the txnd pattern) — a kill
    # would add durability loss both arms share, muddying the control.
    default_faults = ([] if workload_name in ("counter", "ids")
                      else ["kill"])
    faults = set(
        opts["faults"] if opts.get("faults") is not None
        else default_faults
    )
    pkg = nemesis_package({
        "faults": faults,
        "interval": opts.get("interval", 3.0),
    })
    generator = time_limit(
        opts.get("time-limit", 20.0),
        gen_nemesis(
            pkg["generator"],
            stagger(1.0 / opts.get("rate", 100), wl["generator"]),
        ),
    )
    # The package's final generator heals everything the nemesis broke
    # (restart killed DBs, drop partitions) before any final reads.
    if pkg.get("final-generator"):
        from ..generator.core import phases

        generator = phases(generator, gen_nemesis(pkg["final-generator"]))
    test = {
        "name": f"kvdb-{workload_name}",
        "db": KvdbDB(),
        "client": wl["client"],
        "nemesis": pkg["nemesis"],
        "generator": generator,
        "checker": wl["checker"],
        "kvdb-fsync": opts.get("fsync", True),
        "kvdb-buffer": opts.get("buffer", 0),
        "kvdb-atomic-incr": bool(opts.get("atomic-incr")),
        "kvdb-rmw-think-s": opts.get("rmw-think-s", 0.002),
    }
    store_root = os.path.abspath(opts.get("store-dir") or "store")
    test["kvdb-dir"] = opts.get("kvdb-dir") or os.path.join(
        store_root, "kvdb-data"
    )
    test["kvdb-base-port"] = cutil.hashed_base_port(store_root,
                                                    BASE_PORT)
    if "model" in wl:
        test["model"] = wl["model"]
    if wl.get("final-generator") is not None:
        test["final-generator"] = wl["final-generator"]
    return test


def live_suite() -> dict:
    """Adapter for `jepsen monitor --suite kvdb` (monitor/live.py):
    the minimal live-target test map (db + nodes + port topology, no
    batch generator/checker — the monitor owns both) plus client/model
    factories.  kvdb is unreplicated, so one node; each monitor key is
    its own register (``mon<k>``) on that instance."""

    def test(opts: dict) -> dict:
        store_root = os.path.abspath(opts.get("store-dir") or "store")
        return jcli.localize_test({
            "name": "kvdb-live",
            "db": KvdbDB(),
            "nodes": ["n1"],
            "kvdb-dir": os.path.join(store_root, "kvdb-data"),
            "kvdb-base-port": cutil.hashed_base_port(store_root,
                                                     BASE_PORT),
            "store-dir": store_root,
        })

    return {
        "name": "kvdb",
        "test": test,
        "client": lambda test, key: KvdbClient(register=f"mon{key}"),
        "node": lambda test, key: test["nodes"][key % len(test["nodes"])],
        "port": node_port,
        "model": cas_register,
        "with_cas": True,
    }


def _extra_opts(p) -> None:
    p.add_argument("--workload", default="register",
                   choices=["register", "set", "counter", "ids"])
    p.add_argument("--atomic-incr", action="store_true",
                   help="counter/ids workloads: use the server's "
                   "atomic INCR (the control group) instead of racy "
                   "GET+SET")
    p.add_argument("--rmw-think-s", type=float, default=0.002)
    p.add_argument("--faults", action="append", default=None,
                   choices=["kill", "pause", "partition"],
                   help="fault types (repeatable; default kill)")
    p.add_argument("--rate", type=float, default=100.0)
    p.add_argument("--no-fsync", dest="fsync", action="store_false")
    p.add_argument("--buffer", type=int, default=0,
                   help="userspace write buffering (bug mode)")
    p.add_argument("--interval", type=float, default=3.0)
    p.add_argument("--algorithm", default="cpu",
                   choices=["cpu", "wgl", "wgl-tpu"],
                   help="linearizability backend for the register workload")


def main(argv=None) -> int:
    """CLI entry (zookeeper.clj:139-145)."""

    def _localize(t: dict, opt_map: dict) -> dict:
        # kvdb is an UNREPLICATED store: N nodes would be N independent
        # registers, which no checker should call one linearizable
        # object.  The suite drives a single instance; the faults that
        # matter are kill -9 + restart (durability) and pause.  The
        # workers still exercise full client concurrency against it.
        t["nodes"] = (opt_map.get("nodes") or ["n1"])[:1]
        return jcli.localize_test(t)

    def suite(opt_map: dict) -> dict:
        return _localize(kvdb_test(opt_map), opt_map)

    def all_suites(opt_map: dict):
        """test-all matrix: both workloads across the fault set
        (cli.clj:501-529 pattern)."""
        for workload in ("register", "set"):
            for faults in (["kill"], ["pause"]):
                o = dict(opt_map, workload=workload, faults=faults)
                t = _localize(kvdb_test(o), o)
                t["name"] = f"kvdb-{workload}-{'-'.join(faults)}"
                yield t
        # Counter and unique-ids pairs: racy-RMW conviction and the
        # atomic control (faultless — the race is the anomaly).
        for workload in ("counter", "ids"):
            for atomic in (False, True):
                # faults=[] explicitly: inheriting e.g. --faults kill
                # from opt_map would add durability loss both arms
                # share and falsely convict the atomic control.
                o = dict(opt_map, workload=workload, faults=[],
                         **{"atomic-incr": atomic})
                t = _localize(kvdb_test(o), o)
                t["name"] = (f"kvdb-{workload}-atomic" if atomic
                             else f"kvdb-{workload}-rmw")
                yield t

    parser = jcli.single_test_cmd(
        suite, name="kvdb", extra_opts=_extra_opts, tests_fn=all_suites
    )
    return jcli.run(parser, argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
