#!/usr/bin/env python
"""Self-chaos smoke: Jepsen turned on its own checker fleet.

The overload + self-chaos acceptance gate (tier-1): a real router +
2-daemon fleet under 3-tenant load (one whale saturating its queue)
takes a scripted fault sequence —

  * SIGKILL the placed daemon mid-flight, tear its queue journal while
    it is down, restart it on the torn journal;
  * a saturation shed: a submission with an impossible deadline must be
    refused BEFORE a ticket is minted, as a structured F_SHED with a
    positive retry-after (never an error, never a hang);

— and the run passes only if the fleet's own Jepsen history holds:

  * zero lost verdicts: every acked ticket polls to a verdict;
  * >= 1 honest shed recorded with a structured retry-after;
  * replayed verdicts are byte-identical (digest match on re-poll);
  * the whale cannot push the light tenant's queue-wait p95 over the
    fairness bound;
  * the daemon /metrics scrape exposes the checkerd.overload.* gauges
    and the per-tenant shed/queue-wait families.

Usage: python tools/chaos_smoke.py [--duration S] [--bound S]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from jepsen_tpu.nemesis import selfchaos as sc  # noqa: E402

WHALE, ALPHA, BETA = "whale", "alpha", "beta"


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--duration", type=float, default=16.0,
                    help="load window seconds (default 16)")
    ap.add_argument("--bound", type=float, default=30.0,
                    help="light-tenant queue-wait p95 fairness bound "
                         "seconds (default 30; CI CPUs are slow)")
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="chaos-smoke-")
    fleet = sc.ChaosFleet(2, tmp, metrics=True)
    history = sc.ChaosHistory()
    stop = threading.Event()
    print(f"# fleet: router :{fleet.router_port}, daemons "
          f"{fleet.daemon_ports}, workdir {tmp}")
    try:
        fleet.start()
        loads = [
            # The whale: big histories, no think time — the saturation
            # source the fairness invariant measures against.
            sc.TenantLoad(WHALE, fleet.router_addr, history, stop,
                          seed=101, n_keys=6, pairs_per_key=24,
                          think_s=0.0),
            sc.TenantLoad(ALPHA, fleet.router_addr, history, stop,
                          seed=102, n_keys=2, pairs_per_key=4,
                          think_s=0.05),
            sc.TenantLoad(BETA, fleet.router_addr, history, stop,
                          seed=103, n_keys=2, pairs_per_key=4,
                          think_s=0.05),
        ]
        for ld in loads:
            ld.start()

        # Let the fleet place work, then kill a placed daemon, tear its
        # journal while it is down, and restart it on the torn tail.
        time.sleep(args.duration * 0.3)
        victim = 0
        print(f"# chaos: SIGKILL daemon {victim} + journal tear")
        history.record("inject", family="daemon-kill", target=victim)
        fleet.kill_daemon(victim)
        time.sleep(0.5)
        history.record("inject", family="journal-tear", target=victim)
        fleet.tear_journal(victim)
        time.sleep(args.duration * 0.1)
        history.record("heal", family="daemon-kill", target=victim)
        fleet.restart_daemon(victim)

        # Saturation shed: an impossible deadline must come back as a
        # structured SHED before any ticket exists.
        from jepsen_tpu.checkerd.client import (
            CheckerdClient,
            RemoteUnavailable,
            ShedByServer,
        )

        ops = [[{"index": i, "time": i, "type": t, "process": 0,
                 "f": f, "value": v}
                for i, (t, f, v) in enumerate(
                    [("invoke", "write", 1), ("ok", "write", 1)] * 40)]
               for _ in range(4)]
        shed_seen = False
        spec = {"type": "register", "value": None}
        for attempt in range(10):
            try:
                with CheckerdClient(fleet.router_addr,
                                    io_timeout=30.0) as c:
                    c.submit_ops(f"impossible-{attempt}", spec, ops,
                                 tenant=ALPHA, deadline_s=1e-6)
            except ShedByServer as e:
                history.record("shed", tenant=ALPHA,
                               retry_after_s=e.retry_after_s,
                               reason=e.shed.reason)
                print(f"# shed observed: {e.shed.reason!r} "
                      f"retry-after {e.retry_after_s:.2f}s")
                shed_seen = True
                break
            except RemoteUnavailable:
                time.sleep(0.5)
        if not shed_seen:
            return fail("no structured shed for an impossible deadline")

        time.sleep(args.duration * 0.6)
        stop.set()
        for ld in loads:
            ld.join(timeout=60)
        stop.clear()

        print(f"# load done: {sum(ld.submitted for ld in loads)} "
              f"submissions, chasing outstanding tickets")
        sc.chase_outstanding(history, fleet.router_addr, timeout_s=60)
        divergent = sc.replay_check(history, fleet.router_addr, n=5)
        if divergent:
            return fail(f"replay digests diverged: {divergent}")

        # The daemon /metrics surface: overload gauges + per-tenant
        # families must scrape from the restarted daemon.
        url = f"http://127.0.0.1:{fleet.metrics_ports[0]}/metrics"
        body = urllib.request.urlopen(url, timeout=10).read().decode()
        for family in ("jepsen_checkerd_overload_brownout_level",
                       "jepsen_checkerd_overload_shed_total",
                       "jepsen_checkerd_queue_depth"):
            if family not in body:
                return fail(f"{family} missing from {url}")
        print("# /metrics: checkerd.overload.* gauges present")
    finally:
        stop.set()
        fleet.stop()

    violations = sc.check_invariants(
        history, fairness_bound_s=args.bound, light_tenant=ALPHA,
    )
    if violations:
        for v in violations:
            print(f"  violation: {v}")
        return fail(f"{len(violations)} fleet invariant violation(s)")

    st = history.stats()
    acked = st["kinds"].get("ack", 0)
    verdicts = st["kinds"].get("verdict", 0)
    sheds = st["kinds"].get("shed", 0)
    if not acked:
        return fail("no tickets were ever acked — load never ran")
    waits = [op["wait_s"] for op in history.ops("verdict")
             if op.get("tenant") == ALPHA
             and isinstance(op.get("wait_s"), (int, float))]
    p95 = sorted(waits)[max(0, int(len(waits) * 0.95) - 1)] \
        if waits else None
    print(f"PASS: {acked} acked -> {verdicts} verdicts (0 lost), "
          f"{sheds} honest shed(s), replays byte-identical, "
          f"light-tenant p95 {p95}s <= {args.bound}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
