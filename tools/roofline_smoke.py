#!/usr/bin/env python
"""CI smoke for the roofline observatory (tier1.yml step).

Runs a settle cohort (IndependentChecker over a multi-key register
history) with telemetry and a profile store on, then asserts the
roofline acceptance criteria end-to-end:

  * every device-executed pass in the cohort appended a v2
    profiles.jsonl record carrying the `cost` and `roofline` blocks —
    with real numbers where the backend reports cost analysis (CPU
    does), and never a dropped record;
  * the settle record accumulated its children's device cost
    (device_calls > 0 on at least one record with measured flops);
  * `wgl.roofline.*` gauges render in prometheus_text and scrape over
    a live HTTP /metrics endpoint (jepsen_tpu.web server);
  * ingest counters (`ingest.append.ops`) counted the PackedBuilder
    path when the workload streamed through it.

Exit 0 + "PASS" on success, exit 1 with a reason otherwise.  CPU-only:
the workflow runs it under JAX_PLATFORMS=cpu.
"""

import os
import socket
import sys
import tempfile
import threading
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["JEPSEN_TELEMETRY"] = "1"

from jepsen_tpu import telemetry, web  # noqa: E402
from jepsen_tpu.checker.linearizable import Linearizable  # noqa: E402
from jepsen_tpu.history.core import History  # noqa: E402
from jepsen_tpu.history.packed import PackedBuilder  # noqa: E402
from jepsen_tpu.models.registers import Register  # noqa: E402
from jepsen_tpu.parallel.independent import (  # noqa: E402
    KV,
    IndependentChecker,
)
from jepsen_tpu.telemetry import profile, roofline  # noqa: E402

#: Device-executed passes a CPU settle cohort must cover (the elle/scc
#: screen rides inside these; checker tiers beyond them only run on
#: degradation).
REQUIRED_PASSES = ("settle",)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def fail(msg: str) -> None:
    print(f"FAIL: {msg}")
    sys.exit(1)


def cohort_history(keys: int = 6, pairs: int = 5,
                   bad_keys: int = 2) -> History:
    """`keys` valid write/read register rounds plus `bad_keys` keys
    whose final read returns a never-written value: the stream witness
    proves the valid keys, and the invalid ones force the settle
    cohort (screen -> batched -> CPU settle) — the device passes the
    smoke asserts on."""
    ops = []

    def add(k, f, written, returned):
        i = len(ops)
        ops.append({"index": i, "type": "invoke", "process": k,
                    "f": f,
                    "value": KV(k, None if f == "read" else written),
                    "time": i})
        ops.append({"index": i + 1, "type": "ok", "process": k,
                    "f": f, "value": KV(k, returned), "time": i + 1})

    for k in range(keys):
        for v in range(pairs):
            add(k, "write", v, v)
            add(k, "read", v, v)
    for k in range(keys, keys + bad_keys):
        add(k, "write", 1, 1)
        add(k, "read", None, 9)
    return History(ops)


def main() -> None:
    store = tempfile.mkdtemp(prefix="roofline-smoke-")
    os.environ["JEPSEN_ROOFLINE_CACHE"] = os.path.join(
        store, "cpu-peaks.json")
    telemetry.enable(True)
    profile.set_store(store)

    # -- the settle cohort --------------------------------------------------
    checker = IndependentChecker(Linearizable(Register()))
    result = checker.check({"name": "roofline-smoke"},
                           cohort_history(), {})
    if result.get("valid") is not False:
        fail(f"cohort verdict should be false (planted bad keys): "
             f"{result.get('valid')}")

    recs = profile.read(os.path.join(store, profile.PROFILE_FILE))
    if not recs:
        fail("no profile records written")
    by_pass = {}
    for r in recs:
        by_pass.setdefault(r["pass"], []).append(r)
    for name in REQUIRED_PASSES:
        if name not in by_pass:
            fail(f"pass {name!r} produced no record "
                 f"(got {sorted(by_pass)})")

    # -- every record carries the v2 blocks (nulls allowed, keys not) -------
    for r in recs:
        for block, keys in (("cost", ("flops", "bytes_accessed")),
                            ("roofline", ("achieved_flops_per_s",
                                          "flops_ratio", "bound"))):
            d = r.get(block)
            if not isinstance(d, dict):
                fail(f"{r['pass']}: record missing {block} block")
            for k in keys:
                if k not in d:
                    fail(f"{r['pass']}: {block} block missing {k!r}")

    # -- a direct batched device pass (the settle cohort's screen
    # refutes register invalidity without the device, so drive the
    # batched BFS kernel explicitly to cover a second device pass) ----------
    from jepsen_tpu.history.packed import pack_history
    from jepsen_tpu.ops.wgl_batched import check_wgl_batched

    pm = Register().packed()
    sub = cohort_history(keys=1, pairs=4, bad_keys=0)
    packs = [pack_history(sub, pm.encode)] * 2
    batch = check_wgl_batched(packs, pm, beam=32)
    if not all(v is True for v in batch.valid):
        fail(f"batched pass verdicts wrong: {batch.valid}")
    recs = profile.read(os.path.join(store, profile.PROFILE_FILE))
    by_pass = {}
    for r in recs:
        by_pass.setdefault(r["pass"], []).append(r)
    if "batched" not in by_pass:
        fail(f"no batched record (got {sorted(by_pass)})")

    # -- the CPU backend reports cost: require real numbers somewhere -------
    measured = [r for r in recs
                if isinstance(r["cost"].get("flops"), (int, float))
                and r["cost"].get("device_calls", 0) > 0]
    if not measured:
        fail("no record measured flops (cost hook never fired)")
    achieved = [r for r in measured
                if isinstance(r["roofline"].get("achieved_flops_per_s"),
                              (int, float))]
    if not achieved:
        fail("no record derived achieved_flops_per_s")

    # -- ingest counters count the PackedBuilder path -----------------------
    b = PackedBuilder(lambda inv, comp: None)
    for op in cohort_history(keys=2, pairs=3, bad_keys=0):
        b.append(op)
    b.finish()
    if telemetry.counter_value("ingest.append.ops") <= 0:
        fail("ingest.append.ops never counted")

    # -- gauges render and scrape over live HTTP ----------------------------
    mpass = measured[0]["pass"]
    needle = f"jepsen_wgl_roofline_{mpass}_"
    text = telemetry.prometheus_text()
    if needle not in text:
        fail(f"wgl.roofline.{mpass}.* gauges missing from "
             "prometheus_text")
    port = free_port()
    srv = web.make_server(store, port=port)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            scraped = resp.read().decode()
    finally:
        srv.shutdown()
    for want in (needle, "jepsen_ingest_append_ops_total"):
        if want not in scraped:
            fail(f"/metrics scrape missing {want}")

    roofs = roofline.summarize(recs)
    print(f"PASS roofline smoke: {len(recs)} records, passes "
          f"{sorted(by_pass)}, measured cost on {len(measured)}, "
          f"settle median flops "
          f"{roofs.get('settle', {}).get('median_flops')}")


if __name__ == "__main__":
    main()
