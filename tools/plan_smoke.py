#!/usr/bin/env python
"""CI smoke for the checking-plan subsystem (tier1.yml step).

Four phases over one fixed mixed 60-key register history (48 valid
keys, 12 that defeat the checker):

  1. COLD  — a fresh process with `JEPSEN_PLAN=1` and
     `JEPSEN_PLAN_CACHE` pointing at an empty directory checks the
     history; must journal plan-memo entries and populate the XLA
     compile cache.
  2. WARM  — a second fresh process over the same cache directory
     re-checks the identical history; must HIT the persistent plan
     memo, add no new XLA cache files (every kernel compile is
     served from disk), produce byte-identical per-key verdicts, and
     not be slower than the cold run.
  3. PARITY — a fresh process with `JEPSEN_PLAN=0` (the hand-wired
     legacy ladder) must produce the same per-key (valid, algorithm)
     pairs as the cold plan run.
  4. DAEMON — a checkerd daemon started with `--plan-cache`, fed one
     remote run, then killed and RESTARTED over the same directory:
     the resubmitted history must hit the journaled plan memo
     (stats()["plan"]["cache"]["memo"]["hits"] > 0).

Exit 0 + "PASS" on success, exit 1 with a reason otherwise.  CPU-only:
the workflow runs it under JAX_PLATFORMS=cpu.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_KEYS = 60
BAD_EVERY = 5  # keys 4, 9, 14, ... read a never-written value
PAIRS = 4


def build_history():
    from jepsen_tpu.history.core import History
    from jepsen_tpu.parallel.independent import KV

    ops = []

    def add(process, f, key, value, ok_value=None):
        i = len(ops)
        ops.append({"index": i, "type": "invoke", "process": process,
                    "f": f, "value": KV(key, None if f == "read" else value),
                    "time": i})
        ops.append({"index": i + 1, "type": "ok", "process": process,
                    "f": f,
                    "value": KV(key, value if ok_value is None else ok_value),
                    "time": i + 1})

    for k in range(N_KEYS):
        key = f"k{k:03d}"
        bad = (k % BAD_EVERY) == BAD_EVERY - 1
        for v in range(PAIRS):
            add(k % 8, "write", key, v)
            # A bad key's last read observes a value never written.
            if bad and v == PAIRS - 1:
                add(k % 8, "read", key, None, ok_value=99)
            else:
                add(k % 8, "read", key, v)
    return History(ops)


def worker(out_path: str) -> int:
    """One fresh-process check of the fixed history; plan/cache config
    comes from the environment (JEPSEN_PLAN / JEPSEN_PLAN_CACHE)."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.checker.linearizable import Linearizable
    from jepsen_tpu.models.registers import Register
    from jepsen_tpu.parallel.independent import IndependentChecker

    telemetry.enable(True)
    telemetry.reset()
    h = build_history()
    t0 = time.monotonic()
    res = IndependentChecker(Linearizable(Register())).check(
        {"name": "plan-smoke"}, h, {"history-key": None})
    wall_s = time.monotonic() - t0
    counters = telemetry.summary()["counters"]
    from jepsen_tpu.plan import cache as plan_cache

    report = {
        "valid": res.get("valid"),
        "results": {
            str(k): {"valid": r.get("valid"),
                     "algorithm": r.get("algorithm")}
            for k, r in (res.get("results") or {}).items()
        },
        "wall_s": round(wall_s, 3),
        "counters": {k: v for k, v in sorted(counters.items())
                     if k.startswith(("wgl.plan.", "wgl.settle."))},
        "cache": plan_cache.stats(),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    return 0


def run_worker(tag: str, tmp: str, *, plan: str,
               cache: str | None) -> dict:
    out = os.path.join(tmp, f"{tag}.json")
    env = dict(os.environ)
    env["JEPSEN_PLAN"] = plan
    env.pop("JEPSEN_PLAN_CACHE", None)
    if cache is not None:
        env["JEPSEN_PLAN_CACHE"] = cache
    rc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker", out],
        env=env, timeout=600,
    ).returncode
    if rc != 0:
        fail(f"{tag} worker exited rc={rc}")
    with open(out) as f:
        return json.load(f)


def fail(msg: str) -> None:
    print(f"FAIL: {msg}")
    sys.exit(1)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def check_verdicts(tag: str, rep: dict) -> None:
    for k, r in rep["results"].items():
        bad = (int(k[1:]) % BAD_EVERY) == BAD_EVERY - 1
        if r["valid"] is not (not bad):
            fail(f"{tag}: key {k} valid={r['valid']}, "
                 f"expected {not bad}")
    if rep["valid"] is not False:
        fail(f"{tag}: top-level valid={rep['valid']}, expected False")


def daemon_phase(tmp: str) -> dict:
    """Start checkerd --plan-cache, run once, restart, rerun: the
    second daemon must warm-start from the journaled plan memo."""
    from jepsen_tpu.checker.linearizable import Linearizable
    from jepsen_tpu.checkerd.client import CheckerdClient, RemoteChecker
    from jepsen_tpu.models.registers import Register
    from jepsen_tpu.parallel.independent import IndependentChecker

    cache = os.path.join(tmp, "daemon-cache")
    h = build_history()
    stats = {}
    for round_no in (1, 2):
        port = free_port()
        addr = f"127.0.0.1:{port}"
        daemon = subprocess.Popen(
            [sys.executable, "-m", "jepsen_tpu.checkerd",
             "--host", "127.0.0.1", "--port", str(port),
             "--batch-window", "0.2", "--platform", "cpu",
             "--plan-cache", cache],
        )
        try:
            deadline = time.monotonic() + 60
            while True:
                try:
                    with socket.create_connection(("127.0.0.1", port),
                                                  timeout=1):
                        break
                except OSError:
                    if daemon.poll() is not None:
                        fail(f"daemon round {round_no} exited early "
                             f"rc={daemon.returncode}")
                    if time.monotonic() > deadline:
                        fail(f"daemon round {round_no} never listened")
                    time.sleep(0.2)
            rc = RemoteChecker(
                IndependentChecker(Linearizable(Register())),
                addr, run_id=f"plan-smoke-{round_no}", fallback=False)
            res = rc.check({"name": "plan-smoke"}, h, {})
            if "fallback" in res.get("checkerd", {}):
                fail(f"daemon round {round_no} fell back in-process: "
                     f"{res['checkerd']}")
            with CheckerdClient(addr) as c:
                stats = c.stats()
        finally:
            daemon.terminate()
            try:
                daemon.wait(timeout=10)
            except subprocess.TimeoutExpired:
                daemon.kill()
    return stats


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        sys.exit(worker(sys.argv[2]))

    tmp = tempfile.mkdtemp(prefix="plan-smoke-")
    cache = os.path.join(tmp, "cache")

    cold = run_worker("cold", tmp, plan="1", cache=cache)
    check_verdicts("cold", cold)
    memo = (cold["cache"].get("memo") or {})
    if not memo.get("puts"):
        fail(f"cold run journaled no plan-memo entries: {memo}")
    xla_after_cold = cold["cache"].get("xla_files") or 0
    if not xla_after_cold:
        fail("cold run populated no XLA compile-cache files")

    warm = run_worker("warm", tmp, plan="1", cache=cache)
    check_verdicts("warm", warm)
    wmemo = (warm["cache"].get("memo") or {})
    if not wmemo.get("hits"):
        fail(f"warm run hit no plan-memo entries: {wmemo}")
    xla_after_warm = warm["cache"].get("xla_files") or 0
    if xla_after_warm > xla_after_cold:
        fail(f"warm run compiled {xla_after_warm - xla_after_cold} "
             f"new kernels ({xla_after_cold} -> {xla_after_warm})")
    if warm["results"] != cold["results"]:
        fail("warm/cold per-key verdicts differ")
    # "Not slower": generous jitter allowance — CI boxes are loud, but
    # a warm run paying full recompilation would be MUCH slower.
    if warm["wall_s"] > cold["wall_s"] * 1.25 + 1.0:
        fail(f"warm run slower than cold: {warm['wall_s']}s vs "
             f"{cold['wall_s']}s")

    legacy = run_worker("legacy", tmp, plan="0", cache=None)
    check_verdicts("legacy", legacy)
    mismatch = {
        k for k in cold["results"]
        if cold["results"][k] != legacy["results"].get(k)
    }
    if mismatch:
        examples = {k: (cold["results"][k], legacy["results"].get(k))
                    for k in sorted(mismatch)[:4]}
        fail(f"plan/legacy per-pass parity broke on "
             f"{len(mismatch)} keys: {examples}")
    if not any(k.startswith("wgl.plan.") for k in cold["counters"]):
        fail(f"cold run emitted no wgl.plan.* counters: "
             f"{cold['counters']}")
    if any(k.startswith("wgl.plan.") for k in legacy["counters"]):
        fail(f"legacy run emitted plan counters: {legacy['counters']}")

    stats = daemon_phase(tmp)
    plan_stats = stats.get("plan") or {}
    dmemo = ((plan_stats.get("cache") or {}).get("memo")) or {}
    if not dmemo.get("hits"):
        fail(f"restarted daemon warm-started nothing: {dmemo}")

    print(f"PASS: cold {cold['wall_s']}s -> warm {warm['wall_s']}s, "
          f"memo {memo.get('puts')} stored / {wmemo.get('hits')} hit, "
          f"xla files {xla_after_cold} (no new on warm), "
          f"legacy parity on {len(cold['results'])} keys, "
          f"daemon warm-start hits={dmemo.get('hits')}")


if __name__ == "__main__":
    main()
