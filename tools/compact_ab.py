"""A/B the chain-round candidate compaction (VERDICT r3 next-item #2).

Runs check_wgl_witness on the bench-shaped history (cas-register,
info_rate as configured) at several `compact` tile widths, including 0
(compaction off — the round-3 engine), and prints one JSON line per
setting with the best-of-reps wall time.  The witness tier decides these
histories alone, so this isolates the chain-round cost the compaction
targets.

Usage: python tools/compact_ab.py [--ops 100000] [--reps 3]
       [--compact 0 -1 128 256] [--platform cpu|default]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=100_000)
    ap.add_argument("--reps", type=int, default=3,
                    help="measured reps after the warm-up (min 1)")
    ap.add_argument("--info", type=float, default=0.05)
    ap.add_argument("--procs", type=int, default=16)
    ap.add_argument("--compact", type=int, nargs="*",
                    default=[0, -1])
    ap.add_argument("--platform", default="cpu")
    args = ap.parse_args()
    if args.reps < 1:
        ap.error("--reps must be >= 1 (rep 0 is the warm-up)")

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from jepsen_tpu.history.packed import pack_history
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.ops.wgl_witness import check_wgl_witness, plan_width
    from jepsen_tpu.utils.histgen import random_register_history

    pm = cas_register().packed()
    h = random_register_history(args.ops, procs=args.procs,
                                info_rate=args.info, seed=45100)
    packed = pack_history(h, pm.encode)
    width = plan_width(packed)

    for c in args.compact:
        times = []
        for rep in range(args.reps + 1):  # rep 0 = compile warm-up
            t0 = time.monotonic()
            res = check_wgl_witness(packed, pm, width_hint=width,
                                    compact=c)
            dt = time.monotonic() - t0
            assert res is not None and res.valid is True, res
            if rep > 0:
                times.append(dt)
        from jepsen_tpu.utils import summarize_times

        s = summarize_times(times)
        print(json.dumps({
            "ops": args.ops, "compact": c, "W": width, **s,
            "ops_per_s": round(args.ops / s["median_s"]),
            "platform": jax.devices()[0].platform,
        }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
