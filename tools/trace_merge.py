#!/usr/bin/env python
"""Fuse Chrome traces from several processes into one Perfetto timeline.

A run that submits work to checkerd (or spawns search children) ends up
with its trace scattered across processes: the run's own trace.json, the
daemon's cohort/settle spans (shipped back in RESULT meta["spans"] and
adopted into the run trace, or exported from the daemon itself), and any
child-run traces.  Each file carries `otherData.t0_unix_s` — the wall
clock at that process's perf-counter origin — so they can be rebased
onto one shared timeline:

    python tools/trace_merge.py -o merged.json run/trace.json daemon.json

The merge keeps each process under its own pid (colliding pids between
files are offset), rebases every event's `ts` onto the earliest input's
origin, and emits Chrome flow events ("s"/"f") binding daemon spans to
the run span that caused them: a daemon event whose `args.parent_span`
names a run event's `args.span_id` (and whose `args.trace_id` matches)
gets an arrow from that run span in Perfetto's UI.

`daemon_trace_from_spans` builds a merge-ready trace dict straight from
RESULT meta["spans"], for tests and tooling that never wrote the daemon
side to disk.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Optional


def daemon_trace_from_spans(spans: list, pid: Any = "checkerd") -> dict:
    """A Chrome-trace dict from checkerd RESULT meta["spans"] (the
    wall-clock event dicts produced by telemetry.events_between), so
    the daemon side of a run can be merged without a daemon-side
    trace.json.  The earliest span's wall clock becomes the document's
    `otherData.t0_unix_s` origin and every ts is made relative to it —
    exactly the shape telemetry's own trace.json exports have, so the
    merge rebases this like any other input."""
    origin = min(
        (float(ev["t0_unix_s"]) for ev in spans or []
         if isinstance(ev, dict) and "t0_unix_s" in ev),
        default=0.0,
    )
    events: list[dict] = []
    for ev in spans or []:
        if not isinstance(ev, dict) or "name" not in ev:
            continue
        try:
            ts_us = (float(ev["t0_unix_s"]) - origin) * 1e6
            dur_us = float(ev.get("dur_s", 0.0)) * 1e6
        except (KeyError, TypeError, ValueError):
            continue
        e: dict[str, Any] = {
            "name": ev["name"],
            "cat": str(ev["name"]).split(".", 1)[0],
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": ev.get("pid", pid),
            "tid": ev.get("tid", 0),
        }
        if ev.get("attrs"):
            e["args"] = dict(ev["attrs"])
        events.append(e)
    pids = {e["pid"] for e in events}
    for p in pids:
        events.append({
            "name": "process_name", "ph": "M", "pid": p, "tid": 0,
            "args": {"name": f"checkerd[{p}]"},
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "daemon_trace_from_spans",
                      "t0_unix_s": origin},
    }


def _load(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome trace "
                         "(no traceEvents key)")
    return doc


def merge(docs: list[dict], labels: Optional[list[str]] = None) -> dict:
    """Merges Chrome-trace dicts onto one timeline.  Each doc needs
    `otherData.t0_unix_s`; docs without it are assumed already rebased
    (offset 0).  Returns the merged trace dict."""
    labels = labels or [f"trace{i}" for i in range(len(docs))]
    origins = [
        float((d.get("otherData") or {}).get("t0_unix_s") or 0.0)
        for d in docs
    ]
    base = min((o for o in origins if o), default=0.0)

    out: list[dict] = []
    used_pids: set = set()
    # span_id → rebased run event, for flow binding.
    by_span_id: dict[str, dict] = {}
    trace_ids: set = set()

    for doc, origin, label in zip(docs, origins, labels):
        offset_us = (origin - base) * 1e6 if origin else 0.0
        # Offset colliding pids so two processes that happened to share
        # a pid (common across hosts/containers) stay separate rows.
        pid_map: dict[Any, Any] = {}
        doc_pids = {e.get("pid") for e in doc["traceEvents"]}
        bump = 0
        for p in sorted(doc_pids, key=str):
            q = p
            while q in used_pids:
                bump += 100000
                q = (p + bump) if isinstance(p, int) else f"{p}+{bump}"
            pid_map[p] = q
            used_pids.add(q)
        tid_ = (doc.get("otherData") or {}).get("trace_id")
        if tid_:
            trace_ids.add(tid_)
        for ev in doc["traceEvents"]:
            e = dict(ev)
            e["pid"] = pid_map.get(ev.get("pid"), ev.get("pid"))
            if e.get("ph") != "M":
                try:
                    e["ts"] = float(e.get("ts", 0.0)) + offset_us
                except (TypeError, ValueError):
                    pass
            out.append(e)
            args = e.get("args")
            if (e.get("ph") == "X" and isinstance(args, dict)
                    and args.get("span_id")):
                by_span_id[str(args["span_id"])] = e

    # Flow events: daemon/child spans that name a parent_span get an
    # arrow from that span.  Perfetto draws ph "s" at the source and
    # ph "f" (bp "e") at the destination, joined by matching id.
    flows: list[dict] = []
    flow_id = 0
    for e in out:
        args = e.get("args")
        if not (e.get("ph") == "X" and isinstance(args, dict)):
            continue
        parent = args.get("parent_span")
        if not parent or str(parent) not in by_span_id:
            continue
        src = by_span_id[str(parent)]
        if src is e:
            continue
        if args.get("trace_id") and trace_ids \
                and args["trace_id"] not in trace_ids:
            continue
        # Each flow id binds exactly one s→f pair, so the source span
        # re-opens a fresh flow for every destination bound to it.
        flow_id += 1
        fid = f"span-flow-{flow_id}"
        flows.append({
            "name": "span-flow", "cat": "flow", "ph": "s",
            "id": fid, "ts": src["ts"], "pid": src["pid"],
            "tid": src.get("tid", 0),
        })
        flows.append({
            "name": "span-flow", "cat": "flow", "ph": "f", "bp": "e",
            "id": fid, "ts": e["ts"], "pid": e["pid"],
            "tid": e.get("tid", 0),
        })
    out.extend(flows)

    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "tools/trace_merge.py",
            "t0_unix_s": base,
            "inputs": labels,
            "trace_ids": sorted(trace_ids),
            "flows": flow_id,
        },
    }


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("traces", nargs="+",
                    help="Chrome trace JSON files (telemetry trace.json"
                         " exports) to merge")
    ap.add_argument("-o", "--out", default="merged-trace.json",
                    help="output path (default: merged-trace.json)")
    args = ap.parse_args(argv)
    try:
        docs = [_load(p) for p in args.traces]
    except (OSError, ValueError) as e:
        print(f"trace_merge: {e}", file=sys.stderr)
        return 1
    merged = merge(docs, labels=list(args.traces))
    with open(args.out, "w") as f:
        json.dump(merged, f)
    n = len(merged["traceEvents"])
    print(f"trace_merge: wrote {args.out} "
          f"({n} events from {len(docs)} traces, "
          f"{merged['otherData']['flows']} flow bindings)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
