#!/usr/bin/env python
"""CI smoke for the streaming online checker (tier1.yml step).

Builds a mixed-validity independent register workload (60 keys, every
6th carrying an impossible read), feeds it through a StreamingSession
PACED like a live run (ops spread over several seconds of wall time, so
the double-buffered pipeline genuinely overlaps ingest with checking),
and asserts the two properties ISSUE 7 names:

  * per-key verdict PARITY: the consuming IndependentChecker (online
    verdicts + post-hoc for the rest) returns exactly the same per-key
    verdicts as a fresh post-hoc check with the settle memo cleared;
  * verdict LAG: finish() — drain, final proofs, verdict — completes in
    under 10% of the run length.

Exit 0 + "PASS" on success, exit 1 with a reason otherwise.  CPU-only:
the workflow runs it under JAX_PLATFORMS=cpu.  Pytest-reachable via
tests/test_streaming.py::test_smoke_tool (slow marker; CI runs this
file directly as its own tier1 step instead).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from jepsen_tpu.checker.linearizable import Linearizable  # noqa: E402
from jepsen_tpu.history.core import history  # noqa: E402
from jepsen_tpu.models import cas_register  # noqa: E402
from jepsen_tpu.parallel.independent import (  # noqa: E402
    KV,
    IndependentChecker,
    clear_settle_memo,
)
from jepsen_tpu.streaming.pipeline import StreamingSession  # noqa: E402
from jepsen_tpu.utils.histgen import random_register_history  # noqa: E402

N_KEYS = 60
OPS_PER_KEY = 14
BAD_EVERY = 6


def mixed_history(n_keys: int = N_KEYS, ops_per_key: int = OPS_PER_KEY,
                  *, bad_every: int = BAD_EVERY, seed: int = 45100):
    """Independent register streams, every `bad_every`-th key invalid,
    merged round-robin (disjoint process ids per key)."""
    streams = []
    for i in range(n_keys):
        sub = random_register_history(
            ops_per_key, procs=2, info_rate=0.0, cas=False,
            seed=seed + i, bad=(i % bad_every == 0),
        )
        key = f"k{i}"
        streams.append([
            o.replace(value=KV(key, o.value), process=i * 4 + o.process)
            for o in sub
        ])
    merged = []
    pos = [0] * n_keys
    remaining = sum(len(s) for s in streams)
    while remaining:
        for i, s in enumerate(streams):
            if pos[i] < len(s):
                merged.append(s[pos[i]])
                pos[i] += 1
                remaining -= 1
    return history(merged)


def run(run_s: float = 8.0) -> int:
    pm = cas_register().packed()

    # Warm the witness-engine compile outside the measured run, with a
    # SHAPE-IDENTICAL workload on a different seed (different digests,
    # so no memo/verdict of the real run is pre-answered).  The witness
    # buckets compiled kernels by window/block shape; a same-shape
    # warm-up compiles every bucket the real run will touch — including
    # the finalize-sized batch — so the measured lag is steady-state
    # checking, not a one-time XLA compile that happens to land after
    # the last op.
    warm = mixed_history(seed=9)
    ws = StreamingSession(pm, swap_ops=256, recheck_min_rows=4)
    for op in warm:
        ws.feed(op)
    ws.finish()
    clear_settle_memo()

    h = mixed_history()
    n_bad = len([i for i in range(N_KEYS) if i % BAD_EVERY == 0])
    sess = StreamingSession(pm, swap_ops=256, recheck_min_rows=4)

    ops = list(h)
    pause_every = max(1, len(ops) // 64)
    pause = run_s / (len(ops) / pause_every)
    t0 = time.monotonic()
    for i, op in enumerate(ops):
        sess.feed(op)
        if i % pause_every == pause_every - 1:
            time.sleep(pause)
    run_len = time.monotonic() - t0
    stats = sess.finish()
    lag = stats["verdict-lag-s"]

    print(f"# run {run_len:.2f}s, lag {lag:.3f}s "
          f"({100 * lag / run_len:.1f}%), stats {stats}")
    if sess.broken:
        print(f"FAIL: session broken: {sess.broken_reason}")
        return 1
    if stats["proven-online"] != N_KEYS - n_bad:
        print(f"FAIL: proved {stats['proven-online']} keys online, "
              f"expected {N_KEYS - n_bad}")
        return 1
    if lag >= 0.10 * run_len:
        print(f"FAIL: verdict lag {lag:.3f}s >= 10% of the "
              f"{run_len:.2f}s run")
        return 1

    online = IndependentChecker(Linearizable(cas_register())).check(
        {"streaming-session": sess}, h, {}
    )
    consumed = [k for k, r in online["results"].items()
                if r.get("algorithm") == "wgl-online"]
    if len(consumed) != N_KEYS - n_bad:
        print(f"FAIL: consumed {len(consumed)} online verdicts, "
              f"expected {N_KEYS - n_bad}")
        return 1

    clear_settle_memo()  # post-hoc must not replay the online memos
    posthoc = IndependentChecker(
        Linearizable(cas_register()), streaming=False
    ).check({}, h, {})
    if set(online["results"]) != set(posthoc["results"]):
        print("FAIL: key sets diverged")
        return 1
    for k, r in posthoc["results"].items():
        if online["results"][k]["valid"] != r["valid"]:
            print(f"FAIL: verdict parity broken on {k!r}: online "
                  f"{online['results'][k]['valid']} vs post-hoc "
                  f"{r['valid']}")
            return 1
    if online["valid"] is not False or posthoc["valid"] is not False:
        print("FAIL: mixed-validity history must be invalid overall")
        return 1
    print(f"PASS: {N_KEYS} keys ({n_bad} invalid), "
          f"{stats['proven-online']} proven online, "
          f"lag {lag:.3f}s / {run_len:.2f}s run")
    return 0


def main() -> int:
    return run(float(os.environ.get("JEPSEN_STREAMING_SMOKE_RUN_S", "8")))


if __name__ == "__main__":
    sys.exit(main())
