#!/usr/bin/env python
"""Per-pass cost regression diff between two profiles.jsonl stores.

The per-pass profile store (jepsen_tpu/telemetry/profile.py) is the
declared training set for the ROADMAP item-1 learned cost model; this
tool keeps it trustworthy by comparing two stores — typically the
previous CI run's and this one's — pass by pass:

  * records are bucketed by *configuration*: pass name + plan knobs +
    the shape features (op counts, key counts), with measured outputs
    (explored configs, shrink attempts, device seconds) excluded, so a
    bucket means "the same work was asked for";
  * per bucket, the median execute_s (falling back to total_s when a
    pass records no device execution) is compared old → new;
  * a bucket regresses when the delta exceeds the noise floor
    (default +35%, CPU CI timing is loud) AND the old median is above
    the significance floor (default 50 ms — microsecond buckets jitter
    by integer factors without meaning anything).

Exit code 1 when regressions are found, 0 otherwise (including when
either store is missing/empty — an advisory diff must not fail the
first run of a new store).  Wired as an advisory tier1.yml step.

Usage:
  python tools/profile_diff.py OLD.jsonl NEW.jsonl
      [--noise 0.35] [--min-s 0.05] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jepsen_tpu.telemetry import profile  # noqa: E402

#: Feature keys that are measured outputs, not requested shape — two
#: runs of identical work may differ on all of these.
MEASURED_FEATURES = frozenset((
    "explored", "attempts", "kept_units", "checks", "device_s",
    "proven", "settled", "merged", "passes", "restarts",
))


def bucket_key(rec: dict) -> str:
    # profile.read() normalizes records, but buckets() is also handed
    # raw dicts in tests — degrade the same way it would: schema-less
    # records land in the "unknown" bucket rather than raising.
    name = rec.get("pass")
    feats = rec.get("features")
    feats = feats if isinstance(feats, dict) else {}
    plan = rec.get("plan")
    return json.dumps(
        {
            "pass": name if isinstance(name, str) and name else "unknown",
            "plan": plan if isinstance(plan, dict) else {},
            "features": {
                k: v for k, v in feats.items()
                if k not in MEASURED_FEATURES
            },
        },
        sort_keys=True, default=repr,
    )


def cost_of(rec: dict) -> float:
    t = rec.get("timing")
    t = t if isinstance(t, dict) else {}
    try:
        ex = float(t.get("execute_s") or 0.0)
        return ex if ex > 0 else float(t.get("total_s") or 0.0)
    except (TypeError, ValueError):
        return 0.0


def buckets(path: str) -> dict[str, list[float]]:
    out: dict[str, list[float]] = {}
    for rec in profile.read(path):
        out.setdefault(bucket_key(rec), []).append(cost_of(rec))
    return out


def diff(old_path: str, new_path: str, *, noise: float,
         min_s: float) -> dict:
    old = buckets(old_path)
    new = buckets(new_path)
    shared = sorted(set(old) & set(new))
    rows = []
    regressions = 0
    for key in shared:
        o = statistics.median(old[key])
        n = statistics.median(new[key])
        delta = (n - o) / o if o > 0 else (0.0 if n == 0 else float("inf"))
        regressed = bool(delta > noise and o >= min_s)
        regressions += regressed
        cfg = json.loads(key)
        rows.append({
            "pass": cfg["pass"],
            "config": cfg,
            "old_s": round(o, 6),
            "new_s": round(n, 6),
            "delta": round(delta, 4) if delta != float("inf") else "inf",
            "old_n": len(old[key]),
            "new_n": len(new[key]),
            "regressed": regressed,
        })
    rows.sort(key=lambda r: (not r["regressed"],
                             -(r["new_s"] - r["old_s"])))
    return {
        "old": old_path,
        "new": new_path,
        "shared-buckets": len(shared),
        "old-only": len(set(old) - set(new)),
        "new-only": len(set(new) - set(old)),
        "noise-floor": noise,
        "min-s": min_s,
        "regressions": regressions,
        "rows": rows,
    }


def main() -> int:
    ap = argparse.ArgumentParser(
        description="diff per-pass cost records across two "
                    "profiles.jsonl stores")
    ap.add_argument("old", help="baseline store (previous run)")
    ap.add_argument("new", help="candidate store (this run)")
    ap.add_argument("--noise", type=float, default=0.35,
                    help="relative regression floor (default 0.35)")
    ap.add_argument("--min-s", type=float, default=0.05,
                    help="ignore buckets whose old median is below "
                         "this many seconds (default 0.05)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    args = ap.parse_args()

    for path, name in ((args.old, "old"), (args.new, "new")):
        if not os.path.isfile(path):
            print(f"# profile_diff: {name} store {path} missing; "
                  f"nothing to compare")
            return 0

    report = diff(args.old, args.new, noise=args.noise,
                  min_s=args.min_s)
    if args.json:
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        print(f"# {report['shared-buckets']} shared buckets "
              f"({report['old-only']} old-only, "
              f"{report['new-only']} new-only), "
              f"noise floor +{args.noise:.0%}, "
              f"min {args.min_s * 1000:.0f} ms")
        for r in report["rows"][:24]:
            mark = "REGRESSED" if r["regressed"] else "ok"
            print(f"{mark:>9}  {r['pass']:<18} "
                  f"{r['old_s'] * 1000:9.1f}ms -> "
                  f"{r['new_s'] * 1000:9.1f}ms  "
                  f"(delta {r['delta']}, n={r['old_n']}/{r['new_n']})")
    if not report["shared-buckets"]:
        print("# no shared buckets; stores describe different work")
        return 0
    if report["regressions"]:
        print(f"# {report['regressions']} regression(s) beyond the "
              f"noise floor")
        return 1
    print("# no regressions beyond the noise floor")
    return 0


if __name__ == "__main__":
    sys.exit(main())
