#!/usr/bin/env python
"""Differential soak of the round-5 engines.

Random per-key workloads (mixed sizes, info rates, fault injection)
checked THREE independent ways that must agree:

  1. per-key exact CPU reference (checker/wgl_cpu.py memoized DFS);
  2. the key-concatenated stream witness (ops/wgl_stream.py) — its
     True verdicts must never contradict the reference (soundness);
     None only means escalate;
  3. the single-history witness engine under every transfer mode
     ("full" / "indices" / "device") on each key — verdicts AND death
     behavior must agree across modes.

The planted-violation rate (~15% of keys) is itself asserted: a
reference that stops convicting the planted bad reads fails the soak
(reference-miss), so a completeness collapse can't silently pass.

Usage: python tools/soak_round5.py [--minutes 30] [--seed0 0]
Prints one JSON progress line per batch and a final summary line.
The budget is checked between keys, so a batch overruns by at most
one key's check (first-compile batches can still take minutes).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=30.0)
    ap.add_argument("--seed0", type=int, default=0)
    ap.add_argument("--platform", default="cpu",
                    choices=("cpu", "default"))
    args = ap.parse_args()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from jepsen_tpu.checker.wgl_cpu import check_wgl_cpu
    from jepsen_tpu.history.packed import pack_history
    from jepsen_tpu.models import cas_register, register
    from jepsen_tpu.ops.wgl_stream import check_wgl_witness_stream
    from jepsen_tpu.ops.wgl_witness import check_wgl_witness
    from jepsen_tpu.utils.histgen import random_register_history

    t_end = time.monotonic() + args.minutes * 60.0
    rng = random.Random(args.seed0)
    batches = trials = stream_true = stream_none = bad_planted = 0
    mismatches = []

    while time.monotonic() < t_end:
        batches += 1
        use_cas = rng.random() < 0.7
        model = cas_register() if use_cas else register()
        pm = model.packed()
        n_keys = rng.choice([3, 8, 20])
        packs, cpu_verdicts = [], []
        for i in range(n_keys):
            if time.monotonic() > t_end:
                n_keys = i
                break
            n = rng.choice([60, 120])
            info = rng.choice([0.0, 0.05, 0.2])
            procs = rng.choice([4, 8])
            bad = rng.random() < 0.15
            bad_planted += bad
            seed = args.seed0 * 1_000_003 + batches * 1009 + i
            h = random_register_history(
                n, procs=procs, info_rate=info, seed=seed,
                cas=use_cas, bad=bad,
            )
            p = pack_history(h, pm.encode)
            packs.append(p)
            cpu = check_wgl_cpu(p, pm, max_configs=5_000_000)
            cpu_verdicts.append(cpu.valid)
            if bad and cpu.valid is True:
                # The reference itself stopped convicting planted
                # violations: the whole differential would go vacuous.
                mismatches.append({
                    "kind": "reference-miss", "batch": batches,
                    "key": i, "seed": seed,
                })
        if not packs:
            break

        # --- stream soundness: True never contradicts the reference.
        sv = check_wgl_witness_stream(packs, pm)
        for i, (s, c) in enumerate(zip(sv, cpu_verdicts)):
            trials += 1
            if s is True:
                stream_true += 1
                if c is False:
                    mismatches.append({
                        "kind": "stream-unsound", "batch": batches,
                        "key": i, "cpu": c,
                    })
            else:
                stream_none += 1

        # --- transfer-mode agreement on a sample of keys.
        for i in rng.sample(range(n_keys), min(3, n_keys)):
            vs = {}
            for mode in ("full", "indices", "device"):
                r = check_wgl_witness(packs[i], pm, transfer=mode)
                vs[mode] = None if r is None else r.valid
            if len(set(vs.values())) != 1:
                mismatches.append({
                    "kind": "transfer-divergence", "batch": batches,
                    "key": i, "verdicts": vs,
                })
            # Witness True must also never contradict the reference.
            if vs["full"] is True and cpu_verdicts[i] is False:
                mismatches.append({
                    "kind": "witness-unsound", "batch": batches,
                    "key": i,
                })

        if batches % 20 == 0:
            # LLVM executables accumulate across the shape lottery;
            # an hour-long soak OOMed the compile cache (observed:
            # "LLVM compilation error: Cannot allocate memory").
            jax.clear_caches()
        print(json.dumps({
            "batches": batches, "keys": trials,
            "stream_true": stream_true, "stream_none": stream_none,
            "bad_planted": bad_planted,
            "mismatches": len(mismatches),
        }), flush=True)
        if mismatches:
            break

    print(json.dumps({
        "done": True, "batches": batches, "keys": trials,
        "stream_true": stream_true, "stream_none": stream_none,
        "bad_planted": bad_planted, "mismatches": mismatches,
    }), flush=True)
    return 1 if mismatches else 0


if __name__ == "__main__":
    sys.exit(main())
