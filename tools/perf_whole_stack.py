#!/usr/bin/env python
"""Whole-stack run+check throughput benchmark.

Equivalent of the reference's `list-append-perf-test`
(jepsen/test/jepsen/core_test.clj:127-132): run N list-append
transactions through the ENTIRE stack — generator -> interpreter ->
incremental on-disk history -> Elle list-append analysis — against the
in-memory serializable client, and print run and check rates.  The
reference measures 1e6 ops on the JVM at concurrency 100 with no
asserted threshold; this prints the same two numbers for comparison.

Usage: python tools/perf_whole_stack.py [n_ops] [concurrency]

`measure()` is importable (tests/test_whole_stack_perf.py asserts a
floor on the CI shape), so the numbers CI guards and the numbers this
prints are the same code path.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def measure(n_ops: int, concurrency: int) -> dict:
    """Runs the whole stack; returns {"run_rate", "check_rate",
    "valid", "n_run"} (ops/s)."""
    import jepsen_tpu.generator as gen
    from jepsen_tpu.checker import checker as mk_checker
    from jepsen_tpu.core import run as run_test
    from jepsen_tpu.workloads import append

    w = append.workload({"key-count": max(10, n_ops // 20_000),
                         "seed": 45100})
    with tempfile.TemporaryDirectory() as store_dir:
        # Run with a no-op checker so t_run is the pure
        # generator+interpreter+store phase; the real analysis is timed
        # separately below.  (Subtracting a warm re-check from the
        # total would hide the first check's JIT compile inside the
        # run number.)
        test = {
            "name": "perf-whole-stack",
            "nodes": ["n1"],
            "ssh": {"dummy?": True},
            "concurrency": concurrency,
            "store-dir": store_dir,
            "client": w["client"],
            "generator": gen.limit(n_ops, w["generator"]),
            "checker": mk_checker(lambda t, h, o: {"valid": True}),
        }
        t0 = time.monotonic()
        res = run_test(test)
        t_run = time.monotonic() - t0
        hist = res["history"]
        n_run = sum(1 for o in hist if o.is_invoke)

    t1 = time.monotonic()
    checked = w["checker"].check(test, hist, {})
    t_check = time.monotonic() - t1
    valid = checked.get("valid")

    return {
        "run_rate": n_run / t_run,
        "check_rate": n_run / t_check,
        "valid": valid,
        "n_run": n_run,
    }


def main() -> int:
    n_ops = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    concurrency = int(sys.argv[2]) if len(sys.argv) > 2 else 100
    m = measure(n_ops, concurrency)
    print(
        f"ran {m['n_run']} ops ({m['run_rate']:,.0f} ops/s); "
        f"checked at {m['check_rate']:,.0f} ops/s; "
        f"valid={m['valid']}"
    )
    return 0 if m["valid"] is True else 1


if __name__ == "__main__":
    sys.exit(main())
