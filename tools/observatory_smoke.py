#!/usr/bin/env python
"""CI smoke for the fleet observatory (tier1.yml step).

Starts a real `jepsen_tpu.checkerd` daemon (with its /metrics server
and a profile store), runs a small suite against it with telemetry on,
and asserts the three observatory layers end-to-end:

  * trace propagation — the daemon's RESULT meta["spans"] carry the
    submitting run's trace_id / analyze parent span, and
    tools/trace_merge.py fuses run + daemon into one Chrome trace with
    both processes and at least one flow binding;
  * cost profiles — the local profile store holds >= 1 record per
    executed pass (settle plus its tiers), each with the
    compile/execute/total timing split and non-empty shape features;
    the daemon's own store also recorded its cohort passes;
  * scrape surface — GET /metrics on the daemon parses as Prometheus
    text with >= 1 counter and a full one-hot jepsen_chip_health
    family.

Exit 0 + "PASS" on success, exit 1 with a reason otherwise.  CPU-only:
the workflow runs it under JAX_PLATFORMS=cpu.
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["JEPSEN_TELEMETRY"] = "1"

from jepsen_tpu import telemetry  # noqa: E402
from jepsen_tpu.checker.linearizable import Linearizable  # noqa: E402
from jepsen_tpu.checkerd.client import RemoteChecker  # noqa: E402
from jepsen_tpu.history.core import History  # noqa: E402
from jepsen_tpu.models.registers import Register  # noqa: E402
from jepsen_tpu.parallel.independent import (  # noqa: E402
    KV,
    IndependentChecker,
)
from jepsen_tpu.telemetry import profile  # noqa: E402
from trace_merge import daemon_trace_from_spans, merge  # noqa: E402


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def history(prefix: str) -> History:
    ops = []

    def add(process, f, key, value):
        i = len(ops)
        ops.append({"index": i, "type": "invoke", "process": process,
                    "f": f, "value": KV(key, None if f == "read" else value),
                    "time": i})
        ops.append({"index": i + 1, "type": "ok", "process": process,
                    "f": f, "value": KV(key, value), "time": i + 1})

    add(0, "write", f"{prefix}-good", 1)
    add(0, "read", f"{prefix}-good", 1)
    add(1, "write", f"{prefix}-bad", 1)
    add(1, "read", f"{prefix}-bad", 9)
    return History(ops)


def fail(msg: str) -> None:
    print(f"FAIL: {msg}")
    sys.exit(1)


def main() -> None:
    port, mport = free_port(), free_port()
    addr = f"127.0.0.1:{port}"
    tmp = tempfile.mkdtemp(prefix="observatory-smoke-")
    run_dir = os.path.join(tmp, "run")
    daemon_dir = os.path.join(tmp, "daemon")
    env = dict(os.environ, JEPSEN_TELEMETRY="1", JAX_PLATFORMS="cpu")
    daemon = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.checkerd",
         "--host", "127.0.0.1", "--port", str(port),
         "--metrics-port", str(mport),
         "--profile-dir", daemon_dir,
         "--batch-window", "0.2", "--platform", "cpu"],
        env=env,
    )
    try:
        deadline = time.monotonic() + 60
        while True:
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=1):
                    break
            except OSError:
                if daemon.poll() is not None:
                    fail(f"daemon exited early rc={daemon.returncode}")
                if time.monotonic() > deadline:
                    fail("daemon never started listening")
                time.sleep(0.2)

        telemetry.enable(True)
        telemetry.reset()
        profile.set_store(run_dir)
        h = history("obs")
        test = {"name": "observatory-smoke"}

        # Mimic core.analyze's trace scope: the analyze span is the
        # parent every propagated span must point back to.
        sid = telemetry.new_span_id()
        tid = telemetry.trace_id()
        telemetry.set_parent_span(sid)
        try:
            with telemetry.span("lifecycle.analyze",
                                span_id=sid, trace_id=tid):
                expected = IndependentChecker(
                    Linearizable(Register())).check(test, h, {})
                got = RemoteChecker(
                    IndependentChecker(Linearizable(Register())),
                    addr, run_id="obs-run", fallback=False,
                ).check(test, h, {})
        finally:
            telemetry.set_parent_span(None)

        if got["valid"] != expected["valid"]:
            fail(f"remote valid {got['valid']} != {expected['valid']}")
        meta = got.get("checkerd") or {}
        spans = meta.get("spans") or []
        if not spans:
            fail("RESULT meta carried no daemon spans")
        for ev in spans:
            attrs = ev.get("attrs") or {}
            if attrs.get("trace_id") != tid:
                fail(f"daemon span {ev['name']} trace_id "
                     f"{attrs.get('trace_id')} != {tid}")
            if attrs.get("parent_span") != sid:
                fail(f"daemon span {ev['name']} parent_span "
                     f"{attrs.get('parent_span')} != {sid}")

        # --- trace merge: run + daemon on one timeline -------------
        run_trace = telemetry.chrome_trace()
        run_path = os.path.join(tmp, "run-trace.json")
        with open(run_path, "w") as f:
            json.dump(run_trace, f)
        daemon_path = os.path.join(tmp, "daemon-trace.json")
        with open(daemon_path, "w") as f:
            json.dump(daemon_trace_from_spans(spans, pid=meta.get("pid")),
                      f)
        merged = merge([json.load(open(run_path)),
                        json.load(open(daemon_path))],
                       labels=["run", "daemon"])
        mpath = os.path.join(tmp, "merged-trace.json")
        with open(mpath, "w") as f:
            json.dump(merged, f)
        pids = {e.get("pid") for e in merged["traceEvents"]
                if e.get("ph") == "X"}
        if len(pids) < 2:
            fail(f"merged trace has {len(pids)} pid(s), want >= 2")
        if merged["otherData"]["flows"] < 1:
            fail("merged trace has no flow bindings to the analyze span")

        # --- profile store: a record per executed pass -------------
        local = profile.by_pass()
        if not local:
            fail("local profile store is empty")
        if "settle" not in local:
            fail(f"no settle pass record in local store: {local}")
        for rec in profile.read(profile.store_path()):
            t = rec.get("timing") or {}
            for k in ("compile_s", "execute_s", "total_s"):
                if not isinstance(t.get(k), (int, float)):
                    fail(f"record for pass {rec.get('pass')} missing "
                         f"timing.{k}")
            if not rec.get("features"):
                fail(f"record for pass {rec.get('pass')} has no "
                     "shape features")
            if rec.get("trace_id") != tid:
                fail(f"record for pass {rec.get('pass')} trace_id "
                     f"{rec.get('trace_id')} != {tid}")
        remote_profiles = profile.by_pass(
            os.path.join(daemon_dir, profile.PROFILE_FILE))
        if not remote_profiles:
            fail("daemon profile store is empty")

        # --- /metrics scrape ---------------------------------------
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/metrics", timeout=5,
        ).read().decode()
        counters = [ln for ln in body.splitlines()
                    if ln and not ln.startswith("#")
                    and "_total" in ln.split(" ")[0]]
        if not counters:
            fail(f"no counter samples in /metrics:\n{body[:500]}")
        chip = {}
        for ln in body.splitlines():
            if ln.startswith("jepsen_chip_health{"):
                state = ln.split('state="', 1)[1].split('"', 1)[0]
                chip[state] = float(ln.rsplit(" ", 1)[1])
        if set(chip) != set(telemetry.CHIP_HEALTH_STATES):
            fail(f"chip_health states {sorted(chip)} != "
                 f"{sorted(telemetry.CHIP_HEALTH_STATES)}")
        if sum(chip.values()) != 1.0:
            fail(f"chip_health not one-hot: {chip}")

        print(f"PASS: {len(spans)} daemon spans propagated, "
              f"merged trace {mpath} "
              f"({merged['otherData']['flows']} flows), "
              f"local passes {local}, daemon passes {remote_profiles}, "
              f"{len(counters)} counters scraped, chip_health ok")
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()


if __name__ == "__main__":
    main()
