#!/usr/bin/env python
"""CI smoke for the bit-packed WGL kernels (tier1.yml step).

Runs the SAME register workloads through the wide-tensor and the
uint32-lane variants of the device engines and asserts the two
contracts the packed kernels ship under:

  * parity — per-key `(valid, configs)` agreement between packed and
    wide for the BFS, batched, witness and stream engines (verdicts
    must match exactly; exploration counts must stay close — dedup is
    exact in both, only beam-truncation order may drift);
  * roofline — on the same shapes, the packed BFS passes must profile
    at STRICTLY higher arithmetic intensity (flops / bytes accessed)
    than the wide passes.  Packing is a memory-traffic optimisation:
    if intensity doesn't rise, the kernels regressed to byte-per-bool
    traffic and the knee migration claimed in design.md is gone.

Exit 0 + "PASS" on success, exit 1 with a reason otherwise.  CPU-only:
the workflow runs it under JAX_PLATFORMS=cpu.
"""

import os
import statistics
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["JEPSEN_TELEMETRY"] = "1"

from jepsen_tpu import telemetry  # noqa: E402
from jepsen_tpu.history.packed import pack_history  # noqa: E402
from jepsen_tpu.models import cas_register  # noqa: E402
from jepsen_tpu.ops.wgl import check_wgl_device  # noqa: E402
from jepsen_tpu.ops.wgl_batched import check_wgl_batched  # noqa: E402
from jepsen_tpu.ops.wgl_stream import (  # noqa: E402
    check_wgl_witness_stream,
)
from jepsen_tpu.ops.wgl_witness import check_wgl_witness  # noqa: E402
from jepsen_tpu.telemetry import profile  # noqa: E402
from jepsen_tpu.utils.histgen import random_register_history  # noqa: E402


def fail(msg: str) -> None:
    print(f"FAIL: {msg}")
    sys.exit(1)


def trials(pm, n=6, n_ops=140, procs=8):
    out = []
    for rep in range(n):
        h = random_register_history(
            n_ops, procs=procs, info_rate=0.05, seed=7000 + rep,
            bad_at=0.15 if rep % 2 else None,
        )
        out.append(pack_history(h, pm.encode))
    return out


def main() -> None:
    store = tempfile.mkdtemp(prefix="packed-smoke-")
    telemetry.enable(True)
    profile.set_store(store)
    pm = cas_register().packed()
    packs = trials(pm)

    # -- parity: BFS --------------------------------------------------------
    verdicts = {True: 0, False: 0}
    for i, packed in enumerate(packs):
        wide = check_wgl_device(packed, pm, witness=False,
                                packed_lanes=False, time_limit_s=60.0)
        lanes = check_wgl_device(packed, pm, witness=False,
                                 packed_lanes=True, time_limit_s=60.0)
        if lanes.valid != wide.valid:
            fail(f"bfs verdict parity broke on trial {i}: "
                 f"packed={lanes.valid} wide={wide.valid}")
        drift = abs(lanes.configs_explored - wide.configs_explored)
        if drift > max(64, wide.configs_explored // 10):
            fail(f"bfs explored drift on trial {i}: "
                 f"packed={lanes.configs_explored} "
                 f"wide={wide.configs_explored}")
        if wide.valid in (True, False):
            verdicts[wide.valid] += 1
    if min(verdicts.values()) < 2:
        fail(f"parity soak never settled both verdicts: {verdicts}")

    # -- parity: batched ----------------------------------------------------
    bw = check_wgl_batched(packs, pm, packed_lanes=False,
                           time_limit_s=120.0)
    bl = check_wgl_batched(packs, pm, packed_lanes=True,
                           time_limit_s=120.0)
    if bl.valid != bw.valid:
        fail(f"batched verdict parity broke: packed={bl.valid} "
             f"wide={bw.valid}")

    # -- parity: witness + stream -------------------------------------------
    long_h = random_register_history(600, procs=8, info_rate=0.03,
                                     seed=99)
    long_p = pack_history(long_h, pm.encode)
    ww = check_wgl_witness(long_p, pm, packed_lanes=False,
                           time_limit_s=60.0)
    wl = check_wgl_witness(long_p, pm, packed_lanes=True,
                           time_limit_s=60.0)
    if (ww is None) != (wl is None) or \
            (ww is not None and ww.valid != wl.valid):
        fail(f"witness parity broke: packed={wl} wide={ww}")
    sw = check_wgl_witness_stream(packs, pm, packed_lanes=False,
                                  time_limit_s=120.0)
    sl = check_wgl_witness_stream(packs, pm, packed_lanes=True,
                                  time_limit_s=120.0)
    if sl != sw:
        fail(f"stream verdict parity broke: packed={sl} wide={sw}")

    # -- roofline: packed intensity strictly above wide on same shapes ------
    recs = profile.read(os.path.join(store, profile.PROFILE_FILE))
    if not recs:
        fail("no profile records written")

    def intensities(pass_name, packed_flag):
        vals = []
        for r in recs:
            if r["pass"] != pass_name:
                continue
            if bool((r.get("plan") or {}).get("packed")) != packed_flag:
                continue
            c = r.get("cost") or {}
            f, b = c.get("flops"), c.get("bytes_accessed")
            if isinstance(f, (int, float)) and \
                    isinstance(b, (int, float)) and b > 0:
                vals.append(f / b)
        return vals

    compared = 0
    for pass_name in ("bfs", "batched"):
        wide_i = intensities(pass_name, False)
        lane_i = intensities(pass_name, True)
        if not wide_i or not lane_i:
            # The batched pass may fold under bfs on some plans; the
            # bfs comparison below is the hard requirement.
            if pass_name == "bfs":
                fail(f"{pass_name}: missing measured intensities "
                     f"(wide={len(wide_i)} packed={len(lane_i)})")
            continue
        wm = statistics.median(wide_i)
        lm = statistics.median(lane_i)
        if not lm > wm:
            fail(f"{pass_name}: packed median intensity {lm:.3f} not "
                 f"strictly above wide {wm:.3f} flops/byte")
        print(f"{pass_name}: intensity packed {lm:.3f} vs wide "
              f"{wm:.3f} flops/byte ({lm / wm:.2f}x, "
              f"{len(lane_i)}+{len(wide_i)} records)")
        compared += 1
    if compared == 0:
        fail("no pass produced both packed and wide intensities")

    fb = telemetry.counter_value("wgl.packed.fallbacks")
    if fb:
        fail(f"packed kernels shed to wide {fb:g} times during a "
             "clean smoke")
    print(f"PASS packed smoke: {len(packs)} BFS trials (verdict mix "
          f"{verdicts}), batched/witness/stream parity, {compared} "
          "pass(es) above the wide roofline")


if __name__ == "__main__":
    main()
