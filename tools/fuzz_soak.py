"""Long-running cross-engine differential soak (round 4).

Reuses the CI fuzz harness (tests/test_fuzz_parity.py: five model
families, linearizable-by-construction interleavings, early injected
corruption) but runs it for a wall-clock budget with fresh seeds and a
wider size band — including sizes past the witness tier's window-roll
boundaries that the CI-sized soak never reaches.  Any CPU-vs-device
verdict disagreement is a soundness bug in one of the engines and is
printed with its reproduction seed.

Usage: python tools/fuzz_soak.py [--minutes 30] [--seed-base 0]
       [--platform cpu|default]
Prints one JSON summary line at the end; exit 1 on any mismatch.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=30.0)
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("--platform", default="cpu",
                    choices=("cpu", "default"),
                    help='"cpu" pins the CPU backend (default: this '
                         "tool usually runs beside a wedged chip)")
    args = ap.parse_args()

    # Append (don't setdefault): an ambient XLA_FLAGS must not
    # silently drop the 8-device split the parity suite runs under —
    # the conftest pattern.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from test_fuzz_parity import CONFIGS  # the CI harness, verbatim

    from jepsen_tpu.checker.wgl_cpu import check_wgl_cpu
    from jepsen_tpu.history import pack_history
    from jepsen_tpu.ops.wgl import check_wgl_device

    # CI sizes top out at 900; the soak adds sizes that cross the
    # witness window-roll and the >2000-op routing boundary for the
    # register family, and push every other family past its CI max.
    EXTRA_SIZES = {
        "cas-register": (1500, 2600),
        "multi-register": (700,),
        "mutex": (700,),
        "fifo-queue": (600,),
        "unordered-queue": (600,),
    }

    import zlib

    deadline = time.monotonic() + args.minutes * 60.0
    mismatches = []
    trials = 0
    decided: dict[str, int] = {}   # per family-size decided counts
    unknown: dict[str, int] = {}
    errors: dict[str, int] = {}
    round_i = 0
    while time.monotonic() < deadline and not mismatches:
        round_i += 1
        for name, pm_fn, hist_fn, sizes in CONFIGS:
            if time.monotonic() >= deadline or mismatches:
                break
            pm = pm_fn()
            # crc32, not hash(): string hashing is salted per process
            # and would make a reported mismatch unreproducible (the
            # CI harness's own rule).  Reproduction: same --seed-base
            # and round => same family rng => same trial sequence.
            family_seed = (args.seed_base + round_i * 1009 +
                           (zlib.crc32(name.encode()) & 0xFFFF))
            rng = random.Random(family_seed)
            for size in tuple(sizes) + EXTRA_SIZES.get(name, ()):
                for corrupt in (False, True):
                    if time.monotonic() >= deadline or mismatches:
                        break
                    key = f"{name}/{size}"
                    if errors.get(key, 0) >= 5:
                        continue  # this config is systematically sick
                    try:
                        h = hist_fn(rng, size, corrupt)
                        packed = pack_history(h, pm.encode)
                        # The soak's extra sizes get a bigger
                        # exact-oracle budget: at 20 s they mostly
                        # time out to unknown and the boundary
                        # coverage would be vacuous.
                        cpu_budget = (
                            60.0 if size in EXTRA_SIZES.get(name, ())
                            else 20.0
                        )
                        cpu = check_wgl_cpu(packed, pm,
                                            time_limit_s=cpu_budget)
                        dev = check_wgl_device(packed, pm,
                                               time_limit_s=60.0)
                    except Exception as e:  # noqa: BLE001
                        # Hours of compiles can OOM the LLVM JIT (seen
                        # at ~38 min on this box); a dying trial must
                        # not take the summary with it.
                        errors[key] = errors.get(key, 0) + 1
                        print(f"# trial error {key}: "
                              f"{type(e).__name__}: {e}",
                              file=sys.stderr, flush=True)
                        continue
                    trials += 1
                    if "unknown" in (cpu.valid, dev.valid):
                        unknown[key] = unknown.get(key, 0) + 1
                        continue
                    decided[key] = decided.get(key, 0) + 1
                    if cpu.valid is not dev.valid:
                        mismatches.append({
                            "family": name, "size": size,
                            "corrupt": corrupt, "round": round_i,
                            "family_seed": family_seed,
                            "cpu": cpu.valid, "dev": dev.valid,
                        })
                        print(f"MISMATCH: {mismatches[-1]}",
                              flush=True)
        if round_i % 5 == 0:
            print(f"# round {round_i}: {trials} trials, "
                  f"decided {sum(decided.values())}, "
                  f"unknown {sum(unknown.values())}",
                  file=sys.stderr, flush=True)
        if trials == 0 and sum(errors.values()) >= 10:
            # Nothing but errors: the environment is broken (wedged
            # backend, import failure), not merely one flaky trial —
            # don't spin the budget reporting a vacuous clean pass.
            print("# aborting: every trial errors", file=sys.stderr)
            break

    print(json.dumps({
        "trials": trials,
        "rounds": round_i,
        "decided_per_config": decided,
        "unknown_per_config": unknown,
        "errors_per_config": errors,
        "mismatches": len(mismatches),
        "minutes": round(args.minutes, 1),
    }))
    if mismatches:
        return 1
    if trials == 0:
        return 2  # vacuous run: nothing was actually compared
    return 0


if __name__ == "__main__":
    sys.exit(main())
