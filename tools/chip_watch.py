"""Round-long TPU chip-health watcher (VERDICT r3 next-item #1).

Probes the tunneled chip every --interval seconds with bench.probe_chip()
(tiny matmul in a subprocess under a timeout), appends a timestamped line
to CHIP_LOG.md, and on the FIRST healthy probe immediately runs
``python bench.py`` so the TPU measurement is captured and
BENCH_TPU_LAST_GOOD.json is written while the chip breathes.  After a
capture it keeps probing (cheaply) so the log documents the whole round.

The log makes "no TPU number this round" an auditable fact about the
environment rather than a gap in the work.

Usage:  python tools/chip_watch.py [--interval 900] [--once]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "CHIP_LOG.md")
sys.path.insert(0, REPO)

from bench import probe_chip  # noqa: E402


def log_line(text: str) -> None:
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    line = f"- {stamp} {text}"
    with open(LOG, "a") as f:
        f.write(line + "\n")
    print(line, flush=True)


def ensure_header() -> None:
    if os.path.exists(LOG):
        return
    with open(LOG, "w") as f:
        f.write(
            "# Chip probe log\n\n"
            "Timestamped results of `bench.probe_chip()` (one tiny matmul in a\n"
            "subprocess under a 90 s timeout; a healthy chip answers in seconds,\n"
            "a wedged tunnel hangs).  Maintained by `tools/chip_watch.py`, which\n"
            "runs `bench.py` the moment a probe comes back ok.\n\n"
        )


def capture_bench() -> bool:
    """True only when a TPU measurement actually landed (the
    last-good artifact exists) — a failed capture must NOT stop the
    watcher from retrying on the next healthy probe."""
    log_line("probe=ok -> running bench.py to capture TPU measurement")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, timeout=1800, cwd=REPO,
        )
        tail = proc.stdout.decode(errors="replace").strip().splitlines()
        line = tail[-1] if tail else "(no output)"
        log_line(f"bench rc={proc.returncode}: {line}")
    except subprocess.TimeoutExpired:
        log_line("bench TIMED OUT (1800 s) despite ok probe")
        return False
    return os.path.exists(
        os.path.join(REPO, "BENCH_TPU_LAST_GOOD.json")
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=900.0)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args()

    ensure_header()
    captured = os.path.exists(os.path.join(REPO, "BENCH_TPU_LAST_GOOD.json"))
    while True:
        t0 = time.time()
        result = probe_chip()
        log_line(f"probe={result} ({time.time() - t0:.1f}s)")
        if result == "ok" and not captured:
            captured = capture_bench()
        if args.once:
            return 0
        time.sleep(max(1.0, args.interval - (time.time() - t0)))


if __name__ == "__main__":
    raise SystemExit(main())
