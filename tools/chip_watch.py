"""Round-long TPU chip-health watcher (VERDICT r3 next-item #1).

Probes the tunneled chip every --interval seconds with bench.probe_chip()
(tiny matmul in a subprocess under a timeout) and appends a timestamped
line to CHIP_LOG.md.  On a healthy probe it works through a prioritized
battery of TPU captures, each with its own "done" artifact and subprocess
timeout (round-4 observation: health windows can be as short as ~4
minutes — bench landed at 03:48Z and the chip was wedged again by
03:52Z, hanging the follow-up A/B.  A hung capture must die on its own
timeout and be retried at the next window, never wedge the watcher):

  1. bench.py                    -> BENCH_TPU_LAST_GOOD.json
  2. compact_ab  (--reps 3)      -> TPU_COMPACT_AB.json
  3. profile_witness (--reps 3)  -> TPU_WITNESS_PROFILE.json
  4. profile_witness 1M ops      -> TPU_WITNESS_PROFILE_1M.json
  5. transfer_ab                 -> TPU_TRANSFER_AB.json
  6. independent_bench           -> TPU_INDEPENDENT_BENCH.json
     (stream-witness + invalid-heavy 200x100 shapes, >=3 reps,
      median+spread — the chip-side counterpart of the CPU-mesh
      floors in tests/test_whole_stack_perf.py)
  7. bench.py scale child x3     -> TPU_SCALE_POINT.json
     (JEPSEN_BENCH_SCALE_CHILD=1 JEPSEN_BENCH_SCALE_REPS=3; battery
      steps can carry an env overlay as a 5th tuple element)

Between battery steps the chip is re-probed so a mid-window wedge stops
the battery instead of feeding it a dead tunnel.  The log makes "no TPU
number this round" an auditable fact about the environment rather than
a gap in the work.

Usage:  python tools/chip_watch.py [--interval 900] [--once]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "CHIP_LOG.md")
sys.path.insert(0, REPO)

from bench import probe_chip, reset_chip  # noqa: E402
from jepsen_tpu.ops import degrade  # noqa: E402


def log_line(text: str) -> None:
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    line = f"- {stamp} {text}"
    with open(LOG, "a") as f:
        f.write(line + "\n")
    print(line, flush=True)


def ensure_header() -> None:
    if os.path.exists(LOG):
        return
    with open(LOG, "w") as f:
        f.write(
            "# Chip probe log\n\n"
            "Timestamped results of `bench.probe_chip()` (one tiny matmul in a\n"
            "subprocess under a 90 s timeout; a healthy chip answers in seconds,\n"
            "a wedged tunnel hangs).  Maintained by `tools/chip_watch.py`, which\n"
            "runs `bench.py` the moment a probe comes back ok.\n\n"
        )


def run_capture(name: str, cmd: list[str], artifact: str,
                timeout: float, env: dict | None = None) -> bool:
    """Run one battery step; write its stdout JSON lines to `artifact`.
    True only when the artifact actually landed — a failed capture must
    NOT stop the watcher from retrying on the next healthy probe.
    `env` entries overlay the watcher's environment (bench.py's
    child-mode switches are env vars, not flags)."""
    log_line(f"probe=ok -> running {name} to capture TPU measurement")
    try:
        proc = subprocess.run(cmd, capture_output=True,
                              timeout=timeout, cwd=REPO,
                              env={**os.environ, **(env or {})})
    except subprocess.TimeoutExpired:
        log_line(f"{name} TIMED OUT ({timeout:.0f} s) despite ok probe")
        return False
    out = proc.stdout.decode(errors="replace").strip()
    json_lines = [ln for ln in out.splitlines()
                  if ln.startswith("{") and ln.rstrip().endswith("}")]
    log_line(f"{name} rc={proc.returncode}: "
             f"{json_lines[-1] if json_lines else '(no JSON output)'}")
    if proc.returncode != 0 or not json_lines:
        return False
    if name != "bench":  # bench self-records its own artifact
        ok = all('"platform": "tpu"' in ln or "'platform': 'tpu'" in ln
                 or '"tpu"' in ln for ln in json_lines)
        if not ok:
            log_line(f"{name} ran on a non-TPU backend; not recording")
            return False
        with open(os.path.join(REPO, artifact), "w") as f:
            f.write("\n".join(json_lines) + "\n")
    return os.path.exists(os.path.join(REPO, artifact))


BATTERY = [
    # VERDICT r4 #8: every battery step runs >=3 reps when a window
    # opens, so the recorded artifacts carry median+spread instead of
    # a single ±30% sample.  bench.py's own loop is already
    # median-of-3 and now records reps/spread_s in last-good.
    ("bench", [sys.executable, "bench.py"],
     "BENCH_TPU_LAST_GOOD.json", 1800.0),
    ("compact_ab", [sys.executable, "tools/compact_ab.py",
                    "--platform", "default", "--reps", "3"],
     "TPU_COMPACT_AB.json", 1200.0),
    ("profile_witness", [sys.executable, "tools/profile_witness.py",
                         "--ops", "100000", "--reps", "3",
                         "--platform", "default"],
     "TPU_WITNESS_PROFILE.json", 1200.0),
    # The long-history scale point (the reference's own perf shape is
    # 1M ops, core_test.clj:127-132).  A wedge killed the first
    # attempt mid-run at 2026-07-31T10:55Z; retried per-window here.
    ("profile_witness_1m", [sys.executable, "tools/profile_witness.py",
                            "--ops", "1000000", "--reps", "3",
                            "--platform", "default"],
     "TPU_WITNESS_PROFILE_1M.json", 1200.0),
    # H2D transfer-mode A/B: "indices"/"device" exist for exactly this
    # chip's ~50 MB/s uplink; CPU measures neutral, so only a live
    # chip can decide whether to flip the default.
    ("transfer_ab", [sys.executable, "tools/transfer_ab.py",
                     "--reps", "3", "--platform", "default"],
     "TPU_TRANSFER_AB.json", 1200.0),
    # The jepsen.independent shapes (stream witness all-valid + the
    # invalid-heavy settling ladder, 200 keys x 100 ops): the CPU-mesh
    # floors live in tests/test_whole_stack_perf.py; this step records
    # the same shapes on the real chip, median of >=3 memo-cold reps.
    ("independent_bench", [sys.executable,
                           "tools/independent_bench.py",
                           "--reps", "3", "--platform", "default"],
     "TPU_INDEPENDENT_BENCH.json", 1200.0),
    # The scale point as its own >=3-rep capture (the embedded bench
    # point is single-rep inside whatever wall the primary left): the
    # child mode is env-switched, hence the env overlay.
    ("scale_point", [sys.executable, "bench.py"],
     "TPU_SCALE_POINT.json", 1800.0,
     {"JEPSEN_BENCH_SCALE_CHILD": "1", "JEPSEN_BENCH_SCALE_REPS": "3"}),
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=900.0)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args()

    ensure_header()
    while True:
        t0 = time.time()
        result = probe_chip()
        log_line(f"probe={result} ({time.time() - t0:.1f}s)")
        if result == "wedged":
            # Machine-readable forensics next to the log line: the
            # structured dossier (env, toolchain versions, lockfile
            # state, probe timing) the wedged-TPU investigation needs.
            dossier = degrade.write_chip_dossier(
                os.path.join(REPO, "chip.json"))
            if dossier:
                log_line(f"wedged dossier -> {dossier}")
            # A wedged tunnel used to mean "sleep and hope" — every
            # bench since r03 logged probe=wedged without ever trying
            # the reset rung that landed for exactly this.  Sweep the
            # stale libtpu lockfiles (ops/degrade.py reset_chip) and
            # re-probe: a recovered window is recorded as
            # ok-after-reset and gets a fresh capture battery.
            note = reset_chip()
            t1 = time.time()
            reprobe = probe_chip()
            if reprobe == "ok":
                result = "ok-after-reset"
            log_line(f"reset attempt ({note}) -> "
                     f"probe={result if reprobe == 'ok' else reprobe} "
                     f"({time.time() - t1:.1f}s)")
        while result in ("ok", "ok-after-reset"):
            pending = [step for step in BATTERY
                       if not os.path.exists(os.path.join(REPO,
                                                          step[2]))]
            if not pending:
                break
            name, cmd, artifact, timeout, *env = pending[0]
            if not run_capture(name, cmd, artifact, timeout,
                               env[0] if env else None):
                break  # wedged or failed mid-window; retry next window
            result = probe_chip()  # still breathing? then next step
        if args.once:
            return 0
        time.sleep(max(1.0, args.interval - (time.time() - t0)))


if __name__ == "__main__":
    raise SystemExit(main())
