#!/bin/sh
# Installs the mounted public key for root and runs sshd in the
# foreground.
set -eu
if [ -f /jepsen-secret/id_ed25519.pub ]; then
    cat /jepsen-secret/id_ed25519.pub >> /root/.ssh/authorized_keys
    chmod 600 /root/.ssh/authorized_keys
fi
exec /usr/sbin/sshd -D -e
