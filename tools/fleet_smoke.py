#!/usr/bin/env python
"""CI smoke for `jepsen fleet` (tier1.yml step).

End-to-end tenant isolation against a real router-fronted checkerd
federation:

  1. A 2-daemon + router fleet (per-tenant DRR weights) plus a
     FleetSupervisor running 3 tenants — kvdb, logd, electd — each a
     real live monitor child with its own store/search dir, fault
     schedule (kill+pause), and checkerd tee carrying its tenant
     identity.
  2. Once every tenant has completed a fault window, the smoke
     SIGKILLs ONE tenant's monitor (kvdb) and ONE checkerd daemon
     mid-run.
  3. Isolation must hold: the surviving tenants' verdict series and
     fault-window counters keep advancing (zero lost samples — the
     pre-kill points are still there and new ones land); the killed
     tenant is auto-restarted by the supervisor and RESUMES its
     coverage frontier (search.json windows/coverage superset); the
     restarted tenant's store stays under its retention budget.
  4. Observability: /api/fleet (served off the fleet root) lists all
     3 tenants with supervisor state, and both the fleet /metrics
     and a daemon /metrics scrape expose the fleet.*/overload
     counter families (daemon side with per-tenant labels).

Exit 0 + "PASS" on success, exit 1 with a reason.  CPU-only.
"""

import json
import os
import signal
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from jepsen_tpu import telemetry, web  # noqa: E402
from jepsen_tpu.monitor.fleet import (FleetRegistry, FleetSupervisor,  # noqa: E402
                                      TenantSpec, tenant_store_dir)
from jepsen_tpu.monitor.retention import disk_bytes  # noqa: E402
from jepsen_tpu.nemesis import selfchaos as sc  # noqa: E402
from jepsen_tpu.telemetry.timeseries import read_disk_series  # noqa: E402

TENANTS = ("kvdb", "logd", "electd")
SERIES = "monitor.ops-per-s"
RETAIN_BYTES = 32 * 1024 * 1024


class Failure(Exception):
    pass


def read_json(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def live_status(root: str, tenant: str) -> dict:
    return read_json(os.path.join(tenant_store_dir(root, tenant),
                                  "live-status.json"))


def search_json(root: str, tenant: str) -> dict:
    return read_json(os.path.join(tenant_store_dir(root, tenant),
                                  "search", "search.json"))


def wait_until(pred, deadline_s: float, what: str):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.5)
    raise Failure(f"timed out waiting for {what}")


def run() -> int:
    telemetry.enable()
    tmp = tempfile.mkdtemp(prefix="fleet-smoke-")
    root = os.path.join(tmp, "fleet")
    chaos = sc.ChaosFleet(
        2, os.path.join(tmp, "checkerd"),
        tenant_weights={t: 1.0 for t in TENANTS}, metrics=True)
    chaos.start()
    print(f"# checkerd fleet: router {chaos.router_addr}, daemons "
          f"{chaos.daemon_ports}")

    reg = FleetRegistry(root)
    for name in TENANTS:
        reg.add(TenantSpec(
            name=name, suite=name, rate=50.0, duration_s=600.0,
            keys=2, procs_per_key=2, cadence_s=1.0,
            live_faults=("kill", "pause"),
            endpoint=chaos.router_addr, deadline_s=30.0,
            tee_window_ops=256, retain_dossiers=8, retain_days=14.0,
            retain_bytes=RETAIN_BYTES))
    sup = FleetSupervisor(root, endpoint=chaos.router_addr,
                          tick_s=0.5, park_after=5, min_uptime_s=3.0,
                          drain_timeout_s=20.0,
                          retention_interval_s=10.0)
    stop = threading.Event()
    sup_thread = threading.Thread(target=sup.run, args=(stop,),
                                  daemon=True)
    sup_thread.start()

    httpd = web.make_server(root, "127.0.0.1", 0)
    web_port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    try:
        # Phase 1: every tenant completes >= 1 fault window.
        wait_until(
            lambda: all(live_status(root, t).get("windows", 0) >= 1
                        for t in TENANTS),
            300.0, "first fault window on all 3 tenants")
        pre = {t: {"windows": live_status(root, t).get("windows", 0),
                   "coverage": live_status(root, t).get("coverage", 0),
                   "series": len(read_disk_series(
                       tenant_store_dir(root, t), SERIES))}
               for t in TENANTS}
        print(f"# all tenants windowed: "
              f"{ {t: p['windows'] for t, p in pre.items()} }")

        # Phase 2: SIGKILL one tenant's monitor and one daemon.
        victim = "kvdb"
        survivors = [t for t in TENANTS if t != victim]
        vchild = sup.children[victim]
        if not vchild.alive():
            raise Failure(f"{victim} monitor not running pre-kill")
        vpid = vchild.proc.pid
        os.kill(vpid, signal.SIGKILL)
        chaos.kill_daemon(0)
        t_kill = time.time()
        print(f"# killed {victim} monitor (pid {vpid}) and daemon 0")
        time.sleep(2.0)
        chaos.restart_daemon(0)

        # Phase 3a: supervisor restarts the victim, which resumes its
        # coverage frontier.
        wait_until(lambda: (sup.children[victim].restarts >= 1
                            and sup.children[victim].alive()),
                   120.0, f"{victim} auto-restart")
        wait_until(
            lambda: (search_json(root, victim).get("windows", 0)
                     > pre[victim]["windows"]
                     and len(search_json(root, victim).get("coverage")
                             or []) >= pre[victim]["coverage"]),
            240.0, f"{victim} search frontier resume")

        # Phase 3b: survivors never lost a verdict sample and keep
        # producing them across both kills.
        for t in survivors:
            wait_until(
                lambda t=t: live_status(root, t).get("windows", 0)
                > pre[t]["windows"],
                240.0, f"survivor {t} window progress")
            pts = read_disk_series(tenant_store_dir(root, t), SERIES)
            before = [p for p in pts if p[0] <= t_kill]
            after = [p for p in pts if p[0] > t_kill]
            if len(before) < pre[t]["series"]:
                raise Failure(
                    f"survivor {t} lost verdict samples: "
                    f"{len(before)} < {pre[t]['series']} pre-kill")
            if not after:
                raise Failure(f"survivor {t} produced no samples "
                              f"after the kills")
            st = live_status(root, t)
            print(f"# survivor {t}: windows {pre[t]['windows']} -> "
                  f"{st.get('windows')}, series {len(before)} pre + "
                  f"{len(after)} post")

        # Phase 3c: retention keeps every tenant's disk bounded.
        for t in TENANTS:
            db = disk_bytes(tenant_store_dir(root, t))
            if db > RETAIN_BYTES:
                raise Failure(f"tenant {t} disk {db} bytes exceeds "
                              f"retention budget {RETAIN_BYTES}")
        if telemetry.counter_value("fleet.retention.sweeps") < 1:
            raise Failure("no retention sweep ran")

        # Phase 4: observability surfaces.
        api = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{web_port}/api/fleet",
            timeout=10).read().decode())
        rows = api.get("tenants") or {}
        if sorted(rows) != sorted(TENANTS):
            raise Failure(f"/api/fleet tenants {sorted(rows)} != "
                          f"{sorted(TENANTS)}")
        vrow = rows[victim].get("supervisor") or {}
        if not vrow.get("restarts"):
            raise Failure(f"/api/fleet shows no restart for {victim}: "
                          f"{vrow}")
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{web_port}/metrics",
            timeout=10).read().decode()
        for family in ("jepsen_fleet_tenant_starts_total",
                       "jepsen_fleet_retention_sweeps_total"):
            if family not in metrics:
                raise Failure(f"{family} missing from fleet /metrics")
        dmetrics = urllib.request.urlopen(
            f"http://127.0.0.1:{chaos.metrics_ports[1]}/metrics",
            timeout=10).read().decode()
        if "jepsen_checkerd_queue_depth" not in dmetrics:
            raise Failure("daemon /metrics missing checkerd families")
        # Per-tenant shed fairness at fleet scale: a shed must never
        # permanently silence a tenant (the satellite-1 property) —
        # any tenant the daemons shed still kept its verdict stream.
        tenant_lines = [ln for ln in dmetrics.splitlines()
                        if "tenant=" in ln]
        shed_tenants = {t for t in TENANTS
                        for ln in tenant_lines
                        if "shed" in ln and f'tenant="{t}"' in ln
                        and not ln.rstrip().endswith(" 0.0")}
        for t in shed_tenants & set(survivors):
            if live_status(root, t).get("windows", 0) <= \
                    pre[t]["windows"]:
                raise Failure(f"tenant {t} was shed and then "
                              f"stalled — shed handling degraded it")
        print(f"# /api/fleet + /metrics ok; per-tenant metric lines: "
              f"{len(tenant_lines)}, shed tenants: "
              f"{sorted(shed_tenants)}")
    finally:
        stop.set()
        sup_thread.join(timeout=60)
        httpd.shutdown()
        chaos.stop()

    print("PASS: 3-tenant fleet survives SIGKILL of one tenant's "
          "monitor and one daemon — survivors keep their verdict "
          "streams intact, the killed tenant auto-restarts and "
          "resumes its search frontier, disk stays under the "
          "retention budget, and the fleet/daemon scrape surfaces "
          "agree")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(run())
    except Failure as e:
        print(f"FAIL: {e}")
        sys.exit(1)
