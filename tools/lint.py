#!/usr/bin/env python
"""Standalone jepsenlint entry (tier1.yml step).

Equivalent to `jepsen lint` on any suite CLI and to
`python -m jepsen_tpu.analysis`; exists so CI and editors can run the
analyzer without picking a suite.  Exit 0 = no unbaselined findings,
1 = findings, 2 = internal error.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.analysis.core import add_lint_args, main  # noqa: E402

if __name__ == "__main__":
    p = argparse.ArgumentParser(
        prog="jepsenlint",
        description="AST-based invariant analysis: device hygiene, "
        "lock discipline, framework protocols",
    )
    add_lint_args(p)
    try:
        sys.exit(main(p.parse_args()))
    except Exception:  # noqa: BLE001 — CI needs the distinct code
        import traceback

        traceback.print_exc()
        sys.exit(2)
