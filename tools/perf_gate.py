#!/usr/bin/env python
"""Perf-regression gate over recorded per-pass profiles.

Compares a candidate run's profiles.jsonl against a committed
`perf_baseline.json`: records are grouped into shape buckets (pass +
requested-shape features, the same bucketing as tools/profile_diff.py
and costmodel_train.py), and each bucket's median cost and median
roofline flops-ratio are checked against the baseline's.

Machine-speed variance is handled by calibration (on by default): the
global median candidate/baseline cost ratio across shared buckets
rescales the baseline first, so a uniformly slower CI runner cancels
out while a *per-pass* regression — one pass slower than the global
shift — still trips.  Same-machine comparisons (the CI self-check
seeds a baseline from the candidate itself) should pass
`--no-calibrate`.

Noise floors: a bucket only flags when its median exceeds the
(calibrated) expectation by more than `--noise` relative AND
`--min-delta-s` absolute, over at least `--min-n` records on both
sides.  Roofline ratios flag when the candidate achieves less than
`1 - --roofline-noise` of the baseline's fraction-of-peak.

`--seed` writes the baseline from the candidate stores instead of
gating.  `--inflate X --inflate-pass a,b` multiplies the named
passes' candidate costs (and divides their roofline ratios) before
comparing — the planted-slowdown self-test CI runs.  `--selftest`
exercises the true-positive and clean-negative paths on synthetic
stores end-to-end and needs no baseline.

Exit: 0 clean, 1 regression found (or selftest failed), 2 usage/data
errors.  `--advisory` always exits 0 (the tier-1 advisory step).

Usage:
  python tools/perf_gate.py STORE.jsonl [...] --baseline perf_baseline.json
  python tools/perf_gate.py STORE.jsonl --seed --baseline perf_baseline.json
  python tools/perf_gate.py --selftest
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jepsen_tpu.plan import costmodel  # noqa: E402
from jepsen_tpu.telemetry import profile  # noqa: E402

from costmodel_train import shape_key  # noqa: E402

BASELINE_VERSION = 1

#: Calibration shift clamp: a CI runner outside 4x of the baseline
#: machine is a configuration problem, not a signal to scale away.
SHIFT_CLAMP = (0.25, 4.0)


def bucketize(records: list[dict]) -> dict[str, dict]:
    """{shape_key: {"pass", "n", "median_cost_s",
    "median_flops_ratio"}} over normalized records."""
    costs: dict[str, list[float]] = {}
    ratios: dict[str, list[float]] = {}
    passes: dict[str, str] = {}
    for rec in records:
        sk = shape_key(rec)
        passes[sk] = rec["pass"]
        costs.setdefault(sk, []).append(costmodel.record_cost_s(rec))
        r = (rec.get("roofline") or {}).get("flops_ratio")
        if isinstance(r, (int, float)):
            ratios.setdefault(sk, []).append(float(r))
    out = {}
    for sk, vals in costs.items():
        rv = ratios.get(sk)
        out[sk] = {
            "pass": passes[sk],
            "n": len(vals),
            "median_cost_s": round(statistics.median(vals), 6),
            "median_flops_ratio":
                round(statistics.median(rv), 9) if rv else None,
        }
    return out


def seed_baseline(records: list[dict], path: str) -> dict:
    base = {
        "v": BASELINE_VERSION,
        "buckets": bucketize(records),
    }
    with open(path, "w") as f:
        json.dump(base, f, indent=2, sort_keys=True)
        f.write("\n")
    return base


def load_baseline(path: str) -> dict:
    with open(path) as f:
        base = json.load(f)
    if not isinstance(base, dict) or base.get("v") != BASELINE_VERSION:
        raise ValueError(f"{path}: not a v{BASELINE_VERSION} baseline")
    buckets = base.get("buckets")
    if not isinstance(buckets, dict):
        raise ValueError(f"{path}: missing buckets")
    return base


def inflate(buckets: dict[str, dict], factor: float,
            passes: set[str]) -> dict[str, dict]:
    """The planted-slowdown transform: multiplies the named passes'
    costs by `factor` (a slower pass achieves proportionally less of
    peak, so ratios divide)."""
    out = {}
    for sk, b in buckets.items():
        b = dict(b)
        if not passes or b.get("pass") in passes:
            b["median_cost_s"] = round(b["median_cost_s"] * factor, 6)
            if b.get("median_flops_ratio") is not None:
                b["median_flops_ratio"] = round(
                    b["median_flops_ratio"] / factor, 9)
        out[sk] = b
    return out


def compare(base_buckets: dict[str, dict], cand_buckets: dict[str, dict],
            *, noise: float, roofline_noise: float, min_delta_s: float,
            min_n: int, calibrate: bool) -> dict:
    """{shift, compared, regressions: [...], improvements: [...]}."""
    shared = [
        sk for sk in cand_buckets
        if sk in base_buckets
        and base_buckets[sk].get("median_cost_s")
        and cand_buckets[sk]["n"] >= min_n
        and base_buckets[sk].get("n", 0) >= min_n
    ]
    shift = 1.0
    if calibrate and shared:
        shift = statistics.median(
            cand_buckets[sk]["median_cost_s"]
            / base_buckets[sk]["median_cost_s"]
            for sk in shared
        )
        shift = min(max(shift, SHIFT_CLAMP[0]), SHIFT_CLAMP[1])
    regressions, improvements = [], []
    for sk in sorted(shared):
        base, cand = base_buckets[sk], cand_buckets[sk]
        expect = base["median_cost_s"] * shift
        got = cand["median_cost_s"]
        row = {
            "pass": cand.get("pass"),
            "bucket": sk,
            "expected_s": round(expect, 6),
            "measured_s": got,
            "ratio": round(got / expect, 3) if expect else None,
        }
        if got > expect * (1 + noise) and got - expect > min_delta_s:
            row["kind"] = "cost"
            regressions.append(row)
            continue
        br, cr = base.get("median_flops_ratio"), \
            cand.get("median_flops_ratio")
        if (isinstance(br, (int, float)) and br > 0
                and isinstance(cr, (int, float))
                and cr < br * (1 - roofline_noise)
                and got - expect > min_delta_s):
            row["kind"] = "roofline"
            row["baseline_flops_ratio"] = br
            row["measured_flops_ratio"] = cr
            regressions.append(row)
            continue
        if expect and got < expect / (1 + noise):
            row["kind"] = "improvement"
            improvements.append(row)
    return {
        "shift": round(shift, 4),
        "compared": len(shared),
        "candidate_buckets": len(cand_buckets),
        "baseline_buckets": len(base_buckets),
        "regressions": regressions,
        "improvements": improvements,
    }


def _synthetic_store(path: str, slow_pass_factor: float = 1.0) -> None:
    """Writes a deterministic two-pass store for --selftest: 'alpha'
    records at ~10ms and 'beta' at ~40ms, beta scaled by
    `slow_pass_factor` (the planted regression)."""
    import io

    lines = io.StringIO()
    for i in range(6):
        jitter = 1.0 + 0.02 * (i % 3)
        for name, base_s, flops in (("alpha", 0.010, 2e6),
                                    ("beta", 0.040, 8e6)):
            s = base_s * jitter
            if name == "beta":
                s *= slow_pass_factor
            lines.write(json.dumps({
                "v": 2, "pass": name,
                "features": {"keys": 8, "ops": 4096},
                "plan": {},
                "timing": {"execute_s": round(s, 6),
                           "total_s": round(s * 1.2, 6)},
                "cost": {"flops": flops, "bytes_accessed": flops / 4,
                         "transcendentals": None, "device_calls": 1},
                "roofline": {"flops_ratio":
                             round(flops / s / 1e11, 9)},
            }) + "\n")
    with open(path, "w") as f:
        f.write(lines.getvalue())


def selftest() -> int:
    """End-to-end gate behavior on synthetic stores: seeding, the
    clean-negative, the planted 2x true-positive, and calibration
    cancelling a uniform machine slowdown."""
    with tempfile.TemporaryDirectory() as d:
        base_store = os.path.join(d, "base.jsonl")
        slow_store = os.path.join(d, "slow.jsonl")
        baseline = os.path.join(d, "baseline.json")
        _synthetic_store(base_store)
        _synthetic_store(slow_store, slow_pass_factor=2.0)
        seed_baseline(profile.read(base_store), baseline)
        base = load_baseline(baseline)
        kw = dict(noise=0.35, roofline_noise=0.6, min_delta_s=0.005,
                  min_n=3)
        clean = compare(base["buckets"],
                        bucketize(profile.read(base_store)),
                        calibrate=False, **kw)
        if clean["regressions"] or clean["compared"] < 2:
            print(f"# selftest FAIL: clean run flagged {clean}")
            return 1
        planted = compare(base["buckets"],
                          bucketize(profile.read(slow_store)),
                          calibrate=False, **kw)
        hit = [r for r in planted["regressions"] if r["pass"] == "beta"]
        if not hit or any(r["pass"] == "alpha"
                          for r in planted["regressions"]):
            print(f"# selftest FAIL: planted 2x not isolated {planted}")
            return 1
        # A uniformly 3x-slower "machine" with the same planted 2x:
        # calibration must absorb the 3x and still isolate beta.
        uniform = {
            sk: {**b,
                 "median_cost_s": round(b["median_cost_s"] * 3, 6)}
            for sk, b in bucketize(profile.read(slow_store)).items()
        }
        cal = compare(base["buckets"], uniform, calibrate=True, **kw)
        hit = [r for r in cal["regressions"] if r["pass"] == "beta"]
        if not hit:
            print(f"# selftest FAIL: calibrated planted 2x missed {cal}")
            return 1
        print("# selftest ok: clean-negative, planted 2x "
              "true-positive, calibrated true-positive")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="gate per-pass cost/roofline medians against a "
                    "committed baseline")
    ap.add_argument("stores", nargs="*",
                    help="candidate profiles.jsonl paths")
    ap.add_argument("--baseline", default="perf_baseline.json")
    ap.add_argument("--seed", action="store_true",
                    help="write the baseline from the stores and exit")
    ap.add_argument("--noise", type=float, default=0.35,
                    help="relative cost noise floor (default 0.35)")
    ap.add_argument("--roofline-noise", type=float, default=0.6,
                    help="relative flops-ratio floor (default 0.6)")
    ap.add_argument("--min-delta-s", type=float, default=0.005,
                    help="absolute regression floor (default 5ms)")
    ap.add_argument("--min-n", type=int, default=3,
                    help="records per bucket per side (default 3)")
    ap.add_argument("--no-calibrate", action="store_true",
                    help="skip machine-speed calibration "
                         "(same-machine baselines)")
    ap.add_argument("--inflate", type=float, default=None,
                    help="multiply candidate costs (planted-slowdown "
                         "self-test)")
    ap.add_argument("--inflate-pass", default="",
                    help="comma-separated passes --inflate applies to "
                         "(default: all)")
    ap.add_argument("--advisory", action="store_true",
                    help="report regressions but exit 0")
    ap.add_argument("--min-compared", type=int, default=0,
                    help="fail unless at least this many buckets were "
                         "actually compared (guards against a store "
                         "too thin for min-n — a gate that compared "
                         "nothing proved nothing)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in TP/TN check on synthetic "
                         "stores (needs no baseline)")
    args = ap.parse_args()

    if args.selftest:
        return selftest()
    if not args.stores:
        print("# no candidate stores given", file=sys.stderr)
        return 2

    records: list[dict] = []
    for path in args.stores:
        got = profile.read(path)
        print(f"# {path}: {len(got)} records")
        records.extend(got)
    if not records:
        print("# no records; nothing to gate", file=sys.stderr)
        return 2

    if args.seed:
        base = seed_baseline(records, args.baseline)
        print(f"# seeded {args.baseline}: "
              f"{len(base['buckets'])} buckets")
        return 0

    try:
        base = load_baseline(args.baseline)
    except (OSError, ValueError) as e:
        print(f"# baseline unusable: {e}", file=sys.stderr)
        return 2
    cand = bucketize(records)
    if args.inflate:
        passes = {p.strip() for p in args.inflate_pass.split(",")
                  if p.strip()}
        cand = inflate(cand, args.inflate, passes)
        print(f"# planted {args.inflate}x slowdown on "
              f"{sorted(passes) or 'all passes'}")
    report = compare(
        base["buckets"], cand,
        noise=args.noise, roofline_noise=args.roofline_noise,
        min_delta_s=args.min_delta_s, min_n=args.min_n,
        calibrate=not args.no_calibrate,
    )
    if args.json:
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        print(f"# {report['compared']} shared buckets "
              f"(candidate {report['candidate_buckets']}, baseline "
              f"{report['baseline_buckets']}), calibration shift "
              f"{report['shift']}")
        for r in report["regressions"]:
            print(f"REGRESSION [{r['kind']}] {r['pass']}: "
                  f"{r['measured_s'] * 1000:.1f}ms vs expected "
                  f"{r['expected_s'] * 1000:.1f}ms "
                  f"(x{r['ratio']})")
        for r in report["improvements"]:
            print(f"improved {r['pass']}: "
                  f"{r['measured_s'] * 1000:.1f}ms vs expected "
                  f"{r['expected_s'] * 1000:.1f}ms")
        if not report["regressions"]:
            print("# clean: no per-pass regression beyond noise floors")
    if report["compared"] < args.min_compared:
        print(f"# FAIL: only {report['compared']} buckets compared "
              f"(--min-compared {args.min_compared}) — store too thin "
              "to prove anything", file=sys.stderr)
        return 1 if not args.advisory else 0
    if report["regressions"] and not args.advisory:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
