#!/usr/bin/env python
"""A/B the witness engine's transfer modes on the current platform.

transfer="full" ships pre-gathered (NB,6,K)+(NB,5,W) block tables per
chunk call (~74 KB/block); "indices" uploads the per-row tables once
and ships only row-index arrays (~22 KB/block), rebuilding tables on
device; "device" (round 5) plans the blocks on device too — ~640 B
per chunk and no host-side per-block numpy at all.  CPU measures
"full" fastest (the device IS the host's cores, so host-built tables
win); the lever exists for the tunneled TPU's ~50 MB/s uplink
(tools/tunnel_diag.py), where the full mode's ~5 MB/100k-op history
costs ~0.1-0.15 s of a ~0.4 s check plus ~0.35 s of serialized host
numpy that "device" removes entirely.

Usage: python tools/transfer_ab.py [--ops 100000] [--reps 2]
       [--platform default|cpu]
Prints one JSON line per mode.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=100_000)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--platform", default="default")
    args = ap.parse_args()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform

    from jepsen_tpu.history.packed import pack_history
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.ops import wgl_witness as ww
    from jepsen_tpu.utils.histgen import random_register_history

    pm = cas_register().packed()
    h = random_register_history(args.ops, procs=16, info_rate=0.05,
                                seed=45100)
    packed = pack_history(h, pm.encode)
    width = ww.plan_width(packed)

    for mode in ("full", "indices", "device"):
        ww.check_wgl_witness(packed, pm, transfer=mode,
                             width_hint=width)  # warm
        times = []
        for _ in range(args.reps):
            t0 = time.monotonic()
            r = ww.check_wgl_witness(packed, pm, transfer=mode,
                                     width_hint=width)
            dt = time.monotonic() - t0
            assert r is not None and r.valid is True
            times.append(dt)
        from jepsen_tpu.utils import summarize_times

        s = summarize_times(times)
        print(json.dumps({
            "mode": mode, "ops": args.ops, **s,
            "ops_per_s": round(args.ops / s["median_s"]),
            "platform": platform,
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
