#!/usr/bin/env python
"""Fault-matrix harness: the robustness layer end-to-end.

Runs a dummy-remote register suite through the FULL lifecycle
(core.run -> store -> analyze) under each injected failure —

  hanging-client    an op that never returns; the op_timeout watchdog
                    must complete it as :info and rotate the worker
  hanging-checker   a compose child that sleeps forever; the
                    checker_budget must degrade it to unknown while
                    its siblings still report
  crashing-checker  a compose child that raises; isolated the same way
  wgl-fault         JEPSEN_WGL_FAULT=all forces every WGL tier to fail
                    with synthetic RESOURCE_EXHAUSTED; the ladder must
                    settle the verdict on the exact CPU engine and
                    report the degradation path

— asserting in every cell that the run TERMINATES within its deadline,
the history is saved and re-loadable, and per-checker verdicts are
present (with the degraded tier in metadata where the ladder ran).

Usage: JAX_PLATFORMS=cpu python tools/fault_matrix.py

`run_matrix()` / the individual `scenario_*` functions are importable,
so a pytest test can exercise the same cells CI runs.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu import client as jc  # noqa: E402

#: Per-scenario wall-clock ceiling: generous next to the knobs below
#: (op_timeout <= 1 s, checker_budget <= 2 s), tight next to a hang.
SCENARIO_DEADLINE_S = 120.0


def _register_test(store_dir: str, **overrides) -> dict:
    """A dummy-remote cas-register test map (tests/test_core.py's
    factory, restated here so the tool is self-contained)."""
    import random

    from jepsen_tpu import checker as chk
    from jepsen_tpu import generator as gen
    from jepsen_tpu import net as jnet
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.models import cas_register

    t = {
        "name": "fault-matrix",
        "nodes": ["n1", "n2", "n3"],
        "concurrency": "2n",
        "store-dir": store_dir,
        "ssh": {"dummy?": True},
        "net": jnet.noop,
        "client": _AtomRegister(),
        "model": cas_register(),
        "generator": gen.time_limit(
            0.4,
            gen.clients(gen.stagger(0.005, gen.mix([
                gen.FnGen(lambda: {"f": "read"}),
                gen.FnGen(lambda: {"f": "write",
                                   "value": random.randrange(5)}),
            ]))),
        ),
        "checker": chk.compose({
            "stats": chk.Stats(),
            "linear": linearizable(algorithm="cpu"),
        }),
    }
    t.update(overrides)
    return t


class _AtomRegister(jc.Client):
    """In-memory linearizable register (shared-state client)."""

    def __init__(self, state=None, lock=None):
        self.state = state if state is not None else {"v": None}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return _AtomRegister(self.state, self.lock)

    def invoke(self, test, op):
        from jepsen_tpu.history import FAIL, OK

        with self.lock:
            if op.f == "write":
                self.state["v"] = op.value
                return op.complete(OK)
            if op.f == "read":
                return op.complete(OK, value=self.state["v"])
            old, new = op.value
            if self.state["v"] == old:
                self.state["v"] = new
                return op.complete(OK)
            return op.complete(FAIL)


class _HangingRegister(_AtomRegister):
    """Hangs forever on ~every 10th write; released on teardown so the
    abandoned daemon threads exit once the scenario is over."""

    def __init__(self, state=None, lock=None, release=None, counter=None):
        super().__init__(state, lock)
        self.release = release if release is not None else threading.Event()
        self.counter = counter if counter is not None else [0]

    def open(self, test, node):
        return _HangingRegister(
            self.state, self.lock, self.release, self.counter
        )

    def invoke(self, test, op):
        if op.f == "write":
            with self.lock:
                self.counter[0] += 1
                hang = self.counter[0] % 10 == 0
            if hang:
                self.release.wait(SCENARIO_DEADLINE_S)
        return super().invoke(test, op)


class _MortalRegister(_AtomRegister):
    """An _AtomRegister whose node can die: once `dead[node]` is set,
    opens are refused and in-flight invokes drop the connection — the
    client's-eye view of a host that is simply gone."""

    def __init__(self, state=None, lock=None, dead=None, node=None):
        super().__init__(state, lock)
        self.dead = dead if dead is not None else {}
        self.node = node

    def open(self, test, node):
        if self.dead.get(node):
            raise ConnectionRefusedError(f"{node} is dead")
        return _MortalRegister(self.state, self.lock, self.dead, node)

    def invoke(self, test, op):
        if self.dead.get(self.node):
            raise ConnectionResetError(f"{self.node} died mid-op")
        return super().invoke(test, op)


def _run_with_deadline(test: dict) -> dict:
    """core.run under the scenario deadline: a matrix cell that hangs
    is itself a robustness failure and must be reported, not waited on."""
    from jepsen_tpu import core
    from jepsen_tpu.utils import JepsenTimeout, timeout

    res = timeout(SCENARIO_DEADLINE_S * 1000.0, lambda: core.run(test))
    if res is JepsenTimeout:
        raise AssertionError(
            f"run did not terminate within {SCENARIO_DEADLINE_S} s"
        )
    return res


def _assert_history_saved(test: dict) -> None:
    """The store dir must hold a re-loadable history + results."""
    from jepsen_tpu import store

    d = store.test_dir(test)
    tf = store.load(d)
    try:
        n = sum(1 for _ in tf.iter_ops())
        assert n == len(test["history"]), (
            f"saved history has {n} ops, run produced "
            f"{len(test['history'])}"
        )
        assert tf.results is not None and "valid" in tf.results
    finally:
        tf.close()


def scenario_hanging_client(store_dir: str) -> dict:
    client = _HangingRegister()
    test = _register_test(
        store_dir,
        client=client,
        op_timeout=0.5,
        drain_timeout=2.0,
    )
    try:
        test = _run_with_deadline(test)
    finally:
        client.release.set()
    h = test["history"]
    timed_out = [
        o for o in h if o.is_info and "timed out" in (o.error or "")
    ]
    assert timed_out, "watchdog never fired on the hanging client"
    for o in h:
        if o.is_invoke:
            assert h.completion(o) is not None, "unpaired invocation"
    _assert_history_saved(test)
    res = test["results"]
    assert "stats" in res and "linear" in res
    return {
        "ops": len(h),
        "op_timeouts": len(timed_out),
        "valid": res["valid"],
    }


def scenario_hanging_checker(store_dir: str) -> dict:
    from jepsen_tpu import checker as chk
    from jepsen_tpu.checker.linearizable import linearizable

    ev = threading.Event()

    def hang(test, history, opts):
        ev.wait(SCENARIO_DEADLINE_S)
        return {"valid": True}

    test = _register_test(
        store_dir,
        checker=chk.compose({
            "stats": chk.Stats(),
            "linear": linearizable(algorithm="cpu"),
            "hung": chk.checker(hang, name="hung"),
        }),
        checker_budget=2.0,
    )
    try:
        test = _run_with_deadline(test)
    finally:
        ev.set()
    res = test["results"]
    assert res["hung"]["valid"] == "unknown"
    assert "budget" in res["hung"]["error"]
    # Siblings' partial results survive the hung child.
    assert res["stats"]["valid"] is True
    assert res["linear"]["valid"] is True
    assert res["valid"] == "unknown"
    _assert_history_saved(test)
    return {"valid": res["valid"], "hung": res["hung"]["error"]}


def scenario_crashing_checker(store_dir: str) -> dict:
    from jepsen_tpu import checker as chk
    from jepsen_tpu.checker.linearizable import linearizable

    def boom(test, history, opts):
        raise RuntimeError("checker crashed")

    test = _register_test(
        store_dir,
        checker=chk.compose({
            "stats": chk.Stats(),
            "linear": linearizable(algorithm="cpu"),
            "crash": chk.checker(boom, name="crash"),
        }),
    )
    test = _run_with_deadline(test)
    res = test["results"]
    assert res["crash"]["valid"] == "unknown"
    assert "checker crashed" in res["crash"]["error"]
    assert "traceback" in res["crash"]
    assert res["stats"]["valid"] is True
    assert res["linear"]["valid"] is True
    _assert_history_saved(test)
    return {"valid": res["valid"], "crash": res["crash"]["error"]}


def scenario_wgl_fault(store_dir: str) -> dict:
    from jepsen_tpu import checker as chk
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.ops import degrade

    test = _register_test(
        store_dir,
        checker=chk.compose({
            "stats": chk.Stats(),
            "linear": linearizable(algorithm="wgl-tpu", time_limit_s=60.0),
        }),
    )
    old = os.environ.get(degrade.FAULT_ENV)
    os.environ[degrade.FAULT_ENV] = "all"
    try:
        test = _run_with_deadline(test)
    finally:
        if old is None:
            os.environ.pop(degrade.FAULT_ENV, None)
        else:
            os.environ[degrade.FAULT_ENV] = old
    res = test["results"]
    lin = res["linear"]
    # Every device tier failed; the exact CPU engine settled the verdict
    # and the ladder's path made it into the metadata.
    assert lin["valid"] is True, lin
    assert lin.get("degradations"), "degraded tiers missing from metadata"
    tiers = {s["tier"] for s in lin["degradations"]}
    assert "device" in tiers, tiers
    assert res["stats"]["valid"] is True
    _assert_history_saved(test)
    return {
        "valid": res["valid"],
        "algorithm": lin["algorithm"],
        "degraded_tiers": sorted(tiers),
    }


def scenario_nemesis_crash(store_dir: str) -> dict:
    """Control-plane crash mid-fault, then `jepsen repair`: all four
    fault families (partition, netem, clock, process) are injected and
    their heals abandoned via JEPSEN_NEMESIS_FAULT=abandon — the
    in-test stand-in for a SIGKILL'd control process.  The run must
    leave outstanding ledger entries on disk (and count them as
    nemesis.residue.outstanding), and `core.repair` must replay every
    compensator until the residue sweep reports clean — twice, since
    repairing a clean dir is a no-op."""
    import random

    from jepsen_tpu import core, generator as gen, net as jnet
    from jepsen_tpu import store, telemetry
    from jepsen_tpu.nemesis import combined as ncombined, core as ncore
    from jepsen_tpu.nemesis import ledger as nledger
    from jepsen_tpu.nemesis.faults import ClockNemesis, HammerTime

    packet_nem = ncombined.packet_package(
        {"faults": {"packet"}, "interval": 0.05}
    )["nemesis"]
    nem = ncore.compose([
        ({"start-partition": "start", "stop-partition": "stop"},
         ncore.partitioner(
             lambda nodes: ncore.complete_grudge(ncore.bisect(nodes)))),
        packet_nem,
        ClockNemesis(),
        ({"start-hammer": "start", "stop-hammer": "stop"},
         HammerTime("regd")),
    ])
    nem_gen = [
        {"type": "info", "f": "start-partition", "value": None},
        {"type": "info", "f": "start-packet"},
        {"type": "info", "f": "bump", "value": 1000},
        {"type": "info", "f": "start-hammer", "value": None},
    ]
    client_gen = gen.stagger(0.005, gen.mix([
        gen.FnGen(lambda: {"f": "read"}),
        gen.FnGen(lambda: {"f": "write", "value": random.randrange(5)}),
    ]))
    test = _register_test(
        store_dir,
        net=jnet.iptables,  # real net impl; commands no-op on dummy remotes
        nemesis=nem,
        generator=gen.time_limit(0.8, gen.nemesis(nem_gen, client_gen)),
    )
    old_fault = os.environ.get(nledger.FAULT_ENV)
    was_enabled = telemetry.enabled()
    os.environ[nledger.FAULT_ENV] = "abandon"
    telemetry.enable(True)
    try:
        test = _run_with_deadline(test)
    finally:
        if old_fault is None:
            os.environ.pop(nledger.FAULT_ENV, None)
        else:
            os.environ[nledger.FAULT_ENV] = old_fault
        telemetry.enable(was_enabled)
    _assert_history_saved(test)

    d = store.test_dir(test)
    led_path = nledger.ledger_path(d)
    outstanding = nledger.outstanding_entries(
        nledger.read_records(led_path)
    )
    fams = {e["fault"] for e in outstanding}
    assert {"partition", "netem", "clock", "process"} <= fams, (
        f"expected all four families stranded, got {sorted(fams)}"
    )
    resil = test["results"].get("resilience") or {}
    assert resil.get("nemesis.residue.outstanding", 0) >= 4, resil

    # Recovery: repair reopens sessions from the stored test map alone.
    report = core.repair(d)
    assert report["clean"], report
    assert set(report["healed"]) == {e["id"] for e in outstanding}, report
    # Idempotence: a second repair finds nothing to do.
    report2 = core.repair(d)
    assert report2["outstanding"] == 0 and report2["clean"], report2
    return {
        "stranded_families": sorted(fams),
        "stranded_entries": len(outstanding),
        "healed": len(report["healed"]),
        "second_repair_outstanding": report2["outstanding"],
    }


def scenario_node_death(store_dir: str) -> dict:
    """One node dies permanently mid-run under `tolerate` policy: its
    opens are refused and in-flight invokes disconnect.  The health
    monitor must pick up the passive signals, confirm via probes, and
    quarantine the node; from then on its ops complete as fast :fail
    (the armed op_timeout must never fire), the run completes on the
    two survivors, and the results carry the availability timeline."""
    from jepsen_tpu import telemetry

    dead: dict = {}
    victim = "n3"
    test = _register_test(
        store_dir,
        client=_MortalRegister(dead=dead),
        generator=None,  # replaced below: longer window than default
        op_timeout=5.0,
        **{
            "node-loss-policy": "tolerate:2",
            "health-probe": lambda test, node: not dead.get(node),
            # ~0.45 s of probation before quarantine: long enough that
            # the dead node's workers demonstrably retry (and fail)
            # opens first, short enough to leave >1 s of fast-fail.
            "health-probe-interval": 0.15,
            "health-quarantine-after": 3,
        },
    )
    import random

    from jepsen_tpu import generator as gen

    test["generator"] = gen.time_limit(
        2.0,
        gen.clients(gen.stagger(0.01, gen.mix([
            gen.FnGen(lambda: {"f": "read"}),
            gen.FnGen(lambda: {"f": "write",
                               "value": random.randrange(5)}),
        ]))),
    )
    killer = threading.Timer(0.4, lambda: dead.__setitem__(victim, True))
    was_enabled = telemetry.enabled()
    telemetry.enable(True)
    killer.start()
    try:
        test = _run_with_deadline(test)
    finally:
        killer.cancel()
        telemetry.enable(was_enabled)
    _assert_history_saved(test)

    res = test["results"]
    resil = res.get("resilience") or {}
    nodes = resil.get("nodes") or {}
    assert nodes.get(victim, {}).get("state") == "quarantined", nodes
    timeline = nodes[victim]["timeline"]
    assert any(e["to"] == "quarantined" for e in timeline), timeline
    # Survivors stayed healthy and did real work.
    h = test["history"]
    oks = [o for o in h if o.is_ok]
    assert oks, "no successful ops on the surviving nodes"
    for n in ("n1", "n2"):
        assert nodes.get(n, {}).get("state") == "healthy", nodes
    # Ops against the corpse fast-failed — no per-op timeout burn: the
    # armed watchdog never fired.
    from jepsen_tpu.history import FAIL

    fast_fails = [
        o for o in h
        if o.type == FAIL and "quarantined" in (o.error or "")
    ]
    assert fast_fails, "no fast-fail ops against the quarantined node"
    assert resil.get("interpreter.op-timeouts", 0) == 0, resil
    assert resil.get("node.quarantined", 0) >= 1, resil
    assert resil.get("client.open.failed", 0) >= 1, resil
    return {
        "ops": len(h),
        "ok_ops": len(oks),
        "fast_fails": len(fast_fails),
        "timeline": [
            {"from": e["from"], "to": e["to"]} for e in timeline
        ],
    }


class _KillableDB:
    """A DB whose kill/start toggle the same `dead` map
    _MortalRegister reads: killing the process takes the node's client
    face down too, exactly what an overlapping partition+kill composes
    against."""

    def __init__(self, dead):
        from jepsen_tpu import db as jdb

        self._base = jdb.NoopDB()
        self.dead = dead

    def __getattr__(self, name):
        return getattr(self._base, name)

    def kill(self, test, sess, node):
        self.dead[node] = True

    def start(self, test, sess, node):
        self.dead[node] = False

    def pause(self, test, sess, node):
        self.dead[node] = True

    def resume(self, test, sess, node):
        self.dead[node] = False


def scenario_composed_faults(store_dir: str) -> dict:
    """Overlapping kill+partition on the SAME node under tolerate:2 —
    the fault composition class a one-fault-at-a-time matrix never
    exercises: n3's process is killed while a partition isolates n3
    from the survivors, then both heal in overlap order.  Asserts the
    run terminates, every ledger entry is healed (kill's db-start and
    the partition's net-heal), the residue sweep finds nothing, and the
    checker still reaches a verdict on the surviving majority.

    The schedule is expressed as a search genome and compiled through
    `nemesis.search.compile_schedule` — the same path `jepsen search`
    candidates take — so this cell also pins the genome->generator
    contract against a known composition."""
    import random

    from jepsen_tpu import generator as gen, net as jnet, telemetry
    from jepsen_tpu.nemesis import ledger as nledger, search

    dead: dict = {}
    victim = "n3"
    sched = search.Schedule(seed=11, events=(
        search.Event(family="kill", t=0.15, duration=0.5,
                     targets=[victim], salt=1),
        search.Event(family="partition", t=0.3, duration=0.5,
                     params={"kind": "one", "isolate": victim}, salt=2),
    ))
    client_gen = gen.stagger(0.005, gen.mix([
        gen.FnGen(lambda: {"f": "read"}),
        gen.FnGen(lambda: {"f": "write", "value": random.randrange(5)}),
    ]))
    test = _register_test(
        store_dir,
        net=jnet.iptables,  # real net impl; commands no-op on dummy remotes
        client=_MortalRegister(dead=dead),
        db=_KillableDB(dead),
        **{"node-loss-policy": "tolerate:2"},
    )
    # Both events take at most one node down at once — the tolerate:2
    # floor the search itself would enforce holds by construction.
    assert search.respects_floor(sched, len(test["nodes"]), 2)
    pkg = search.compile_schedule(sched, {"interval": 0.05},
                                  nodes=test["nodes"])
    fs = [op["f"] for _, op in pkg["timeline"]]
    assert fs == ["kill", "start-partition", "start", "stop-partition"], fs
    test["nemesis"] = pkg["nemesis"]
    test["generator"] = gen.time_limit(
        pkg["horizon"] + 0.4, gen.nemesis(pkg["generator"], client_gen)
    )
    was_enabled = telemetry.enabled()
    telemetry.enable(True)
    try:
        test = _run_with_deadline(test)
    finally:
        telemetry.enable(was_enabled)
    _assert_history_saved(test)

    from jepsen_tpu import store

    d = store.test_dir(test)
    records = nledger.read_records(nledger.ledger_path(d))
    fams = {e["fault"] for e in records if e.get("rec") == "intent"}
    assert {"process", "partition"} <= fams, sorted(fams)
    outstanding = nledger.outstanding_entries(records)
    assert not outstanding, outstanding
    assert dead.get(victim) is False, dead  # the DB came back
    resil = test["results"].get("resilience") or {}
    residue = {k: v for k, v in resil.items()
               if k.startswith("nemesis.residue.") and v}
    assert not residue, residue
    res = test["results"]
    assert res["stats"]["valid"] is True, res["stats"]
    assert res["linear"]["valid"] in (True, False), res["linear"]
    h = test["history"]
    assert any(o.f == "kill" and o.type == "info" for o in h)
    assert any(o.f == "start-partition" and o.type == "info" for o in h)
    return {
        "timeline": fs,
        "ledger_families": sorted(fams),
        "ops": len(h),
        "valid": res["valid"],
    }


SCENARIOS = {
    "hanging-client": scenario_hanging_client,
    "hanging-checker": scenario_hanging_checker,
    "crashing-checker": scenario_crashing_checker,
    "wgl-fault": scenario_wgl_fault,
    "nemesis-crash": scenario_nemesis_crash,
    "node-death": scenario_node_death,
    "composed-faults": scenario_composed_faults,
}


def run_matrix(names=None) -> dict:
    """Runs each scenario in its own temp store dir; returns
    {name: detail}.  Raises AssertionError on the first failing cell."""
    out = {}
    for name, fn in SCENARIOS.items():
        if names and name not in names:
            continue
        with tempfile.TemporaryDirectory(prefix=f"fm-{name}-") as d:
            out[name] = fn(os.path.join(d, "store"))
    return out


def main(argv) -> int:
    import logging

    logging.basicConfig(level=logging.WARNING)
    results = run_matrix(set(argv[1:]) or None)
    print(json.dumps({"fault_matrix": "ok", "scenarios": results},
                     default=repr))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
