#!/usr/bin/env python
"""Witness-engine cost decomposition: sweep vs heavy chain rounds.

VERDICT r2 #6 asked either for the heavy chain search to move into the
Pallas kernel or for a measured profile showing the easy sweep
dominates end-to-end time.  This tool produces that profile on the
bench configs (BASELINE.json north star: 100k and 1M ops):

  total   — check_wgl_device wall time on the real bench history
            (info_rate as configured: heavy rounds fire at barriers
            the easy path cannot survive).
  sweep   — the same history shape with info_rate=0: identical barrier
            count, zero heavy rounds, so the whole run is the barrier
            sweep (Pallas kernel on TPU, lax.scan on CPU).
  chain   — total - sweep: the marginal cost of every heavy round
            (targeted + expand escalations AND their lax.cond
            scheduling overhead), i.e. the most the chain search could
            save if it were free.

Method note: info-free histories have slightly fewer packed rows (the
same op count, but no indeterminate rows widening the window), so
`sweep` is measured per-barrier and scaled to the real history's
barrier count before subtraction.  Each figure is the best of
`--reps` runs after a compile warm-up.

Usage: python tools/profile_witness.py [--ops 100000] [--reps 3]
       [--platform cpu|default]
Prints one JSON line per config; paste into
doc/design-notes/witness-profile.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def measure(n_ops: int, reps: int, info_rate: float = 0.05,
            procs: int = 16) -> dict:
    from jepsen_tpu.history.packed import pack_history
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.ops.wgl import check_wgl_device
    from jepsen_tpu.ops.wgl_witness import plan_width
    from jepsen_tpu.utils.histgen import random_register_history

    pm = cas_register().packed()

    def packed_for(rate, seed):
        h = random_register_history(
            n_ops, procs=procs, info_rate=rate, seed=seed
        )
        return pack_history(h, pm.encode)

    real = packed_for(info_rate, 45100)
    easy = packed_for(0.0, 45100)
    width = plan_width(real)

    def timed(packed, label):
        times = []
        # warm-up compiles the kernel shape for this bucket
        check_wgl_device(packed, pm, time_limit_s=600.0,
                         width_hint=width)
        for _ in range(reps):
            t0 = time.monotonic()
            res = check_wgl_device(packed, pm, time_limit_s=600.0,
                                   width_hint=width)
            dt = time.monotonic() - t0
            assert res.valid is True, (label, res.valid, res.reason)
            times.append(dt)
        times.sort()
        return times

    from jepsen_tpu.utils import summarize_times

    real_times = timed(real, "real")
    t_total = real_times[0]
    t_sweep_raw = timed(easy, "sweep-only")[0]
    # scale the sweep cost to the real history's barrier count
    scale = real.n_ok / max(1, easy.n_ok)
    t_sweep = t_sweep_raw * scale
    return {
        "n_ops": n_ops,
        "info_rate": info_rate,
        "barriers": int(real.n_ok),
        "total_s": round(t_total, 3),
        # Multi-rep evidence (VERDICT r4 #8): median + min/max spread
        # across the measured reps, so a single capture is auditable
        # against the chip's observed ±30% run-to-run variance.
        **summarize_times(real_times),
        "sweep_s": round(t_sweep, 3),
        "chain_s": round(max(0.0, t_total - t_sweep), 3),
        "sweep_pct": round(100.0 * t_sweep / t_total, 1),
        "ops_per_s": round(n_ops / t_total),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, nargs="*",
                    default=[100_000, 1_000_000])
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--info", type=float, default=0.05)
    ap.add_argument("--platform", default="default",
                    help='"cpu" pins the CPU backend')
    args = ap.parse_args()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform

    for n in args.ops:
        rec = measure(n, args.reps, info_rate=args.info)
        rec["platform"] = platform
        print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
