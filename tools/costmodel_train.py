#!/usr/bin/env python
"""Train the plan cost model from recorded per-pass profiles.

The offline half of plan/costmodel.py: reads one or more
profiles.jsonl stores (telemetry/profile.py — the declared training
set), fits the per-pass ridge regressor over history-shape + knob
features, and writes the model JSON that `JEPSEN_COSTMODEL=<path>`
loads at runtime.  Untrained processes keep the hand heuristics, so
shipping no model file is always safe.

`--eval` replays the SAME recorded data as a knob-choice benchmark, in
profile_diff's bucket terms: records are grouped by shape bucket (pass
+ requested-shape features), then by knob config within the bucket.
For each bucket holding at least two configs, the model picks the
config it predicts cheapest; the pick WINS when its measured median
cost beats the hand-heuristic config's measured median.  `--require-win`
exits nonzero unless the model wins at least one bucket — the CI
acceptance gate for "the trained model beats the heuristics on at
least one recorded shape".

Usage:
  python tools/costmodel_train.py STORE.jsonl [STORE2.jsonl ...]
      [--out model.json] [--min-samples 4] [--eval] [--require-win]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jepsen_tpu.plan import costmodel  # noqa: E402
from jepsen_tpu.telemetry import profile  # noqa: E402

#: Same exclusion set as tools/profile_diff.py: measured outputs never
#: define a shape bucket.
MEASURED_FEATURES = frozenset((
    "explored", "attempts", "kept_units", "checks", "device_s",
    "proven", "settled", "merged", "passes", "restarts",
))

#: A bucket is noise-dominated when the model-vs-heuristic median gap
#: is within this many within-config MADs — the measured "win" or
#: "loss" is then a timing coin-flip, not a knob effect, and the
#: --require-win gate downgrades to advisory for it.
NOISE_FACTOR = 2.0


def _mad_s(samples: list[float]) -> float:
    """Median absolute deviation — the within-config timing spread."""
    m = statistics.median(samples)
    return statistics.median(abs(x - m) for x in samples)


def shape_key(rec: dict) -> str:
    feats = {
        k: v for k, v in (rec.get("features") or {}).items()
        if k not in MEASURED_FEATURES
    }
    return json.dumps({"pass": rec["pass"], "features": feats},
                      sort_keys=True, default=repr)


def knob_key(rec: dict) -> str:
    plan = {
        k: rec["plan"][k] for k in costmodel.KNOB_KEYS
        if k in (rec.get("plan") or {})
    }
    return json.dumps(plan, sort_keys=True)


def heuristic_config(pass_name: str, features: dict,
                     configs: list[dict]) -> dict | None:
    """The knob config the hand-wired ladder would have picked for this
    shape, iff it appears among the bucket's recorded configs."""
    if pass_name == "stream":
        keys = int(features.get("keys") or 0)
        want = costmodel.heuristic_stream_knobs(keys)
        for c in configs:
            if all(c.get(k) == v for k, v in want.items()):
                return c
        return None
    if pass_name == "batched":
        # The ladder starts batched at min(lin.beam, 32); the requested
        # beam is not recorded, so the widest recorded beam <= 32
        # stands in for the legacy start.
        beams = [c.get("beam") for c in configs
                 if isinstance(c.get("beam"), (int, float))]
        legal = [b for b in beams if b <= 32]
        if not legal:
            return None
        start = max(legal)
        for c in configs:
            if c.get("beam") == start:
                return c
    return None


def evaluate(model: costmodel.CostModel, records: list[dict]) -> dict:
    """{buckets, comparable, wins, losses, ties, rows} over the
    recorded data."""
    shapes: dict[str, dict[str, list[float]]] = {}
    feats_of: dict[str, dict] = {}
    roofs_of: dict[str, dict[str, list[float]]] = {}
    for rec in records:
        if rec["pass"] not in model.passes:
            continue
        sk = shape_key(rec)
        feats_of[sk] = {
            k: v for k, v in rec["features"].items()
            if k not in MEASURED_FEATURES
        }
        shapes.setdefault(sk, {}).setdefault(
            knob_key(rec), []
        ).append(costmodel.record_cost_s(rec))
        # Roofline columns per bucket (v2 records; v1 contribute
        # nothing and the columns render as None).
        roof = roofs_of.setdefault(sk, {})
        cost = rec.get("cost") or {}
        roofline = rec.get("roofline") or {}
        for col, v in (("flops", cost.get("flops")),
                       ("bytes_accessed", cost.get("bytes_accessed")),
                       ("flops_ratio", roofline.get("flops_ratio"))):
            if isinstance(v, (int, float)):
                roof.setdefault(col, []).append(float(v))

    rows = []
    wins = losses = ties = comparable = 0
    for sk, by_cfg in sorted(shapes.items()):
        if len(by_cfg) < 2:
            continue
        cfg = json.loads(sk)
        pass_name, features = cfg["pass"], feats_of[sk]
        configs = [json.loads(k) for k in by_cfg]
        heur = heuristic_config(pass_name, features, configs)
        if heur is None:
            continue
        comparable += 1
        roof = roofs_of.get(sk) or {}
        med_roof = {col: round(statistics.median(vals), 6)
                    for col, vals in roof.items() if vals}
        # The bucket's median cost block feeds prediction identically
        # for every config (cost describes the shape, not the knobs),
        # so roofline-aware models rank configs without train/serve
        # feature skew.
        cost_feats = {k: med_roof.get(k)
                      for k in costmodel.COST_KEYS} \
            if any(k in med_roof for k in costmodel.COST_KEYS) else None
        preds = []
        for k in by_cfg:
            p = model.predict_s(pass_name, features, json.loads(k),
                                cost_feats)
            preds.append((p if p is not None else float("inf"), k))
        picked = min(preds)[1]
        heur_k = json.dumps(heur, sort_keys=True)
        picked_s = statistics.median(by_cfg[picked])
        heur_s = statistics.median(by_cfg[heur_k])
        if picked == heur_k or picked_s == heur_s:
            verdict = "tie"
            ties += 1
        elif picked_s < heur_s:
            verdict = "win"
            wins += 1
        else:
            verdict = "loss"
            losses += 1
        # Noise dominance: the verdict only means something when the
        # median gap clears the within-config timing spread.
        noise_s = max(_mad_s(by_cfg[picked]), _mad_s(by_cfg[heur_k]))
        noisy = (len(by_cfg[picked]) < 2 or len(by_cfg[heur_k]) < 2
                 or abs(picked_s - heur_s) <= NOISE_FACTOR * noise_s)
        rows.append({
            "pass": pass_name,
            "features": features,
            "configs": len(by_cfg),
            "model-config": json.loads(picked),
            "model-median-s": round(picked_s, 6),
            "heuristic-config": heur,
            "heuristic-median-s": round(heur_s, 6),
            "verdict": verdict,
            "noise-s": round(noise_s, 6),
            "noise-dominated": noisy,
            "median-flops": med_roof.get("flops"),
            "median-bytes-accessed": med_roof.get("bytes_accessed"),
            "median-flops-ratio": med_roof.get("flops_ratio"),
        })
    return {
        "buckets": len(shapes),
        "comparable": comparable,
        "wins": wins,
        "losses": losses,
        "ties": ties,
        "clean-wins": sum(1 for r in rows
                          if r["verdict"] == "win"
                          and not r["noise-dominated"]),
        "noise-dominated": sum(1 for r in rows
                               if r["noise-dominated"]),
        "rows": rows,
    }


def main() -> int:
    ap = argparse.ArgumentParser(
        description="fit the plan cost model from profiles.jsonl stores"
    )
    ap.add_argument("stores", nargs="+",
                    help="profiles.jsonl paths (telemetry/profile.py)")
    ap.add_argument("--out", default="costmodel.json",
                    help="model output path (default costmodel.json)")
    ap.add_argument("--min-samples", type=int,
                    default=costmodel.MIN_SAMPLES,
                    help="per-pass training floor (default "
                         f"{costmodel.MIN_SAMPLES})")
    ap.add_argument("--eval", action="store_true",
                    help="benchmark model vs heuristic knob choices "
                         "on the recorded shape buckets")
    ap.add_argument("--require-win", action="store_true",
                    help="exit 1 unless the model wins >=1 bucket "
                         "(implies --eval)")
    ap.add_argument("--json", action="store_true",
                    help="emit the eval report as JSON")
    args = ap.parse_args()

    records: list[dict] = []
    for path in args.stores:
        got = profile.read(path)
        print(f"# {path}: {len(got)} records")
        records.extend(got)
    if not records:
        print("# no records; nothing to train")
        return 1

    model = costmodel.fit(records, min_samples=args.min_samples)
    if not model.passes:
        print(f"# no pass reached {args.min_samples} samples; "
              f"no model written (runtime keeps the heuristics)")
        return 1
    model.save(args.out)
    for name in sorted(model.passes):
        p = model.passes[name]
        print(f"# trained {name}: n={p['n']} "
              f"rmse_log={p['rmse_log']:.4f}")
    print(f"# wrote {args.out}")

    if not (args.eval or args.require_win):
        return 0
    report = evaluate(model, records)
    if args.json:
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        for r in report["rows"]:
            print(f"{r['verdict']:>5}  {r['pass']:<10} "
                  f"{json.dumps(r['features'], sort_keys=True)} "
                  f"model {r['model-config']} "
                  f"{r['model-median-s'] * 1000:.1f}ms vs heuristic "
                  f"{r['heuristic-config']} "
                  f"{r['heuristic-median-s'] * 1000:.1f}ms")
    print(f"# {report['comparable']} comparable buckets: "
          f"{report['wins']} win(s), {report['ties']} tie(s), "
          f"{report['losses']} loss(es); "
          f"{report['noise-dominated']} noise-dominated")
    if args.require_win and report["wins"] < 1:
        # When every comparable bucket's verdict is inside the timing
        # noise floor, a zero-win run is a coin-flip, not a regression
        # (PR-16 known flake): downgrade to advisory with an
        # annotation instead of failing the gate.  A zero-win run with
        # at least one CLEAN (signal-dominated) bucket still fails —
        # there the model genuinely lost.
        clean = [r for r in report["rows"] if not r["noise-dominated"]]
        if report["comparable"] and not clean:
            msg = (f"costmodel --require-win: 0 wins, but all "
                   f"{report['comparable']} comparable bucket(s) are "
                   f"noise-dominated (median gap within "
                   f"{NOISE_FACTOR}x the within-config MAD); "
                   f"win-requirement downgraded to advisory")
            print(f"# ADVISORY: {msg}")
            # GitHub Actions annotation; inert noise elsewhere.
            print(f"::warning title=costmodel advisory::{msg}")
            return 0
        print("# FAIL: model beats the heuristics on no recorded "
              "bucket and at least one bucket is signal-dominated")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
