#!/usr/bin/env python
"""CI smoke for anomaly forensics + the SLO engine (tier1.yml step).

Plants a non-linearizable register run and asserts the forensics
pipeline end-to-end:

  * `core.analyze` over a mixed-validity keyed history attaches a
    ``forensics`` block and writes a dossier for the bad key;
  * the dossier's minimal counterexample is strictly smaller than the
    original per-key subhistory and is *re-refuted* here by the exact
    CPU engine, from the written JSON alone;
  * the linviz SVG and the timeline HTML rendered;
  * the same run routed through a real checkerd daemon produces a
    byte-identical counterexample.json (remote parity);
  * a blown verdict-lag SLO fires (postmortem dumped, `slo.jsonl`
    transition journaled, `jepsen_slo_firing{rule=...} 1` exported),
    then clears; and the daemon's /metrics scrape carries the
    jepsen_slo_firing family.

Exit 0 + "PASS" on success, exit 1 with a reason otherwise.  CPU-only:
the workflow runs it under JAX_PLATFORMS=cpu.
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["JEPSEN_TELEMETRY"] = "1"

from jepsen_tpu import core, store, telemetry  # noqa: E402
from jepsen_tpu.checker.linearizable import Linearizable  # noqa: E402
from jepsen_tpu.checker.wgl_cpu import check_wgl_cpu  # noqa: E402
from jepsen_tpu.history.core import History, Op  # noqa: E402
from jepsen_tpu.history.packed import pack_history  # noqa: E402
from jepsen_tpu.models.registers import Register  # noqa: E402
from jepsen_tpu.parallel.independent import (  # noqa: E402
    KV,
    IndependentChecker,
)
from jepsen_tpu.telemetry import flight, profile, slo  # noqa: E402


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def history() -> History:
    """Key "good" is linearizable; key "bad" reads a never-written
    value twice, with a healthy write around it, so the minimal
    counterexample has room to shrink."""
    ops = []

    def add(process, f, key, value):
        i = len(ops)
        ops.append({"index": i, "type": "invoke", "process": process,
                    "f": f, "value": KV(key, None if f == "read" else value),
                    "time": i * 1_000_000})
        ops.append({"index": i + 1, "type": "ok", "process": process,
                    "f": f, "value": KV(key, value), "time": (i + 1) * 1_000_000})

    add(0, "write", "good", 1)
    add(0, "read", "good", 1)
    add(1, "write", "bad", 1)
    add(1, "read", "bad", 1)
    add(1, "read", "bad", 9)
    add(1, "write", "bad", 2)
    return History(ops)


def fail(msg: str) -> None:
    print(f"FAIL: {msg}")
    sys.exit(1)


def analyze_run(tmp: str, name: str, checkerd=None) -> tuple[dict, str]:
    run_dir = os.path.join(tmp, name)
    os.makedirs(run_dir, exist_ok=True)
    test = {
        "name": name,
        "start-time": store.time_str(),
        "checker": IndependentChecker(Linearizable(Register())),
        "model": Register(),
    }
    if checkerd:
        test["checkerd"] = checkerd
    results = core.analyze(test, history(), dir=run_dir)
    return results, run_dir


def check_dossier(results: dict, run_dir: str) -> str:
    """Asserts one complete dossier for key "bad"; returns the path of
    its counterexample.json."""
    forens = results.get("forensics")
    if not isinstance(forens, dict):
        fail(f"no forensics block in results: {sorted(results)}")
    dossiers = forens.get("dossiers") or []
    bad = [d for d in dossiers if d.get("key") == "'bad'"]
    if not bad:
        fail(f"no dossier for key 'bad': {dossiers}")
    d = bad[0]["dir"]
    for fn in ("dossier.json", "counterexample.json",
               "counterexample.txt", "death.json", "linear.svg",
               "timeline.html", "profiles.json", "trace-slice.json",
               "flight.json", "nemesis.json"):
        p = os.path.join(d, fn)
        if not os.path.isfile(p) or os.path.getsize(p) == 0:
            fail(f"dossier file {fn} missing or empty in {d}")
    ce_path = os.path.join(d, "counterexample.json")
    with open(ce_path) as f:
        ce = json.load(f)

    # Strictly smaller than the original per-key subhistory.
    if not ce["op-count"] < ce["original-op-count"]:
        fail(f"counterexample not smaller: {ce['op-count']} vs "
             f"{ce['original-op-count']}")

    # Re-refute from the written JSON alone: the exact CPU engine must
    # still reject the minimal subhistory.
    ops = [Op.from_dict(o) for o in ce["ops"]]
    h = History(ops, reindex=False)
    pm = Register().packed()
    res = check_wgl_cpu(pack_history(h, pm.encode), pm)
    if res.valid is not False:
        fail(f"shrunk counterexample no longer refuted: {res.valid}")

    # The timeline highlights the crashed op; the SVG draws the death.
    with open(os.path.join(d, "timeline.html")) as f:
        if "border:2px solid" not in f.read():
            fail("timeline.html has no highlighted op")
    with open(os.path.join(d, "linear.svg")) as f:
        if "<svg" not in f.read(200):
            fail("linear.svg is not an SVG")
    if not ce.get("signature"):
        fail("counterexample carries no anomaly signature")
    return ce_path


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="forensics-smoke-")
    port, mport = free_port(), free_port()
    addr = f"127.0.0.1:{port}"
    env = dict(os.environ, JEPSEN_TELEMETRY="1", JAX_PLATFORMS="cpu")
    daemon = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.checkerd",
         "--host", "127.0.0.1", "--port", str(port),
         "--metrics-port", str(mport),
         "--profile-dir", os.path.join(tmp, "daemon"),
         "--batch-window", "0.2", "--platform", "cpu"],
        env=env,
    )
    try:
        deadline = time.monotonic() + 60
        while True:
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=1):
                    break
            except OSError:
                if daemon.poll() is not None:
                    fail(f"daemon exited early rc={daemon.returncode}")
                if time.monotonic() > deadline:
                    fail("daemon never started listening")
                time.sleep(0.2)

        telemetry.enable(True)
        telemetry.reset()
        profile.set_store(os.path.join(tmp, "local"))

        # --- in-process dossier ------------------------------------
        results, run_dir = analyze_run(tmp, "forensics-smoke")
        if results.get("valid") is not False:
            fail(f"planted run not invalid: {results.get('valid')}")
        local_ce = check_dossier(results, run_dir)

        # --- remote parity -----------------------------------------
        r_results, r_dir = analyze_run(tmp, "forensics-smoke-remote",
                                       checkerd=addr)
        if (r_results.get("checkerd") or {}).get("fallback"):
            fail("remote run fell back in-process; parity untested")
        remote_ce = check_dossier(r_results, r_dir)
        with open(local_ce, "rb") as f:
            local_bytes = f.read()
        with open(remote_ce, "rb") as f:
            remote_bytes = f.read()
        if local_bytes != remote_bytes:
            fail("remote counterexample.json differs from in-process")

        # --- SLO engine: fire, postmortem, journal, clear ----------
        slo_dir = os.path.join(tmp, "slo")
        slo.reset()
        slo.set_dir(slo_dir)
        flight.set_dir(slo_dir)
        telemetry.gauge("wgl.online.verdict-lag-s", 99.0)
        fired = slo.evaluate()
        if not any(t["rule"] == "verdict-lag" and t["rec"] == "firing"
                   for t in fired):
            fail(f"verdict-lag SLO did not fire: {fired}")
        text = telemetry.prometheus_text()
        if 'jepsen_slo_firing{rule="verdict-lag"} 1' not in text:
            fail("firing SLO gauge not exported by prometheus_text")
        if not os.path.isfile(os.path.join(slo_dir, "postmortem.json")):
            fail("firing SLO dumped no postmortem")
        telemetry.gauge("wgl.online.verdict-lag-s", 0.5)
        cleared = slo.evaluate()
        if not any(t["rule"] == "verdict-lag" and t["rec"] == "cleared"
                   for t in cleared):
            fail(f"verdict-lag SLO did not clear: {cleared}")
        journal = slo.read(slo.slo_path(slo_dir))
        if [r["rec"] for r in journal
                if r["rule"] == "verdict-lag"] != ["firing", "cleared"]:
            fail(f"slo.jsonl transitions wrong: {journal}")

        # --- daemon /metrics carries the SLO family ----------------
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/metrics", timeout=5,
        ).read().decode()
        slo_lines = [ln for ln in body.splitlines()
                     if ln.startswith("jepsen_slo_firing{")]
        if not slo_lines:
            fail(f"no jepsen_slo_firing family in daemon /metrics:\n"
                 f"{body[:500]}")

        with open(local_ce) as f:
            ce = json.load(f)
        print(f"PASS: dossier at {os.path.dirname(local_ce)}, "
              f"counterexample {ce['original-op-count']} -> "
              f"{ce['op-count']} ops (sig {ce['signature']}), "
              f"remote parity byte-identical, verdict-lag SLO "
              f"fired+cleared, {len(slo_lines)} SLO gauges scraped")
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()
        slo.set_dir(None)
        flight.set_dir(None)
        profile.set_store(None)


if __name__ == "__main__":
    main()
