#!/usr/bin/env python
"""CI smoke for `jepsen monitor --suite` (tier1.yml step).

One scenario, end to end against real kvdb daemons:

  1. A live monitor subprocess drives the kvdb suite with an evolving
     in-run fault schedule (kill + pause families).  It must complete
     at least one fault window (live-status.json) with novel coverage.
  2. The smoke polls the fault ledger and lands a SIGKILL in the
     inject→heal gap, so the dying monitor strands outstanding intent
     — the crash the repair sweep exists for.
  3. A second monitor on the SAME store dir must sweep the residue
     (`core.repair` replays the db-start compensator), resume the
     search frontier from search.json, keep appending to the same
     series files, and exit cleanly with zero outstanding intent and a
     clean residue probe.

Checks: >= 2 fault families injected AND healed (ledger records),
coverage continuity (resumed map is a superset, window counter
advances, >= 1 novel window), series continuity across the kill, zero
residue at exit.  Exit 0 + "PASS" on success, exit 1 with a reason.
CPU-only: the workflow runs it under JAX_PLATFORMS=cpu.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from jepsen_tpu.nemesis import ledger  # noqa: E402
from jepsen_tpu.telemetry.timeseries import read_disk_series  # noqa: E402

SERIES = "monitor.ops-per-s"


class Failure(Exception):
    pass


def start_monitor(store: str, duration: float) -> subprocess.Popen:
    return subprocess.Popen([
        sys.executable, "-m", "jepsen_tpu.suites.kvdb", "monitor",
        "--suite", "kvdb", "--store-dir", store,
        "--search-dir", os.path.join(store, "search"),
        "--live-faults", "kill,pause",
        "--rate", "50", "--duration", str(duration),
        "--keys", "2", "--procs-per-key", "2", "--cadence", "1",
    ])


def stop(proc: subprocess.Popen) -> None:
    if proc.poll() is None:
        proc.kill()
        proc.wait(timeout=10)


def read_json(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def wait_first_window(store: str, proc: subprocess.Popen,
                      deadline_s: float = 120.0) -> dict:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise Failure(f"live monitor exited early "
                          f"rc={proc.returncode}")
        st = read_json(os.path.join(store, "live-status.json"))
        if st.get("windows", 0) >= 1:
            return st
        time.sleep(0.2)
    raise Failure("no fault window completed before the deadline")


def kill_between_inject_and_heal(store: str, proc: subprocess.Popen,
                                 deadline_s: float = 60.0) -> list:
    """SIGKILL the monitor while the ledger holds outstanding intent —
    i.e. a wound is open and its heal hasn't landed."""
    path = ledger.ledger_path(os.path.join(store, "live"))
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise Failure(f"live monitor exited early "
                          f"rc={proc.returncode}")
        out = ledger.read_outstanding(path)
        if out:
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
            return out
    raise Failure("never caught the ledger with outstanding intent")


def check_families_injected_and_healed(store: str) -> set:
    """>= 2 fault families must have journaled intent AND a healed
    record (by the run itself, the repair sweep, or teardown)."""
    path = ledger.ledger_path(os.path.join(store, "live"))
    records = ledger.read_records(path)
    healed_ids = {r["id"] for r in records if r.get("rec") == "healed"}
    healed_tags = set()
    for r in records:
        if r.get("rec") == "intent" and r["id"] in healed_ids:
            healed_tags.add(r.get("tag"))
    fams = {t for t in healed_tags if t in ("db-kill", "db-pause")}
    if len(fams) < 2:
        raise Failure(f"need >=2 families injected+healed, ledger "
                      f"shows {sorted(healed_tags)}")
    return fams


def run() -> int:
    tmp = tempfile.mkdtemp(prefix="live-monitor-smoke-")
    store = os.path.join(tmp, "store")
    proc = start_monitor(store, duration=300.0)
    try:
        st0 = wait_first_window(store, proc)
        c0, w0 = st0["coverage"], st0["windows"]
        if st0.get("novel-windows", 0) < 1:
            raise Failure(f"first window landed no novel coverage: {st0}")
        pre_pts = read_disk_series(store, SERIES)
        stranded = kill_between_inject_and_heal(store, proc)
    finally:
        stop(proc)
    t_kill = time.time()
    print(f"  killed mid-window with outstanding "
          f"{[(e.get('fault'), e.get('tag')) for e in stranded]}; "
          f"{w0}+ windows, coverage {c0}")

    proc = start_monitor(store, duration=20.0)
    try:
        rc = proc.wait(timeout=180)
    finally:
        stop(proc)
    if rc not in (0, 2):
        raise Failure(f"resumed monitor exited rc={rc}")

    summary = read_json(os.path.join(store, "monitor-summary.json"))
    live = summary.get("live") or {}
    repair = live.get("repair-on-start") or {}
    if not repair.get("healed"):
        raise Failure(f"resume did not sweep the stranded intent: "
                      f"{repair}")
    residue = live.get("residue") or {}
    if residue.get("clean") is not True:
        raise Failure(f"residue probe not clean at exit: {residue}")
    if live.get("outstanding-at-exit") != 0:
        raise Failure(f"outstanding intent at exit: {live}")

    fams = check_families_injected_and_healed(store)

    sj = read_json(os.path.join(store, "search", "search.json"))
    if sj.get("coverage") is None or len(sj["coverage"]) < c0:
        raise Failure(f"coverage map shrank across resume: "
                      f"{len(sj.get('coverage') or [])} < {c0}")
    if sj.get("windows", 0) <= w0:
        raise Failure(f"search did not advance past window {w0}: {sj}")
    if sj.get("novel-windows", 0) < 1:
        raise Failure(f"no novel coverage fingerprint: {sj}")

    merged = read_disk_series(store, SERIES)
    before = [t for t, _ in merged if t <= t_kill]
    after = [t for t, _ in merged if t > t_kill]
    if len(before) < len(pre_pts) or not after:
        raise Failure(f"series not continuous across the kill: "
                      f"{len(before)} pre + {len(after)} post")

    print(f"  resume: repair healed {repair['healed']}, residue clean, "
          f"families {sorted(fams)} injected+healed, search advanced "
          f"{w0} -> {sj['windows']} windows "
          f"(coverage {c0} -> {len(sj['coverage'])}, "
          f"{sj['novel-windows']} novel), series {len(before)} pre + "
          f"{len(after)} post samples")
    print("PASS: live monitor injects+heals across real daemons, a "
          "SIGKILL between inject and heal is swept on resume with "
          "zero residue, and both the verdict stream and the search "
          "frontier continue")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(run())
    except Failure as e:
        print(f"FAIL: {e}")
        sys.exit(1)
