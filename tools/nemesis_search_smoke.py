#!/usr/bin/env python
"""Coverage-guided search smoke: seeded `jepsen search` end-to-end.

Runs the real search loop — CoreRunner, full core.run per iteration,
corpus, shrinker, checkpoint — against an in-process dummy cluster
with a PLANTED multi-fault bug: the register loses its acknowledged
writes only when a process kill lands while a partition is open (the
amnesia models a node dropping unsynced state exactly when it cannot
re-replicate it).  Single-family schedules stay valid; only the
composition is anomalous, so the search has something real to find
and the shrinker something real to minimize.

Asserts:

  1. coverage strictly grows across the seed round (every seed
     iteration contributes novel features);
  2. the search discovers the planted anomaly and shrinks it to a
     reproducer cell that still composes kill + partition in at most
     three events;
  3. every corpus entry replays deterministically — the replay's
     stable features (verdicts, ledger outcomes, hang/error classes;
     timing-bucketed counters excluded) match the recorded signature,
     and its interesting-reasons match exactly;
  4. nothing is left for `jepsen repair`: a post-hoc
     heal_crashed_iterations sweep over the search dir finds no
     outstanding ledger entries.

Usage: JAX_PLATFORMS=cpu python tools/nemesis_search_smoke.py [budget_s]

`run()` is importable so a slow-marked pytest test can exercise the
same smoke CI runs.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from jepsen_tpu import net as jnet  # noqa: E402
from jepsen_tpu.checker import core as chk_core  # noqa: E402

from fault_matrix import _KillableDB, _MortalRegister  # noqa: E402

NODES = ["n1", "n2", "n3"]
#: Families under search: just the two whose composition is the bug.
FAMILIES = ("partition", "kill")
SEED = 3


class _RecordingNet(jnet.IptablesNet):
    """jnet.iptables (command no-ops on dummy remotes) that also keeps
    a shared partition-open flag the amnesia DB reads."""

    def __init__(self, cut: dict):
        super().__init__()
        self.cut = cut

    def drop_all(self, test, grudge):
        self.cut["active"] = True
        super().drop_all(test, grudge)

    def heal(self, test):
        self.cut["active"] = False
        super().heal(test)


class _AmnesiaDB(_KillableDB):
    """The planted bug: a kill inside an open partition rolls the
    register back to None and leaves the store stale — acknowledged
    writes are lost (a replica restarting from a torn log while it
    cannot re-replicate).  Kills outside a partition are harmless."""

    def __init__(self, dead: dict, cut: dict, state: dict):
        super().__init__(dead)
        self.cut = cut
        self.state = state

    def kill(self, test, sess, node):
        if self.cut.get("active"):
            self.state["v"] = None
            self.state["stale"] = True
        super().kill(test, sess, node)


class _LostWriteChecker(chk_core.Checker):
    """Writes are monotonically increasing, so under linearizability a
    read may never observe a value below the highest write acknowledged
    before the read began (None counts as below everything once any
    write is acked).  A violating read IS a lost acknowledged write,
    regardless of interleaving."""

    def check(self, test, history, opts):
        acked_max = None
        floor: dict = {}  # process -> acked_max at that read's invoke
        lost = []
        for i, op in enumerate(history):
            if not op.is_client_op:
                continue
            if op.f == "write":
                if op.type == "ok" and (acked_max is None
                                        or op.value > acked_max):
                    acked_max = op.value
            elif op.f == "read":
                if op.is_invoke:
                    floor[op.process] = acked_max
                else:
                    fl = floor.pop(op.process, None)
                    if (op.type == "ok" and fl is not None
                            and (op.value is None or op.value < fl)):
                        lost.append(i)
        if lost:
            return {"valid": False,
                    "anomaly-types": ["lost-write"],
                    "lost-reads": lost[:8],
                    "count": len(lost)}
        return {"valid": True, "count": 0}


class _AmnesiaRegister(_MortalRegister):
    """_MortalRegister over a monotonic store: writes carry strictly
    increasing values and the register rejects any write at or below
    its current value (a worker delayed by a kill may retry a stale
    value late — without the guard that's a legal regression and the
    checker's floor rule would false-positive on it).  Once the
    amnesia wipe hit, writes are refused entirely: the lost state
    stays lost, so the planted anomaly is observable for the rest of
    the run."""

    def open(self, test, node):
        if self.dead.get(node):
            raise ConnectionRefusedError(f"{node} is dead")
        return _AmnesiaRegister(self.state, self.lock, self.dead, node)

    def invoke(self, test, op):
        from jepsen_tpu.history import FAIL, OK

        if op.f == "write":
            if self.dead.get(self.node):
                raise ConnectionResetError(f"{self.node} died mid-op")
            with self.lock:
                v = self.state["v"]
                if (self.state.get("stale")
                        or (v is not None and op.value <= v)):
                    return op.complete(FAIL)
                self.state["v"] = op.value
                return op.complete(OK)
        return super().invoke(test, op)


def _factory(ignored_store: str):
    """Fresh base test map per iteration: shared register + amnesia DB
    + recording net, per-iteration state so runs don't contaminate
    each other."""
    def make() -> dict:
        import itertools

        from jepsen_tpu import checker as chk
        from jepsen_tpu import generator as gen

        state = {"v": None}
        lock = threading.Lock()
        dead: dict = {}
        cut = {"active": False}
        counter = itertools.count(1)
        return {
            "name": "search-smoke",
            "nodes": list(NODES),
            "concurrency": 3,
            "store-dir": ignored_store,  # CoreRunner redirects to runs/
            "ssh": {"dummy?": True},
            "net": _RecordingNet(cut),
            "db": _AmnesiaDB(dead, cut, state),
            "client": _AmnesiaRegister(state, lock, dead=dead),
            "generator": gen.stagger(0.02, gen.mix([
                gen.FnGen(lambda: {"f": "read"}),
                gen.FnGen(lambda: {"f": "write",
                                   "value": next(counter)}),
            ])),
            "checker": chk.compose({
                "stats": chk.Stats(),
                "lost-write": _LostWriteChecker(),
            }),
            "node-loss-policy": "tolerate:1",
        }
    return make


def _stable(sig) -> frozenset:
    """Signature minus the timing-bucketed `c:` counter features —
    what a deterministic replay must reproduce exactly."""
    return frozenset(f for f in sig if not f.startswith("c:"))


def run(budget_s: float = 60.0, max_iterations=None) -> int:
    from jepsen_tpu import telemetry
    from jepsen_tpu.nemesis import search

    tmp = tempfile.mkdtemp(prefix="jepsen-search-smoke-")
    search_dir = os.path.join(tmp, "search")
    runner = search.CoreRunner(
        _factory(os.path.join(tmp, "store")), search_dir,
        {"iteration-deadline": 30.0, "interval": 0.05},
    )
    telemetry.enable(True)
    try:
        out = search.run_search(
            runner,
            search_dir=search_dir,
            n_nodes=len(NODES),
            budget_s=budget_s,
            seed=SEED,
            families=FAMILIES,
            min_nodes=2,
            max_iterations=max_iterations,
            shrink_attempts=8,
        )
    finally:
        telemetry.enable(False)

    history = out["history"]
    assert len(history) >= len(FAMILIES), (
        f"search ran only {len(history)} iteration(s)"
    )

    # 1. Coverage strictly grows across the seed round.
    seed_round = history[:len(FAMILIES)]
    for h in seed_round:
        assert h["new_features"] > 0, (
            f"seed iteration {h['label']} added no coverage: {h}"
        )
    covs = [h["coverage"] for h in seed_round]
    assert covs == sorted(covs) and len(set(covs)) == len(covs), (
        f"coverage did not strictly grow over the seed round: {covs}"
    )

    # 2. The planted kill-in-partition anomaly was found and shrunk
    #    to a small composed reproducer.
    anomaly = [c for c in out["cells"] if c["reason"] == "anomaly"]
    assert anomaly, (
        f"no anomaly reproducer found in {len(history)} iterations; "
        f"cells={[c['name'] for c in out['cells']]}"
    )
    cell = anomaly[0]
    sched = search.Schedule.from_json(cell["schedule"])
    assert {"kill", "partition"} <= sched.families, (
        f"reproducer lost the composition: {sorted(sched.families)}"
    )
    assert len(sched.events) <= 3, (
        f"shrinker left {len(sched.events)} events"
    )

    # 3. Deterministic replay: every corpus entry reproduces its
    #    recorded stable signature and reasons.
    state = search.load_state(search_dir)
    assert state is not None and state["coverage"] == out["coverage"]
    corpus = search.Corpus(os.path.join(search_dir, "corpus"))
    assert corpus.entries, "corpus is empty"
    replayed = 0
    for entry in corpus.entries:
        got = search.replay(entry, runner)
        want_sig = _stable(frozenset(entry["signature"]))
        got_sig = _stable(search.signature(got))
        assert got_sig == want_sig, (
            f"corpus {entry['id']} replay diverged:\n"
            f"  missing: {sorted(want_sig - got_sig)}\n"
            f"  extra:   {sorted(got_sig - want_sig)}"
        )
        assert search.reasons(got) == list(entry["interesting"]), (
            f"corpus {entry['id']} reasons changed on replay"
        )
        replayed += 1

    # 4. Crash-safety: the whole search dir is repair-clean.
    assert search.heal_crashed_iterations(search_dir) == {}, (
        "search left outstanding ledger entries behind"
    )

    print(json.dumps({
        "iterations": out["stats"]["iterations"],
        "coverage": out["coverage"],
        "corpus": out["corpus"],
        "cells": [c["name"] for c in out["cells"]],
        "reproducer-events": len(sched.events),
        "replayed": replayed,
        "search-dir": search_dir,
    }, indent=2))
    return 0


def main(argv) -> int:
    budget = float(argv[1]) if len(argv) > 1 else 60.0
    return run(budget_s=budget)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
