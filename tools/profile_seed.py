#!/usr/bin/env python
"""Generates a profiles.jsonl store from a fixed checker workload.

The candidate half of the advisory profile-diff CI step: runs the
independent checker over a deterministic multi-key register history
with profiling on, so the per-pass cost records land in
`<dir>/profiles.jsonl` with identical shape features every run —
`tools/profile_diff.py` then buckets this run's records against the
cached previous run's.

Usage: python tools/profile_seed.py OUT_DIR [keys] [pairs-per-key]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["JEPSEN_TELEMETRY"] = "1"

from jepsen_tpu import telemetry  # noqa: E402
from jepsen_tpu.checker.linearizable import Linearizable  # noqa: E402
from jepsen_tpu.history.core import History  # noqa: E402
from jepsen_tpu.models.registers import Register  # noqa: E402
from jepsen_tpu.parallel.independent import (  # noqa: E402
    KV,
    IndependentChecker,
)
from jepsen_tpu.telemetry import profile  # noqa: E402


def seed_history(keys: int, pairs: int) -> History:
    """`keys` independent registers, each `pairs` write/read rounds —
    linearizable by construction, identical shape every run."""
    ops = []
    for k in range(keys):
        for v in range(pairs):
            for f, val in (("write", v), ("read", v)):
                i = len(ops)
                ops.append({"index": i, "type": "invoke", "process": k,
                            "f": f,
                            "value": KV(k, None if f == "read" else val),
                            "time": i})
                ops.append({"index": i + 1, "type": "ok", "process": k,
                            "f": f, "value": KV(k, val), "time": i + 1})
    return History(ops)


def main() -> int:
    out = sys.argv[1] if len(sys.argv) > 1 else "profile-seed"
    keys = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    pairs = int(sys.argv[3]) if len(sys.argv) > 3 else 40
    os.makedirs(out, exist_ok=True)
    telemetry.enable(True)
    telemetry.reset()
    profile.set_store(out)
    try:
        checker = IndependentChecker(Linearizable(Register()))
        res = checker.check({"name": "profile-seed"},
                            seed_history(keys, pairs),
                            {"history-key": None})
        if res.get("valid") is not True:
            print(f"FAIL: seed workload not valid: {res.get('valid')}")
            return 1
        path = profile.store_path()
        n = len(profile.read(path)) if path and os.path.isfile(path) else 0
        if not n:
            print(f"FAIL: no profile records landed in {path}")
            return 1
        print(f"PASS: {n} profile records in {path} "
              f"({keys} keys x {pairs} pairs)")
        return 0
    finally:
        profile.set_store(None)


if __name__ == "__main__":
    sys.exit(main())
