#!/usr/bin/env python
"""Generates a profiles.jsonl store from a fixed checker workload.

The candidate half of the advisory profile-diff CI step: runs the
independent checker over a deterministic multi-key register history
with profiling on, so the per-pass cost records land in
`<dir>/profiles.jsonl` with identical shape features every run —
`tools/profile_diff.py` then buckets this run's records against the
cached previous run's.

With `--sweep`, additionally runs the stream witness over an
invalid-heavy multi-key shape at several segment knobs — the
knob-varied records tools/costmodel_train.py needs: a model can only
out-pick the hand heuristics on shapes where the store actually
recorded more than one knob config.

`--reps N` repeats the checker run N times so every pass bucket holds
N records — tools/perf_gate.py needs >= its --min-n per side before a
bucket participates in the comparison at all.

Usage: python tools/profile_seed.py OUT_DIR [keys] [pairs-per-key]
           [--sweep] [--reps N]
"""

import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["JEPSEN_TELEMETRY"] = "1"

from jepsen_tpu import telemetry  # noqa: E402
from jepsen_tpu.checker.linearizable import Linearizable  # noqa: E402
from jepsen_tpu.history.core import History  # noqa: E402
from jepsen_tpu.models.registers import Register  # noqa: E402
from jepsen_tpu.parallel.independent import (  # noqa: E402
    KV,
    IndependentChecker,
)
from jepsen_tpu.telemetry import profile  # noqa: E402


def seed_history(keys: int, pairs: int) -> History:
    """`keys` independent registers, each `pairs` write/read rounds —
    linearizable by construction, identical shape every run."""
    ops = []
    for k in range(keys):
        for v in range(pairs):
            for f, val in (("write", v), ("read", v)):
                i = len(ops)
                ops.append({"index": i, "type": "invoke", "process": k,
                            "f": f,
                            "value": KV(k, None if f == "read" else val),
                            "time": i})
                ops.append({"index": i + 1, "type": "ok", "process": k,
                            "f": f, "value": KV(k, val), "time": i + 1})
    return History(ops)


def sweep_stream_knobs(repeats: int = 3) -> int:
    """Stream-witness passes over one invalid-heavy shape at several
    segment sizes.  A dead key restarts the stream, and each restart
    re-plans O(segment) rows — so on this shape the small segment
    measurably beats the heuristic ~K/8, giving the trained model a
    recorded bucket to win.  Returns the record count added."""
    from jepsen_tpu.history.packed import pack_history
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.ops.wgl_stream import check_wgl_witness_stream
    from jepsen_tpu.utils.histgen import random_register_history

    pm = cas_register().packed()
    bad = set(range(0, 60, 3))  # 20 of 60 keys defeat the witness
    packs = []
    for i in range(60):
        h = random_register_history(
            120, procs=4, info_rate=0.05, seed=i, bad=(i in bad),
        )
        packs.append(pack_history(h, pm.encode))
    n = 0
    restarts = max(8, len(packs) // 2)  # the heuristic cap: only the
    # Seeded shuffle per repeat: a monotone machine drift (thermal,
    # page-cache warm-up) hits every knob config equally in expectation
    # instead of systematically inflating whichever knob always ran
    # first — the costmodel_train --require-win gate compares measured
    # medians across these configs, so ordering bias reads as signal.
    rng = random.Random(0x5EED)         # segment knob varies
    order = [2, 3, 4, 6, 8, 16]
    for _ in range(repeats):
        rng.shuffle(order)
        for seg in order:
            check_wgl_witness_stream(
                packs, pm, segment_keys=seg, max_restarts=restarts,
            )
            n += 1
    return n


def main() -> int:
    argv = [a for a in sys.argv[1:] if a != "--sweep"]
    sweep = "--sweep" in sys.argv[1:]
    reps = 1
    if "--reps" in argv:
        i = argv.index("--reps")
        reps = max(1, int(argv[i + 1]))
        del argv[i:i + 2]
    out = argv[0] if len(argv) > 0 else "profile-seed"
    keys = int(argv[1]) if len(argv) > 1 else 8
    pairs = int(argv[2]) if len(argv) > 2 else 40
    os.makedirs(out, exist_ok=True)
    telemetry.enable(True)
    telemetry.reset()
    profile.set_store(out)
    try:
        checker = IndependentChecker(Linearizable(Register()))
        for _ in range(reps):
            res = checker.check({"name": "profile-seed"},
                                seed_history(keys, pairs),
                                {"history-key": None})
            if res.get("valid") is not True:
                print(f"FAIL: seed workload not valid: "
                      f"{res.get('valid')}")
                return 1
        if sweep:
            n_sweep = sweep_stream_knobs()
            print(f"# sweep: {n_sweep} knob-varied stream passes")
        path = profile.store_path()
        n = len(profile.read(path)) if path and os.path.isfile(path) else 0
        if not n:
            print(f"FAIL: no profile records landed in {path}")
            return 1
        print(f"PASS: {n} profile records in {path} "
              f"({keys} keys x {pairs} pairs)")
        return 0
    finally:
        profile.set_store(None)


if __name__ == "__main__":
    sys.exit(main())
