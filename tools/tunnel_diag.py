#!/usr/bin/env python
"""Tunnel/dispatch cost diagnostic for the axon-tunneled TPU chip.

The round-4 TPU witness profile (TPU_WITNESS_PROFILE.json) shows the
sweep at 97.5% of witness time on TPU (1.147 s) vs 0.15 s on CPU —
an inversion of the CPU profile where the chain dominates.  The
sweep's device work is ~3 jitted chunk calls, each carrying ~2.4 MB
of host-planned block tensors, so the candidate explanations are
(a) tunnel dispatch round-trip latency, (b) tunnel host->device
bandwidth, or (c) genuinely slow on-device sweep (Pallas while_loop
underutilizing the VPU).  This measures (a) and (b) directly:

  dispatch_us    — per-call latency of a tiny jitted op incl. a
                   blocking fetch of its () result (the sync pattern
                   the witness driver uses between chunk calls)
  h2d_mb_s       — device_put bandwidth at 1/4/16 MB
  d2h_mb_s       — device_get bandwidth at the same sizes
  kernel_us      — per-iteration cost of a 10k-iteration on-device
                   while_loop doing sweep-shaped (8-lane) vector work,
                   amortized: separates on-chip loop speed from the
                   transfer story

Prints one JSON line.  Run under a timeout; the tunnel can wedge.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    rec: dict = {"platform": dev.platform}

    # --- dispatch round trip ---
    @jax.jit
    def tiny(x):
        return x + 1

    x = jnp.zeros((8,), jnp.int32)
    tiny(x).block_until_ready()
    t0 = time.monotonic()
    n = 30
    for _ in range(n):
        tiny(x).block_until_ready()
    rec["dispatch_us"] = round((time.monotonic() - t0) / n * 1e6)

    # --- transfer bandwidth ---
    import numpy as np

    for mb in (1, 4, 16):
        a = np.zeros((mb << 20) // 4, np.int32)
        t0 = time.monotonic()
        d = jax.device_put(a, dev)
        d.block_until_ready()
        h2d = time.monotonic() - t0
        t0 = time.monotonic()
        np.asarray(d)
        d2h = time.monotonic() - t0
        rec[f"h2d_{mb}mb_s"] = round(h2d, 4)
        rec[f"d2h_{mb}mb_s"] = round(d2h, 4)
    rec["h2d_mb_s"] = round(16 / rec["h2d_16mb_s"], 1)
    rec["d2h_mb_s"] = round(16 / rec["d2h_16mb_s"], 1)

    # --- on-device serial loop, sweep-shaped work ---
    B, SW = 8, 4
    ITER = 10_000

    @jax.jit
    def loop(states, alive):
        def body(c):
            k, st, al = c
            ns = st + k
            legal = (ns[0] & 1) == 0
            al2 = al & legal
            st2 = jnp.where(al2, ns, st)
            return k + 1, st2, al2 | al
        k, st, al = jax.lax.while_loop(
            lambda c: c[0] < ITER, body,
            (jnp.int32(0), states, alive),
        )
        return st, al

    st = jnp.zeros((SW, B), jnp.int32)
    al = jnp.ones((B,), jnp.bool_)
    loop(st, al)[0].block_until_ready()
    t0 = time.monotonic()
    loop(st, al)[0].block_until_ready()
    rec["kernel_us_per_iter"] = round(
        (time.monotonic() - t0) / ITER * 1e6, 2
    )

    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
