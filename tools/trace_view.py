#!/usr/bin/env python
"""Print the top spans of a run's telemetry.json.

A JEPSEN_TELEMETRY=1 run (jepsen_tpu/telemetry) writes two files to
its store dir: trace.json (load in https://ui.perfetto.dev for the
flame view) and telemetry.json (aggregate span/counter/gauge summary).
This tool is the terminal view of the latter — "where did the time
go" without leaving the shell:

    python tools/trace_view.py store/<test>/<t>/telemetry.json
    python tools/trace_view.py -n 20 store/latest/telemetry.json

Spans print sorted by total time, with counters and gauges after.
"""

from __future__ import annotations

import argparse
import json
import sys


def format_summary(summ: dict, n: int) -> str:
    lines = []
    spans = sorted(
        (summ.get("spans") or {}).items(),
        key=lambda kv: kv[1].get("total_s", 0),
        reverse=True,
    )
    if spans:
        name_w = max(len(name) for name, _ in spans[:n])
        lines.append(
            f"{'span':<{name_w}}  {'count':>9}  {'total s':>10}  "
            f"{'mean s':>10}  {'max s':>10}"
        )
        for name, st in spans[:n]:
            lines.append(
                f"{name:<{name_w}}  {st.get('count', 0):>9}  "
                f"{st.get('total_s', 0):>10.3f}  "
                f"{st.get('mean_s', 0):>10.6f}  "
                f"{st.get('max_s', 0):>10.6f}"
            )
        if len(spans) > n:
            lines.append(f"... {len(spans) - n} more spans")
    else:
        lines.append("no spans recorded")
    counters = summ.get("counters") or {}
    if counters:
        lines.append("")
        lines.append("counters:")
        for k, v in sorted(counters.items()):
            lines.append(f"  {k} = {v}")
    gauges = summ.get("gauges") or {}
    if gauges:
        lines.append("")
        lines.append("gauges:")
        for k, g in sorted(gauges.items()):
            lines.append(
                f"  {k} = {g.get('last')} "
                f"(min {g.get('min')}, max {g.get('max')}, "
                f"{g.get('samples')} samples)"
            )
    dropped = summ.get("trace_events_dropped", 0)
    if dropped:
        lines.append("")
        lines.append(
            f"note: {dropped} trace events dropped past the buffer cap "
            f"(aggregates above still count them)"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="print the top spans of a telemetry.json"
    )
    ap.add_argument("path", help="path to a telemetry.json")
    ap.add_argument("-n", type=int, default=10,
                    help="spans to show (default 10)")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            summ = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read {args.path}: {e}", file=sys.stderr)
        return 1
    print(format_summary(summ, args.n))
    return 0


if __name__ == "__main__":
    sys.exit(main())
