"""Independent-checking capture for the chip battery (and by hand).

Measures the 200-key x 100-op jepsen.independent shape — the workload
tests/test_whole_stack_perf.py floors on the CPU mesh — on whatever
backend is available, in two variants:

  * **all-valid** — every key linearizable: the key-concatenated
    stream witness (ops/wgl_stream.py) should decide all keys in one
    device pass.
  * **mixed** — ~15% of keys carry a planted violation: the cohort
    settling ladder (parallel/independent.py: stream -> memo ->
    refutation screens -> batched BFS -> parallel CPU settle) does the
    work; the settle memo is cleared before every rep so each rep
    prices the cold ladder.

Each variant runs >= --reps measured reps (plus one compile warm-up)
and prints ONE JSON line with median + spread (utils.summarize_times)
and the backend platform, so tools/chip_watch.py can verify a capture
really ran on the chip before recording it.

Usage:
  python tools/independent_bench.py [--keys 200] [--key-ops 100]
      [--reps 3] [--platform default|cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build(n_keys: int, key_ops: int, n_bad: int):
    from jepsen_tpu.history.core import history as make_history
    from jepsen_tpu.parallel.independent import kv
    from jepsen_tpu.utils.histgen import random_register_history

    ops = []
    for i in range(n_keys):
        h = random_register_history(key_ops, procs=4, info_rate=0.05,
                                    seed=i, bad=(i < n_bad))
        ops += [o.replace(value=kv(f"k{i}", o.value)) for o in h]
    return make_history(ops)


def measure(name: str, hist, n_bad: int, reps: int, platform: str,
            time_limit_s: float) -> dict:
    from jepsen_tpu.checker.linearizable import Linearizable
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.parallel.independent import (
        IndependentChecker, clear_settle_memo,
    )
    from jepsen_tpu.parallel.mesh import default_mesh
    from jepsen_tpu.utils import summarize_times

    from jepsen_tpu import telemetry
    from jepsen_tpu.telemetry import flight, profile

    chk = IndependentChecker(
        Linearizable(cas_register(), time_limit_s=time_limit_s)
    )
    test = {"mesh": default_mesh()}
    # With JEPSEN_TELEMETRY=1 the observatory rides along: the record
    # gains profile_records + flight status so the BENCH trajectory
    # prices the instrumentation's own overhead (<2% target on the
    # mixed shape).
    profile_dir = None
    if telemetry.enabled():
        import tempfile

        profile_dir = tempfile.mkdtemp(prefix=f"bench-profiles-{name}-")
        profile.set_store(profile_dir)
        flight.reset()
    times = []
    for rep in range(reps + 1):  # rep 0 = compile warm-up, not counted
        clear_settle_memo()
        t0 = time.monotonic()
        res = chk.check(test, hist, {})
        dt = time.monotonic() - t0
        expect_valid = n_bad == 0
        if (res["valid"] is True) is not expect_valid or \
                res.get("failure-count", 0) != n_bad:
            return {
                "metric": f"independent_{name}",
                "platform": platform,
                "error": (
                    f"expected {'valid' if expect_valid else 'invalid'}"
                    f" with {n_bad} failures, got valid={res['valid']} "
                    f"failures={res.get('failure-count')}"
                ),
            }
        if rep > 0:
            times.append(dt)
    stats = summarize_times(times)
    rec = {
        "metric": f"independent_{name}",
        "platform": platform,
        "ops_per_s": round((len(hist) / 2) / stats["median_s"], 1),
        **stats,
    }
    if profile_dir is not None:
        rec["profile_records"] = profile.count_records()
        rec["flight"] = flight.status()
        profile.set_store(None)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=200)
    ap.add_argument("--key-ops", type=int, default=100)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--time-limit", type=float, default=300.0)
    ap.add_argument("--platform", default="default",
                    choices=["default", "cpu"])
    args = ap.parse_args()

    if args.platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    platform = jax.devices()[0].platform

    rc = 0
    n_bad = max(1, round(args.keys * 0.15))
    for name, bad in (("stream_all_valid", 0), ("mixed", n_bad)):
        hist = _build(args.keys, args.key_ops, bad)
        rec = measure(name, hist, bad, args.reps, platform,
                      args.time_limit)
        rec.update(keys=args.keys, key_ops=args.key_ops, bad_keys=bad)
        print(json.dumps(rec), flush=True)
        if "error" in rec:
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
