#!/usr/bin/env python
"""CI smoke for checker-as-a-service (tier1.yml step).

Starts a real `jepsen_tpu.checkerd` daemon as a subprocess, points two
concurrent runs at it through RemoteChecker, and asserts

  * both remote verdicts are identical to an in-process
    IndependentChecker over the same histories (per key, not just the
    top-level bool);
  * the two runs were merged into one settle cohort (cohorts-merged
    counter > 0 and each result's merged-runs == 2) — the cross-run
    amortization the daemon exists for.

Exit 0 + "PASS" on success, exit 1 with a reason otherwise.  CPU-only:
the workflow runs it under JAX_PLATFORMS=cpu.
"""

import os
import socket
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from jepsen_tpu.checker.linearizable import Linearizable  # noqa: E402
from jepsen_tpu.checkerd.client import (  # noqa: E402
    CheckerdClient,
    RemoteChecker,
)
from jepsen_tpu.history.core import History  # noqa: E402
from jepsen_tpu.models.registers import Register  # noqa: E402
from jepsen_tpu.parallel.independent import (  # noqa: E402
    KV,
    IndependentChecker,
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def history(prefix: str) -> History:
    """One good register key and one that reads a never-written value."""
    ops = []

    def add(process, f, key, value):
        i = len(ops)
        ops.append({"index": i, "type": "invoke", "process": process,
                    "f": f, "value": KV(key, None if f == "read" else value),
                    "time": i})
        ops.append({"index": i + 1, "type": "ok", "process": process,
                    "f": f, "value": KV(key, value), "time": i + 1})

    add(0, "write", f"{prefix}-good", 1)
    add(0, "read", f"{prefix}-good", 1)
    add(1, "write", f"{prefix}-bad", 1)
    add(1, "read", f"{prefix}-bad", 9)
    return History(ops)


def fail(msg: str) -> None:
    print(f"FAIL: {msg}")
    sys.exit(1)


def main() -> None:
    port = free_port()
    addr = f"127.0.0.1:{port}"
    # Wide batch window so both runs land in one cohort despite CI jitter.
    daemon = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.checkerd",
         "--host", "127.0.0.1", "--port", str(port),
         "--batch-window", "1.0", "--platform", "cpu"],
    )
    try:
        deadline = time.monotonic() + 60
        while True:
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=1):
                    break
            except OSError:
                if daemon.poll() is not None:
                    fail(f"daemon exited early rc={daemon.returncode}")
                if time.monotonic() > deadline:
                    fail("daemon never started listening")
                time.sleep(0.2)

        runs = {"run-a": history("a"), "run-b": history("b")}
        expected = {
            name: IndependentChecker(Linearizable(Register())).check(
                {"name": name}, h, {})
            for name, h in runs.items()
        }
        results: dict = {}
        barrier = threading.Barrier(len(runs))

        def submit(name: str, h: History) -> None:
            rc = RemoteChecker(
                IndependentChecker(Linearizable(Register())),
                addr, run_id=name, fallback=False)
            barrier.wait()
            results[name] = rc.check({"name": name}, h, {})

        threads = [threading.Thread(target=submit, args=(n, h))
                   for n, h in runs.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)

        for name, exp in expected.items():
            got = results.get(name)
            if got is None:
                fail(f"{name}: no remote result")
            if "fallback" in got.get("checkerd", {}):
                fail(f"{name}: fell back in-process: {got['checkerd']}")
            if got["valid"] != exp["valid"]:
                fail(f"{name}: valid {got['valid']} != {exp['valid']}")
            for k, kr in exp["results"].items():
                if got["results"][k]["valid"] != kr["valid"]:
                    fail(f"{name}/{k}: {got['results'][k]['valid']} "
                         f"!= {kr['valid']}")
            merged = got["checkerd"].get("merged-runs")
            if merged != 2:
                fail(f"{name}: merged-runs {merged} != 2")

        with CheckerdClient(addr) as c:
            stats = c.stats()
        if stats["cohorts-merged"] < 1:
            fail(f"cohorts-merged {stats['cohorts-merged']} < 1")
        if not (stats["merge-ratio"] > 0):
            fail(f"merge-ratio {stats['merge-ratio']} not > 0")

        print(f"PASS: 2 runs, verdicts match in-process, "
              f"cohorts-merged={stats['cohorts-merged']}, "
              f"merge-ratio={stats['merge-ratio']}")
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()


if __name__ == "__main__":
    main()
