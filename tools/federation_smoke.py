#!/usr/bin/env python
"""CI smoke for the durable checkerd federation (tier1.yml step).

Phase 1 — router failover, zero lost verdicts: two daemons behind a
`checkerd-router` (with a ticket journal and a /metrics port), two
concurrent runs through the router with in-process fallback DISABLED,
SIGKILL the daemon the router placed the tickets on while they sit in
its batch window.  Asserts both runs still produce verdicts identical
per-key to in-process checking, the router's failover counter fired,
and the /metrics scrape exposes the router gauges.

Phase 2 — daemon crash + restart replay (the acceptance criterion):
one daemon with a --queue journal and a long batch window, submit,
SIGKILL mid-window (ticket accepted and journaled, verdict not yet
computed), restart the daemon on the same port with the same journal,
poll the ORIGINAL ticket.  Asserts the replayed verdict matches the
uninterrupted in-process result per key — zero in-flight verdicts
lost.

Exit 0 + "PASS" on success, exit 1 with a reason otherwise.  CPU-only:
the workflow runs it under JAX_PLATFORMS=cpu.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from jepsen_tpu.checker.linearizable import Linearizable  # noqa: E402
from jepsen_tpu.checkerd.client import (  # noqa: E402
    CheckerdClient,
    RemoteChecker,
    fetch_stats,
)
from jepsen_tpu.history.core import History  # noqa: E402
from jepsen_tpu.models.registers import Register  # noqa: E402
from jepsen_tpu.parallel.independent import (  # noqa: E402
    KV,
    IndependentChecker,
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def history(prefix: str) -> History:
    """One good register key and one that reads a never-written value —
    a per-key valid/invalid mix so parity checks bite."""
    ops = []

    def add(process, f, key, value):
        i = len(ops)
        ops.append({"index": i, "type": "invoke", "process": process,
                    "f": f, "value": KV(key, None if f == "read" else value),
                    "time": i})
        ops.append({"index": i + 1, "type": "ok", "process": process,
                    "f": f, "value": KV(key, value), "time": i + 1})

    add(0, "write", f"{prefix}-good", 1)
    add(0, "read", f"{prefix}-good", 1)
    add(1, "write", f"{prefix}-bad", 1)
    add(1, "read", f"{prefix}-bad", 9)
    return History(ops)


class Failure(Exception):
    pass


def wait_listening(port: int, proc: subprocess.Popen, what: str,
                   deadline_s: float = 60.0) -> None:
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            if proc.poll() is not None:
                raise Failure(f"{what} exited early rc={proc.returncode}")
            if time.monotonic() > deadline:
                raise Failure(f"{what} never started listening")
            time.sleep(0.2)


def start_daemon(port: int, queue: str, batch_window: float) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.checkerd",
         "--host", "127.0.0.1", "--port", str(port),
         "--batch-window", str(batch_window), "--platform", "cpu",
         "--metrics-port", "-1", "--queue", queue],
    )


def stop(proc: subprocess.Popen) -> None:
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def expected_results(runs: dict) -> dict:
    return {
        name: IndependentChecker(Linearizable(Register())).check(
            {"name": name}, h, {})
        for name, h in runs.items()
    }


def assert_parity(name: str, got: dict, exp: dict) -> None:
    if got is None:
        raise Failure(f"{name}: no result")
    if "fallback" in (got.get("checkerd") or {}):
        raise Failure(f"{name}: fell back in-process: {got['checkerd']}")
    if got.get("valid") != exp.get("valid"):
        raise Failure(f"{name}: valid {got.get('valid')} != "
                      f"{exp.get('valid')}")
    for k, kr in exp["results"].items():
        if got["results"][k]["valid"] != kr["valid"]:
            raise Failure(f"{name}/{k}: {got['results'][k]['valid']} "
                          f"!= {kr['valid']}")


def phase_router_failover(tmp: str) -> str:
    """2 daemons + router; SIGKILL the placed daemon mid-window; both
    runs must still verdict correctly via failover."""
    ports = [free_port(), free_port()]
    addrs = [f"127.0.0.1:{p}" for p in ports]
    rport, mport = free_port(), free_port()
    raddr = f"127.0.0.1:{rport}"
    daemons = [
        start_daemon(ports[i], os.path.join(tmp, f"d{i}.queue"), 2.0)
        for i in range(2)
    ]
    router = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.checkerd.router",
         "--host", "127.0.0.1", "--port", str(rport),
         "--daemon", addrs[0], "--daemon", addrs[1],
         "--metrics-port", str(mport),
         "--queue", os.path.join(tmp, "router.queue")],
    )
    try:
        for i, d in enumerate(daemons):
            wait_listening(ports[i], d, f"daemon {i}")
        wait_listening(rport, router, "router")

        runs = {"fed-a": history("a"), "fed-b": history("b")}
        expected = expected_results(runs)
        results: dict = {}
        barrier = threading.Barrier(len(runs) + 1)

        def submit(name: str, h: History) -> None:
            rc = RemoteChecker(
                IndependentChecker(Linearizable(Register())),
                raddr, run_id=name, fallback=False)
            barrier.wait()
            results[name] = rc.check({"name": name}, h, {})

        threads = [threading.Thread(target=submit, args=(n, h))
                   for n, h in runs.items()]
        for t in threads:
            t.start()
        barrier.wait()
        # Let both submissions land in a daemon's batch window, then
        # SIGKILL the daemon the router placed them on.
        time.sleep(0.8)
        st = fetch_stats(raddr, timeout=5.0)
        placed = set((st.get("affinity") or {}).values())
        if not placed:
            raise Failure("router placed nothing (affinity empty)")
        victim_addr = placed.pop()
        victim = daemons[addrs.index(victim_addr)]
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=10)

        for t in threads:
            t.join(timeout=300)
        for name, exp in expected.items():
            assert_parity(name, results.get(name), exp)

        st = fetch_stats(raddr, timeout=5.0)
        if not st.get("failovers"):
            raise Failure(f"router failovers {st.get('failovers')} not > 0")
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/metrics", timeout=10,
        ).read().decode()
        for gauge in ("jepsen_router_daemons", "jepsen_router_failovers",
                      "jepsen_router_queue_depth"):
            if gauge not in body:
                raise Failure(f"/metrics scrape missing {gauge}")
        return (f"failover: {st['failovers']} failover(s), both runs "
                f"verdict-correct after SIGKILL of {victim_addr}")
    finally:
        stop(router)
        for d in daemons:
            stop(d)


def phase_restart_replay(tmp: str) -> str:
    """SIGKILL a daemon mid-cohort; restart with the same journal; the
    ORIGINAL ticket must produce the uninterrupted verdict."""
    port = free_port()
    addr = f"127.0.0.1:{port}"
    queue = os.path.join(tmp, "replay.queue")
    h = history("r")
    exp = IndependentChecker(Linearizable(Register())).check(
        {"name": "replay"}, h, {})

    from jepsen_tpu.parallel.independent import subhistories
    subs = subhistories(h)
    keys = list(subs)
    subs_ops = [[o.to_dict() for o in subs[k]] for k in keys]

    daemon = start_daemon(port, queue, 5.0)
    client = None
    try:
        wait_listening(port, daemon, "daemon")
        # Keep the submitting connection open across the kill: closing
        # it first would (correctly) abandon the ticket.
        client = CheckerdClient(addr)
        spec = {"type": "register", "value": None}
        ticket = client.submit_ops("replay", spec, subs_ops)
        # The ticket is journaled+fsynced before the TICKET reply, so
        # the kill can land any time from here on.
        time.sleep(0.3)
        os.kill(daemon.pid, signal.SIGKILL)
        daemon.wait(timeout=10)
        client.close()
        client = None

        daemon = start_daemon(port, queue, 0.05)
        wait_listening(port, daemon, "restarted daemon")
        with CheckerdClient(addr) as c:
            payload = c.wait(ticket, deadline_s=120)
        krs = payload.get("key-results") or []
        if len(krs) != len(keys):
            raise Failure(f"replayed ticket returned {len(krs)} keys "
                          f"for {len(keys)}")
        got = {k: r for k, r in zip(keys, krs)}
        for k, kr in exp["results"].items():
            if got[k]["valid"] != kr["valid"]:
                raise Failure(f"replay/{k}: {got[k]['valid']} != "
                              f"{kr['valid']}")
        # Replay idempotence: a second restart must serve the SAME
        # journaled bytes for the same ticket.
        stop(daemon)
        daemon = start_daemon(port, queue, 0.05)
        wait_listening(port, daemon, "re-restarted daemon")
        with CheckerdClient(addr) as c:
            again = c.wait(ticket, deadline_s=60)
        if json.dumps(again, sort_keys=True) != \
                json.dumps(payload, sort_keys=True):
            raise Failure("replayed result changed across restarts")
        return (f"replay: ticket {ticket} survived SIGKILL + restart, "
                f"{len(keys)} key verdicts match uninterrupted run, "
                f"byte-identical across a second restart")
    finally:
        if client is not None:
            client.close()
        stop(daemon)


def run() -> int:
    tmp = tempfile.mkdtemp(prefix="federation-smoke-")
    try:
        msg2 = phase_restart_replay(tmp)
        print(f"  {msg2}")
        msg1 = phase_router_failover(tmp)
        print(f"  {msg1}")
    except Failure as e:
        print(f"FAIL: {e}")
        return 1
    print("PASS: daemon crash-replay parity + router failover with "
          "zero lost verdicts")
    return 0


if __name__ == "__main__":
    sys.exit(run())
