#!/usr/bin/env python
"""CI smoke for `jepsen monitor` (tier1.yml step).

Phase 1 — durable observatory across SIGKILL: a real monitor
subprocess runs paced against a store dir until the time-series store
holds samples, then takes a SIGKILL mid-cadence and gets a garbage
torn tail appended on top.  Readers must stop cleanly at the tear, a
restarted monitor on the SAME store must truncate the garbage and
keep appending, and its embedded dashboard must serve the pre-kill
samples over /api/series plus a live SSE payload — one continuous
series across the crash.

Phase 2 — alert round trip + constant memory (the acceptance
criterion): an in-process run with --inject-slo fires a synthetic SLO
that must reach a file sink exactly once (deduped) with a forensics
dossier attached, then clear; every key's verdict stays proven; and
the resident-history gauge stays flat — rolling-window discards hold
resident rows under a ceiling a full-retention run would blow
through.

Exit 0 + "PASS" on success, exit 1 with a reason otherwise.  CPU-only:
the workflow runs it under JAX_PLATFORMS=cpu.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from jepsen_tpu.monitor import MonitorConfig, run_monitor  # noqa: E402
from jepsen_tpu.telemetry.timeseries import (  # noqa: E402
    read_disk_series,
    series_path,
)

SERIES = "monitor.resident-history-bytes"
TORN = b"\x00\x17GARBAGE-TORN-TAIL-NOT-A-BLOCK"


class Failure(Exception):
    pass


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def start_monitor(store: str, duration: float, port=None) -> subprocess.Popen:
    cmd = [sys.executable, "-m", "jepsen_tpu.suites.kvdb", "monitor",
           "--store-dir", store, "--rate", "400", "--duration",
           str(duration), "--keys", "3", "--procs-per-key", "2",
           "--cadence", "1"]
    if port is not None:
        cmd += ["--serve-port", str(port)]
    return subprocess.Popen(cmd)


def stop(proc: subprocess.Popen) -> None:
    if proc.poll() is None:
        proc.kill()
        proc.wait(timeout=10)


def wait_samples(store: str, proc: subprocess.Popen, n: int,
                 deadline_s: float = 90.0) -> list:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise Failure(f"monitor exited early rc={proc.returncode}")
        pts = read_disk_series(store, SERIES)
        if len(pts) >= n:
            return pts
        time.sleep(0.5)
    raise Failure(f"{SERIES} never reached {n} samples in the store")


def wait_listening(port: int, proc: subprocess.Popen,
                   deadline_s: float = 60.0) -> None:
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            if proc.poll() is not None:
                raise Failure(
                    f"restarted monitor exited early rc={proc.returncode}")
            if time.monotonic() > deadline:
                raise Failure("dashboard never started listening")
            time.sleep(0.2)


def fetch(url: str) -> bytes:
    return urllib.request.urlopen(url, timeout=15).read()


def read_sse_payload(url: str, deadline_s: float = 30.0) -> dict:
    """First `data:` payload off the stream — the monitor's 1 s cadence
    guarantees a fresh block well inside the deadline."""
    resp = urllib.request.urlopen(url, timeout=deadline_s)
    deadline = time.monotonic() + deadline_s
    try:
        while time.monotonic() < deadline:
            line = resp.readline()
            if line.startswith(b"data:"):
                return json.loads(line[5:].strip())
    finally:
        resp.close()
    raise Failure("SSE stream produced no data payload before deadline")


def phase_crash_durability(tmp: str) -> str:
    store = os.path.join(tmp, "store")
    proc = start_monitor(store, duration=120.0)
    try:
        pts = wait_samples(store, proc, n=3)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        stop(proc)
    t_kill = max(t for t, _ in pts)

    # A SIGKILL can land mid-write; make the torn tail certain.
    t0_file = series_path(store)
    with open(t0_file, "ab") as f:
        f.write(TORN)
    survivors = read_disk_series(store, SERIES)
    if len(survivors) < len(pts):
        raise Failure(f"reader lost samples at the tear: "
                      f"{len(survivors)} < {len(pts)}")

    port = free_port()
    proc = start_monitor(store, duration=25.0, port=port)
    try:
        wait_listening(port, proc)
        # The restarted writer must have truncated the garbage before
        # appending its first block.
        wait_samples(store, proc, n=len(pts) + 2)
        with open(t0_file, "rb") as f:
            if TORN in f.read():
                raise Failure("torn tail survived the restart")
        merged = read_disk_series(store, SERIES)
        before = [t for t, _ in merged if t <= t_kill]
        after = [t for t, _ in merged if t > t_kill]
        if len(before) < len(pts) or not after:
            raise Failure(f"series not continuous across restart: "
                          f"{len(before)} pre-kill + {len(after)} post")

        base = f"http://127.0.0.1:{port}"
        names = json.loads(fetch(f"{base}/api/series"))["names"]
        if SERIES not in names:
            raise Failure(f"/api/series names missing {SERIES}")
        served = json.loads(
            fetch(f"{base}/api/series?name={SERIES}"))["points"]
        if min(t for t, _ in served) > t_kill:
            raise Failure("dashboard lost the pre-kill history")
        page = fetch(f"{base}/monitor").decode()
        if "EventSource" not in page or SERIES not in page:
            raise Failure("/monitor page missing the live-series wiring")
        payload = read_sse_payload(f"{base}/api/series/stream")
        if not payload.get("s"):
            raise Failure(f"SSE payload carried no samples: {payload}")

        rc = proc.wait(timeout=90)
        if rc != 0:
            raise Failure(f"restarted monitor exited rc={rc}")
    finally:
        stop(proc)
    summary = json.load(open(os.path.join(store, "monitor-summary.json")))
    if summary["unknown_keys"] != 0:
        raise Failure(f"restarted run left unknown keys: {summary}")
    return (f"crash-durability: {len(before)} pre-kill + {len(after)} "
            f"post-restart samples in one series, torn tail truncated, "
            f"dashboard + SSE served both sides of the crash")


def phase_alert_and_memory(tmp: str) -> str:
    store = os.path.join(tmp, "inproc")
    alerts = os.path.join(tmp, "alerts.jsonl")
    cfg = MonitorConfig(
        store_dir=store, rate=20000.0, duration_s=6.0, keys=4,
        procs_per_key=4, cadence_s=0.3, advance_rows=2048,
        inject_slo_s=1.0, sinks=(f"file:{alerts}",),
    )
    summary = run_monitor(cfg)
    if summary["ok_keys"] != 4 or summary["unknown_keys"] != 0:
        raise Failure(f"verdicts not all proven: {summary['verdicts']}")
    status = summary["checker"]
    if status["discarded-rows"] <= 0:
        raise Failure("no rolling-window discards landed")

    events = [json.loads(ln) for ln in open(alerts) if ln.strip()]
    firing = [e for e in events
              if e.get("rule") == "monitor-injected"
              and e.get("rec") == "firing" and not e.get("renotify")]
    cleared = [e for e in events
               if e.get("rule") == "monitor-injected"
               and e.get("rec") == "cleared"]
    if len(firing) != 1:
        raise Failure(f"expected exactly 1 deduped firing, got "
                      f"{len(firing)}: {firing}")
    if len(cleared) != 1:
        raise Failure(f"expected exactly 1 cleared, got {len(cleared)}")
    dossier = firing[0].get("dossier")
    if not dossier or not os.path.exists(dossier):
        raise Failure(f"firing alert missing its dossier: {dossier!r}")
    if not firing[0].get("postmortem"):
        raise Failure("firing alert missing its flight postmortem")

    rows = [v for _, v in read_disk_series(store, "monitor.resident-rows")]
    if not rows:
        raise Failure("monitor.resident-rows series is empty")
    # ~50k+ rows flowed through; a full-retention run holds them all.
    if max(rows) >= 25000:
        raise Failure(f"resident-rows gauge not flat: peak {max(rows)}")
    return (f"alert+memory: 1 deduped firing (dossier attached) + 1 "
            f"cleared through the file sink, {summary['ops']} ops with "
            f"{status['discarded-rows']} rows discarded, resident peak "
            f"{max(rows)} rows")


def run() -> int:
    tmp = tempfile.mkdtemp(prefix="monitor-smoke-")
    try:
        msg2 = phase_alert_and_memory(tmp)
        print(f"  {msg2}")
        msg1 = phase_crash_durability(tmp)
        print(f"  {msg1}")
    except Failure as e:
        print(f"FAIL: {e}")
        return 1
    print("PASS: monitor store survives SIGKILL with a continuous "
          "served series, alerts round-trip with evidence, memory flat")
    return 0


if __name__ == "__main__":
    sys.exit(run())
