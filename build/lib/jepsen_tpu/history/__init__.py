"""History: ops, histories, pairing, and packed device tensors.

Replaces the reference's external `io.jepsen/history` dependency
(SURVEY.md §2.4) with a host-friendly Op/History view plus the packed
int32 columnar representation the TPU checkers consume.
"""

from .core import (
    FAIL,
    INFO,
    INVOKE,
    NEMESIS,
    NEMESIS_CODE,
    OK,
    TYPE_CODES,
    TYPE_NAMES,
    TYPES,
    History,
    Op,
    fail,
    history,
    info,
    invoke,
    ok,
    op,
    parse_literal,
)
from .fold import Fold, Task, loopf, task
from .fold import fold as run_fold  # `fold` stays the submodule name
from .packed import (
    NIL,
    NO_RET,
    ST_INFO,
    ST_OK,
    Interner,
    PackedOps,
    pack_history,
)

__all__ = [
    "FAIL",
    "Fold",
    "Task",
    "loopf",
    "run_fold",
    "task",
    "INFO",
    "INVOKE",
    "NEMESIS",
    "NEMESIS_CODE",
    "OK",
    "TYPE_CODES",
    "TYPE_NAMES",
    "TYPES",
    "History",
    "Op",
    "fail",
    "history",
    "info",
    "invoke",
    "ok",
    "op",
    "parse_literal",
    "NIL",
    "NO_RET",
    "ST_INFO",
    "ST_OK",
    "Interner",
    "PackedOps",
    "pack_history",
]
