"""Operations and histories.

Equivalent of the external `io.jepsen/history` library as consumed by the
reference (SURVEY.md §2.4): the `Op` record (fields index, time, type,
process, f, value — constructed at
/root/reference/jepsen/src/jepsen/generator.clj:529-536), history
construction with dense indices, invoke↔completion pairing, predicates
(invoke?/ok?/fail?/info?/client-op?), and filtered views.

Design notes (TPU-first): a History is an immutable sequence of Op rows
backed by plain Python objects for host-side ergonomics, with `pair_index`
computed once in O(n).  The device-facing columnar encoding lives in
`jepsen_tpu.history.packed` — this module is the friendly host view.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, Sequence

# Op types (the reference uses keywords :invoke :ok :fail :info).
INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

TYPES = (INVOKE, OK, FAIL, INFO)

#: Packed integer codes for op types (BASELINE.json packed tensor layout).
TYPE_CODES = {INVOKE: 0, OK: 1, FAIL: 2, INFO: 3}
TYPE_NAMES = {v: k for k, v in TYPE_CODES.items()}

#: The nemesis's logical process (the reference uses the keyword :nemesis,
#: generator/context.clj:258-286).
NEMESIS = "nemesis"

#: Packed process code for the nemesis.
NEMESIS_CODE = -1

#: Sentinel for Op.complete: keep the invocation's value.
_KEEP = object()


@dataclass(slots=True)
class Op:
    """One history event.

    Mirrors jepsen.history's Op record: `index` is the dense position in the
    history, `time` is nanoseconds since test start, `type` is one of
    invoke/ok/fail/info, `process` is an integer worker process or
    NEMESIS, `f` is the operation function (any hashable), `value` its
    payload.  Extra keys (e.g. :error) live in `ext`."""

    type: str
    f: Any = None
    value: Any = None
    process: Any = None
    time: int = -1
    index: int = -1
    ext: dict[str, Any] = field(default_factory=dict)

    # -- predicates (jepsen.history predicates; SURVEY.md §2.4) ------------

    @property
    def is_invoke(self) -> bool:
        return self.type == INVOKE

    @property
    def is_ok(self) -> bool:
        return self.type == OK

    @property
    def is_fail(self) -> bool:
        return self.type == FAIL

    @property
    def is_info(self) -> bool:
        return self.type == INFO

    @property
    def is_client_op(self) -> bool:
        """Client ops have integer processes; the nemesis doesn't."""
        return isinstance(self.process, int)

    @property
    def error(self) -> Any:
        return self.ext.get("error")

    def replace(self, *, type: Any = _KEEP, f: Any = _KEEP,
                value: Any = _KEEP, process: Any = _KEEP,
                time: Any = _KEEP, index: Any = _KEEP,
                ext: Any = _KEEP) -> "Op":
        # Hand-rolled dataclasses.replace: this sits on the interpreter
        # hot path (3 calls per executed op); named sentinel parameters
        # beat both the generic version's field introspection and a
        # **kw dict (7 dict lookups per call) in whole-stack profiles.
        # Unknown fields still raise TypeError via normal arg binding.
        return Op(
            type=self.type if type is _KEEP else type,
            f=self.f if f is _KEEP else f,
            value=self.value if value is _KEEP else value,
            process=self.process if process is _KEEP else process,
            time=self.time if time is _KEEP else time,
            index=self.index if index is _KEEP else index,
            ext=self.ext if ext is _KEEP else ext,
        )

    def complete(self, type: str, value: Any = _KEEP, **ext: Any) -> "Op":
        """The completion of this invocation: same process/f, new type,
        optionally a new value and extra keys (e.g. error=...); time and
        index are left for the interpreter to fill."""
        new_ext = dict(self.ext)
        new_ext.update(ext)
        return self.replace(
            type=type,
            value=self.value if value is _KEEP else value,
            time=-1,
            index=-1,
            ext=new_ext,
        )

    def with_ext(self, **kw: Any) -> "Op":
        ext = dict(self.ext)
        ext.update(kw)
        return self.replace(ext=ext)

    def to_dict(self) -> dict[str, Any]:
        d = {
            "index": self.index,
            "time": self.time,
            "type": self.type,
            "process": self.process,
            "f": self.f,
            "value": self.value,
        }
        d.update(self.ext)
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Op":
        ext = {
            k: v
            for k, v in d.items()
            if k not in ("index", "time", "type", "process", "f", "value")
        }
        return cls(
            type=d["type"],
            f=d.get("f"),
            value=d.get("value"),
            process=d.get("process"),
            time=d.get("time", -1),
            index=d.get("index", -1),
            ext=ext,
        )

    def __str__(self) -> str:
        return (
            f"{self.index}\t{self.process}\t{self.type}\t{self.f}\t{self.value!r}"
            + (f"\t{self.ext}" if self.ext else "")
        )


def op(type: str, f: Any = None, value: Any = None, process: Any = None, **ext: Any) -> Op:
    """Terse Op constructor for tests and literal histories."""
    return Op(type=type, f=f, value=value, process=process, ext=ext)


def invoke(f: Any = None, value: Any = None, process: Any = 0, **ext: Any) -> Op:
    return op(INVOKE, f, value, process, **ext)


def ok(f: Any = None, value: Any = None, process: Any = 0, **ext: Any) -> Op:
    return op(OK, f, value, process, **ext)


def fail(f: Any = None, value: Any = None, process: Any = 0, **ext: Any) -> Op:
    return op(FAIL, f, value, process, **ext)


def info(f: Any = None, value: Any = None, process: Any = 0, **ext: Any) -> Op:
    return op(INFO, f, value, process, **ext)


class History(Sequence[Op]):
    """An immutable, dense-indexed sequence of Ops with O(1)
    invoke↔completion pairing.

    Construction mirrors `(h/history ops {:dense-indices? true ...})` at
    generator/interpreter.clj:284-286: indices are (re)assigned densely
    unless the ops already carry dense indices, and missing times are filled
    from indices so literal test histories sort sensibly."""

    __slots__ = ("ops", "_pair_index", "_by_index")

    def __init__(self, ops: Iterable[Op | dict], *, reindex: bool | None = None):
        rows: list[Op] = [
            o if isinstance(o, Op) else Op.from_dict(o) for o in ops
        ]
        if reindex is None:
            reindex = not all(o.index == i for i, o in enumerate(rows))
        if reindex:
            rows = [
                o.replace(index=i, time=(o.time if o.time >= 0 else i))
                for i, o in enumerate(rows)
            ]
        self.ops: tuple[Op, ...] = tuple(rows)
        #: Op.index -> position in self.ops (they differ on filtered views,
        #: which preserve original indices).
        self._by_index: dict[int, int] = {
            o.index: pos for pos, o in enumerate(self.ops)
        }
        self._pair_index = self._compute_pairs()

    # -- pairing ----------------------------------------------------------

    def _compute_pairs(self) -> dict[int, int]:
        """Maps Op.index -> paired Op.index.

        An invocation pairs with the next op on the same process (its
        completion).  Client processes perform one op at a time; a client
        :info completion crashes the process, after which the interpreter
        assigns a fresh pid (interpreter.clj:245-249), so same-process
        pairing is unambiguous.  Nemesis invokes pair with the following
        nemesis completion.  A double invoke without completion is
        tolerated (earlier op stays unpaired), like jepsen.history."""
        pair: dict[int, int] = {}
        pending: dict[Any, int] = {}
        for o in self.ops:
            if o.is_invoke:
                pending[o.process] = o.index
            else:
                j = pending.pop(o.process, None)
                if j is not None:
                    pair[j] = o.index
                    pair[o.index] = j
        return pair

    def completion(self, o: Op | int) -> Op | None:
        """The completion op for an invocation (or None if it never
        completed).  Works on filtered views: lookups key on Op.index."""
        i = o if isinstance(o, int) else o.index
        j = self._pair_index.get(i, -1)
        if j > i and j in self._by_index:
            return self.ops[self._by_index[j]]
        return None

    def invocation(self, o: Op | int) -> Op | None:
        """The invocation op for a completion."""
        i = o if isinstance(o, int) else o.index
        j = self._pair_index.get(i, -1)
        if 0 <= j < i and j in self._by_index:
            return self.ops[self._by_index[j]]
        return None

    def pair_index(self, i: int) -> int:
        return self._pair_index.get(i, -1)

    def get_index(self, i: int) -> Op | None:
        """The op with Op.index == i, or None (O(1))."""
        pos = self._by_index.get(i)
        return self.ops[pos] if pos is not None else None

    # -- sequence protocol -------------------------------------------------

    def __len__(self) -> int:
        return len(self.ops)

    def __getitem__(self, i):  # type: ignore[override]
        if isinstance(i, slice):
            return list(self.ops[i])
        return self.ops[i]

    def __iter__(self) -> Iterator[Op]:
        return iter(self.ops)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, History):
            return self.ops == other.ops
        if isinstance(other, (list, tuple)):
            return list(self.ops) == list(other)
        return NotImplemented

    def __repr__(self) -> str:
        return f"History({len(self.ops)} ops)"

    # -- filtered views ----------------------------------------------------

    def filter(self, pred: Callable[[Op], bool]) -> "History":
        """A new history of ops matching pred.  Indices are preserved
        (like jepsen.history filtered views), so pairing against the
        original remains meaningful via .index."""
        return History([o for o in self.ops if pred(o)], reindex=False)

    def remove(self, pred: Callable[[Op], bool]) -> "History":
        return self.filter(lambda o: not pred(o))

    def map(self, f: Callable[[Op], Op]) -> "History":
        return History([f(o) for o in self.ops], reindex=False)

    def client_ops(self) -> "History":
        return self.filter(lambda o: o.is_client_op)

    def invokes(self) -> "History":
        return self.filter(lambda o: o.is_invoke)

    def oks(self) -> "History":
        return self.filter(lambda o: o.is_ok)

    def fails(self) -> "History":
        return self.filter(lambda o: o.is_fail)

    def infos(self) -> "History":
        return self.filter(lambda o: o.is_info)

    def nemesis_ops(self) -> "History":
        return self.filter(lambda o: o.process == NEMESIS)

    def has_f(self, fs) -> "History":
        if callable(fs):
            return self.filter(lambda o: fs(o.f))
        fset = {fs} if isinstance(fs, str) else set(fs)
        return self.filter(lambda o: o.f in fset)

    def possible(self) -> "History":
        """Ops that may have happened: everything except :fail completions
        and their invocations (knossos drops certainly-failed ops)."""
        failed_invokes = {
            self._pair_index[o.index]
            for o in self.ops
            if o.is_fail and o.index in self._pair_index
        }
        return self.filter(
            lambda o: not (o.is_fail or o.index in failed_invokes)
        )

    def fold(self, f: "Any", chunk_size: "int | None" = None) -> Any:
        """Runs a history.fold.Fold over this history (h/fold)."""
        # Import the submodule explicitly: the package re-exports the
        # `fold` FUNCTION, which shadows the module name.
        from .fold import fold as run_fold

        if chunk_size is None:
            return run_fold(self, f)
        return run_fold(self, f, chunk_size=chunk_size)

    def strip_indices(self) -> list[Op]:
        """Ops with indices removed (generator/test.clj:73)."""
        return [o.replace(index=-1) for o in self.ops]

    # -- convenience -------------------------------------------------------

    def to_dicts(self) -> list[dict[str, Any]]:
        return [o.to_dict() for o in self.ops]


def history(ops: Iterable[Op | dict], **kw: Any) -> History:
    return History(ops, **kw)


def parse_literal(rows: Iterable[tuple]) -> History:
    """Builds a history from terse (process, type, f, value) tuples — the
    shape checker tests use (checker_test.clj feeds literal op vectors)."""
    ops = []
    for row in rows:
        process, type_, f, value = row
        ops.append(Op(type=type_, f=f, value=value, process=process))
    return History(ops)
