"""Chunked parallel folds and async tasks over histories.

Equivalent of the `jepsen.history.fold` / `h/task` surface the
reference consumes (SURVEY.md §2.4: `h/fold`, `jepsen.history.fold/loopf`
at checker.clj:161-181, `h/task` async analysis helpers).  The
reference folds run chunk-concurrent over the on-disk BigVector; here
chunks fan out over a shared thread pool — worthwhile for reducers
that release the GIL (numpy/JAX batch steps) and for I/O-adjacent
work, and semantically identical for pure-Python reducers.

A Fold is reducer machinery in the tesser shape:

    Fold(identity=..., reducer=..., combiner=..., post=...)

`reducer(acc, op)` folds one op into a chunk accumulator (starting
from `identity()`); `combiner(a, b)` merges adjacent chunk results in
order; `post(acc)` finishes.  Without a combiner the fold runs
sequentially (order-dependent reductions stay correct).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional, Sequence

from ..utils import bounded_pmap
from .core import History, Op

#: Chunk granularity, matching the store's sealed-chunk size
#: (format.clj:372-375).
CHUNK_SIZE = 16384


@dataclass(frozen=True)
class Fold:
    identity: Callable[[], Any]
    reducer: Callable[[Any, Op], Any]
    combiner: Optional[Callable[[Any, Any], Any]] = None
    post: Callable[[Any], Any] = lambda acc: acc


def loopf(identity: Callable[[], Any],
          reducer: Callable[[Any, Op], Any],
          combiner: Optional[Callable[[Any, Any], Any]] = None,
          post: Callable[[Any], Any] = lambda acc: acc) -> Fold:
    """Terse Fold constructor (jepsen.history.fold/loopf shape)."""
    return Fold(identity, reducer, combiner, post)


def fold(ops: Sequence[Op] | History, f: Fold,
         chunk_size: int = CHUNK_SIZE) -> Any:
    """Runs a fold over a history.  With a combiner, chunks reduce
    concurrently and merge in order; without one, a single sequential
    pass."""
    rows: Sequence[Op] = ops.ops if isinstance(ops, History) else ops
    if f.combiner is None or len(rows) <= chunk_size:
        acc = f.identity()
        red = f.reducer
        for o in rows:
            acc = red(acc, o)
        return f.post(acc)

    def one_chunk(lo: int) -> Any:
        acc = f.identity()
        red = f.reducer
        for o in rows[lo : lo + chunk_size]:
            acc = red(acc, o)
        return acc

    # Per-call pool (utils.bounded_pmap): no shared executor to leak
    # or to deadlock on nested folds.
    chunks = bounded_pmap(one_chunk, range(0, len(rows), chunk_size))
    out = chunks[0]
    for c in chunks[1:]:
        out = f.combiner(out, c)
    return f.post(out)


class Task:
    """A named async computation over a history (h/task): `result()`
    joins.  Dependencies are other tasks whose results are passed to
    `fn` positionally once they resolve.

    One thread per task (not the fold pool): tasks are coarse analysis
    jobs, and blocking on deps inside a bounded pool would deadlock on
    chains deeper than the worker count."""

    def __init__(self, name: str, fn: Callable[..., Any],
                 deps: Iterable["Task"] = ()):
        self.name = name
        self._deps = tuple(deps)
        self._future: Future = Future()
        t = threading.Thread(
            target=self._run, args=(fn,),
            name=f"history-task-{name}", daemon=True,
        )
        t.start()

    def _run(self, fn: Callable[..., Any]) -> None:
        try:
            args = [d.result() for d in self._deps]
            self._future.set_result(fn(*args))
        except BaseException as e:  # noqa: BLE001 — delivered via result()
            self._future.set_exception(e)

    def done(self) -> bool:
        return self._future.done()

    def result(self, timeout: Optional[float] = None) -> Any:
        return self._future.result(timeout)

    def __repr__(self) -> str:
        state = "done" if self.done() else "running"
        return f"Task({self.name!r}, {state})"


def task(name: str, fn: Callable[..., Any],
         deps: Iterable[Task] = ()) -> Task:
    return Task(name, fn, deps)
