"""Control-node filesystem cache.

Equivalent of /root/reference/jepsen/src/jepsen/fs_cache.clj (:1-44):
expensive setup artifacts — compiled DB binaries, downloaded tarballs,
pre-joined cluster state — are cached on the *control* node between
runs, addressed by logical paths (tuples of strings/ints/keyword-ish
values).  Writers are atomic (temp file + rename); `locking(path)`
serializes concurrent builders; remote save/deploy move files between
nodes and the cache through the control plane's Session.

Python idioms replace the Clojure surface: JSON instead of EDN for the
data format, context-manager locking, plain strings for paths on disk.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import tempfile
import threading
from typing import Any, Iterator, Optional, Sequence

#: Default cache root on the control node (fs_cache.clj stores under
#: /tmp/jepsen/cache; ours lives with the store by default).
DEFAULT_ROOT = os.path.join("store", "cache")

_locks: dict[str, threading.Lock] = {}
_locks_guard = threading.Lock()


class Cache:
    def __init__(self, root: str = DEFAULT_ROOT):
        self.root = root

    # -- path encoding ----------------------------------------------------

    def _encode_part(self, part: Any) -> str:
        from .utils import sanitize_path_part

        return sanitize_path_part(part)

    def file_path(self, path: Sequence[Any]) -> str:
        """The file backing a logical path."""
        if not path:
            raise ValueError("cache path may not be empty")
        parts = [self._encode_part(p) for p in path]
        return os.path.join(self.root, *parts[:-1], parts[-1] + ".cache")

    # -- predicates -------------------------------------------------------

    def cached(self, path: Sequence[Any]) -> bool:
        return os.path.exists(self.file_path(path))

    def clear(self, path: Optional[Sequence[Any]] = None) -> None:
        if path is None:
            shutil.rmtree(self.root, ignore_errors=True)
        else:
            with contextlib.suppress(FileNotFoundError):
                os.remove(self.file_path(path))

    # -- locking ----------------------------------------------------------

    @contextlib.contextmanager
    def locking(self, path: Sequence[Any]) -> Iterator[None]:
        """Serializes builders of one cache path within this process."""
        key = self.file_path(path)
        with _locks_guard:
            lock = _locks.setdefault(key, threading.Lock())
        with lock:
            yield

    # -- atomic write plumbing --------------------------------------------

    @contextlib.contextmanager
    def _atomic(self, path: Sequence[Any]) -> Iterator[str]:
        dest = self.file_path(path)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(dest), prefix=".cache-tmp"
        )
        os.close(fd)
        try:
            yield tmp
            os.replace(tmp, dest)
        finally:
            with contextlib.suppress(FileNotFoundError):
                os.remove(tmp)

    # -- strings ----------------------------------------------------------

    def save_string(self, path: Sequence[Any], s: str) -> str:
        with self._atomic(path) as tmp:
            with open(tmp, "w") as f:
                f.write(s)
        return s

    def load_string(self, path: Sequence[Any]) -> Optional[str]:
        try:
            with open(self.file_path(path)) as f:
                return f.read()
        except FileNotFoundError:
            return None

    # -- data (JSON standing in for EDN) ----------------------------------

    def save_data(self, path: Sequence[Any], value: Any) -> Any:
        with self._atomic(path) as tmp:
            with open(tmp, "w") as f:
                json.dump(value, f)
        return value

    def load_data(self, path: Sequence[Any]) -> Any:
        s = self.load_string(path)
        return None if s is None else json.loads(s)

    # -- local files ------------------------------------------------------

    def save_file(self, src: str, path: Sequence[Any]) -> str:
        with self._atomic(path) as tmp:
            shutil.copyfile(src, tmp)
        return src

    def load_file(self, path: Sequence[Any]) -> Optional[str]:
        """The backing file's path, or None when uncached."""
        p = self.file_path(path)
        return p if os.path.exists(p) else None

    # -- remote files (fs_cache.clj save-remote!/deploy-remote!) ----------

    def save_remote(self, sess, remote_path: str,
                    path: Sequence[Any]) -> None:
        """Downloads a file from the session's node into the cache."""
        with self._atomic(path) as tmp:
            sess.download(remote_path, tmp)

    def deploy_remote(self, sess, path: Sequence[Any],
                      remote_path: str) -> bool:
        """Uploads a cached file to the session's node; False when the
        path is uncached."""
        local = self.load_file(path)
        if local is None:
            return False
        sess.upload(local, remote_path)
        return True


#: Module-level default instance, like the reference's implicit cache.
cache = Cache()

cached = cache.cached
clear = cache.clear
locking = cache.locking
save_string = cache.save_string
load_string = cache.load_string
save_data = cache.save_data
load_data = cache.load_data
save_file = cache.save_file
load_file = cache.load_file
save_remote = cache.save_remote
deploy_remote = cache.deploy_remote
