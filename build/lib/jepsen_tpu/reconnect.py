"""Auto-reconnecting connection wrappers for DB clients.

Equivalent of /root/reference/jepsen/src/jepsen/reconnect.clj: a
`Wrapper` owns a connection created by `open` and torn down by
`close`; `with_conn` hands the live connection to a body and, when the
body raises, closes and reopens it so the next caller gets a fresh
one.  Open/close/reconnect serialize under the wrapper's write lock
while concurrent bodies share a read lock (reconnect.clj:17-60).

    wrapper = Wrapper(open=lambda: connect(node), close=Conn.close)
    with wrapper.conn() as c:
        c.query(...)
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any, Callable, Iterator, Optional

log = logging.getLogger(__name__)


class _RWLock:
    """Writer-preference read/write lock (ReentrantReadWriteLock's
    role in reconnect.clj:33-49)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    @contextlib.contextmanager
    def read(self) -> Iterator[None]:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                if not self._readers:
                    self._cond.notify_all()

    @contextlib.contextmanager
    def write(self) -> Iterator[None]:
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


class Wrapper:
    """reconnect.clj:17-32."""

    def __init__(
        self,
        *,
        open: Callable[[], Any],
        close: Callable[[Any], None],
        name: Optional[str] = None,
        log_reconnects: bool = True,
    ):
        self._open = open
        self._close = close
        self.name = name
        self.log_reconnects = log_reconnects
        self._lock = _RWLock()
        self._conn: Any = None

    # -- lifecycle (reconnect.clj:53-92) ----------------------------------

    def open(self) -> "Wrapper":
        # Fast path without the write lock: conn() calls open() on
        # every use, and a writer-preference write acquisition would
        # stall behind (and deadlock with) threads already holding the
        # read lock in their bodies.
        with self._lock.read():
            if self._conn is not None:
                return self
        with self._lock.write():
            if self._conn is None:
                self._conn = self._open()
        return self

    def close(self) -> None:
        with self._lock.write():
            if self._conn is not None:
                try:
                    self._close(self._conn)
                finally:
                    self._conn = None

    def reopen(self) -> "Wrapper":
        """Close (best-effort) and open a fresh connection."""
        with self._lock.write():
            if self._conn is not None:
                try:
                    self._close(self._conn)
                except Exception:  # noqa: BLE001 — old conn may be dead
                    pass
                self._conn = None
            self._conn = self._open()
        return self

    # -- use (reconnect.clj:94-151 with-conn) -----------------------------

    @contextlib.contextmanager
    def conn(self) -> Iterator[Any]:
        """Yields the live connection (opening lazily); the read lock
        is held across the body, so a reopen triggered by one thread's
        failure waits for concurrent healthy bodies to finish instead
        of closing the connection under them (reconnect.clj:94-151).
        On a body exception the connection is reopened (after the read
        lock is released — the lock is not reentrant), then the error
        re-raises."""
        self.open()
        reopen_needed = False
        try:
            with self._lock.read():
                c = self._conn
                try:
                    yield c
                except Exception:
                    reopen_needed = True
                    raise
        finally:
            if reopen_needed:
                if self.log_reconnects:
                    log.info(
                        "reconnecting %s after error",
                        self.name or "conn", exc_info=True,
                    )
                try:
                    self.reopen()
                except Exception:  # noqa: BLE001 — reopen may fail too
                    log.warning(
                        "reopen of %s failed", self.name or "conn"
                    )
