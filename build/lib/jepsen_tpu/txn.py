"""Transaction micro-op helpers.

Equivalent of /root/reference/txn/src/jepsen/txn.clj (:6-79): a
transaction is a list of micro-ops ("mops"), each a [f, k, v] triple —
f is "r"/"w"/"append", k a key, v a value (for reads, the observed
value; None in invocations).  `reduce_mops`, external reads/writes.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Sequence

Mop = Sequence  # [f, k, v]


def reduce_mops(f: Callable, init: Any, txn: Iterable[Mop]) -> Any:
    """Folds f(acc, [fk, k, v]) over every mop (txn.clj:6-20)."""
    acc = init
    for mop in txn:
        acc = f(acc, mop)
    return acc


def ext_reads(txn: Iterable[Mop]) -> dict:
    """{k: value} for reads of keys not previously written in this txn
    — reads visible to the outside world (txn.clj:22-45)."""
    out: dict = {}
    written: set = set()
    for fk, k, v in txn:
        if fk == "r":
            if k not in written and k not in out:
                out[k] = v
        else:
            written.add(k)
    return out


def ext_writes(txn: Iterable[Mop]) -> dict:
    """{k: value} of the *last* write to each key — writes visible
    externally (txn.clj:47-79).  For appends the 'value' is the last
    appended element."""
    out: dict = {}
    for fk, k, v in txn:
        if fk != "r":
            out[k] = v
    return out


def int_reads(txn: Iterable[Mop]) -> list:
    """All read mops, internal or external."""
    return [m for m in txn if m[0] == "r"]


def writes(txn: Iterable[Mop]) -> list:
    return [m for m in txn if m[0] != "r"]
