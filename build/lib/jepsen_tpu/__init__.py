"""jepsen-tpu: a TPU-native distributed-systems correctness testing framework.

A ground-up rebuild of Jepsen's capabilities (reference:
/root/reference/jepsen, SURVEY.md) designed TPU-first: the control plane —
remotes, generators, nemeses, orchestration — is host Python; histories are
packed int32 op tensors; and the expensive analysis (Wing–Gong
linearizability search, transactional cycle detection, per-key independent
checking) runs on TPU via JAX with mesh sharding.
"""

__version__ = "0.1.0"
