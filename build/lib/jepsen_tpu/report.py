"""Report redirection: run a block with stdout bound to a file.

Equivalent of /root/reference/jepsen/src/jepsen/report.clj's `to`
macro, as a context manager:

    with report.to(path):
        print("everything printed here lands in the file")
"""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Iterator


@contextlib.contextmanager
def to(filename: str) -> Iterator[None]:
    os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
    with open(filename, "w") as w:
        old = sys.stdout
        sys.stdout = w
        try:
            yield
        finally:
            sys.stdout = old
            print(f"Report written to {filename}")
