/* Adjust CLOCK_REALTIME by a signed millisecond delta: `bump-time 500`
 * jumps the wall clock half a second forward, `bump-time -500` back.
 * The delta MUST be argv[1]: there is no option parsing, and a "--"
 * separator would be atoll'd to 0 — a silent no-op bump.
 * Compiled on the DB node by the clock nemesis, the same strategy the
 * reference uses (jepsen/src/jepsen/nemesis/time.clj:21-40 compiles
 * resources/bump-time.c with gcc at setup time).  Fresh implementation.
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
    return 2;
  }
  long long delta_ms = atoll(argv[1]);
  struct timespec ts;
  if (clock_gettime(CLOCK_REALTIME, &ts) != 0) {
    perror("clock_gettime");
    return 1;
  }
  long long ns = ts.tv_nsec + (delta_ms % 1000) * 1000000LL;
  ts.tv_sec += delta_ms / 1000 + ns / 1000000000LL;
  ts.tv_nsec = ns % 1000000000LL;
  if (ts.tv_nsec < 0) {
    ts.tv_nsec += 1000000000L;
    ts.tv_sec -= 1;
  }
  if (clock_settime(CLOCK_REALTIME, &ts) != 0) {
    perror("clock_settime");
    return 1;
  }
  return 0;
}
