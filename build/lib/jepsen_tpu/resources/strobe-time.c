/* Strobe CLOCK_REALTIME: flip the wall clock between now and now+delta
 * every `period` ms for `duration` ms total:
 *     strobe-time <delta-ms> <period-ms> <duration-ms>
 * Equivalent role to the reference's resources/strobe-time.c (compiled
 * on-node by nemesis/time.clj); fresh implementation.
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <unistd.h>

static void shift_ms(long long delta_ms) {
  struct timespec ts;
  if (clock_gettime(CLOCK_REALTIME, &ts) != 0) return;
  long long ns = ts.tv_nsec + (delta_ms % 1000) * 1000000LL;
  ts.tv_sec += delta_ms / 1000 + ns / 1000000000LL;
  ts.tv_nsec = ns % 1000000000LL;
  if (ts.tv_nsec < 0) {
    ts.tv_nsec += 1000000000L;
    ts.tv_sec -= 1;
  }
  clock_settime(CLOCK_REALTIME, &ts);
}

int main(int argc, char **argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s <delta-ms> <period-ms> <duration-ms>\n",
            argv[0]);
    return 2;
  }
  long long delta = atoll(argv[1]);
  long long period = atoll(argv[2]);
  long long duration = atoll(argv[3]);
  if (period <= 0) period = 1;
  long long elapsed = 0;
  int forward = 1;
  while (elapsed < duration) {
    shift_ms(forward ? delta : -delta);
    forward = !forward;
    usleep((useconds_t)(period * 1000));
    elapsed += period;
  }
  if (!forward) shift_ms(-delta); /* leave the clock where we found it */
  return 0;
}
