"""Dependency graphs and cycle search for transactional anomaly checking.

Host-side core of the Elle-equivalent (SURVEY.md §2.4: the external
`elle` 0.1.8 library consumed at tests/cycle/{append,wr}.clj — NOT
vendored in the reference; reimplemented here from the anomaly
definitions in Adya's thesis and the Elle paper).

A DepGraph has integer vertices (transaction indices into the history)
and typed directed edges: "ww" (write-write), "wr" (write-read), "rw"
(read-write anti-dependency), "realtime", "process".  Cycle search:
Tarjan SCC, then a shortest cycle inside each nontrivial SCC (BFS),
classified by the edge types it contains:

    G0        cycle of ww edges only
    G1c       cycle of ww/wr edges (at least one wr)
    G2-item   cycle containing an rw edge (exactly one -> G-single)

The batched device screen for many per-key graphs lives in
jepsen_tpu.ops.scc (check_cycles_device): an MXU transitive-closure
kernel settles acyclic graphs, and this module's exact search extracts
and classifies cycles for the flagged ones.
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Any, Iterable, Optional

EDGE_TYPES = ("ww", "wr", "rw", "realtime", "process")


class DepGraph:
    def __init__(self) -> None:
        #: {src: {dst: set(edge-types)}}
        self.adj: dict[int, dict[int, set]] = defaultdict(dict)
        self.vertices: set[int] = set()

    def add_vertex(self, v: int) -> None:
        self.vertices.add(v)

    def add_edge(self, src: int, dst: int, etype: str) -> None:
        if src == dst:
            return  # self-edges are internal anomalies, handled separately
        self.vertices.add(src)
        self.vertices.add(dst)
        self.adj[src].setdefault(dst, set()).add(etype)

    def edge_types(self, src: int, dst: int) -> set:
        return self.adj.get(src, {}).get(dst, set())

    def out_edges(self, v: int) -> Iterable[int]:
        return self.adj.get(v, {}).keys()

    def n_edges(self) -> int:
        return sum(len(d) for d in self.adj.values())

    def restricted(self, etypes: Iterable[str]) -> "DepGraph":
        """Subgraph keeping only edges of the given types."""
        keep = set(etypes)
        g = DepGraph()
        g.vertices |= self.vertices
        for src, dsts in self.adj.items():
            for dst, types in dsts.items():
                inter = types & keep
                for t in inter:
                    g.add_edge(src, dst, t)
        return g

    # -- SCC (Tarjan, iterative) ----------------------------------------

    def sccs(self) -> list[list[int]]:
        """Strongly-connected components, nontrivial ones only (size > 1;
        self-loops are excluded by construction)."""
        index_of: dict[int, int] = {}
        low: dict[int, int] = {}
        on_stack: set[int] = set()
        stack: list[int] = []
        out: list[list[int]] = []
        counter = [0]

        for root in self.vertices:
            if root in index_of:
                continue
            # Iterative Tarjan: (vertex, iterator over successors).
            work = [(root, iter(self.out_edges(root)))]
            index_of[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index_of:
                        index_of[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(self.out_edges(w))))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[v] = min(low[v], index_of[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index_of[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == v:
                            break
                    if len(comp) > 1:
                        out.append(comp)
        return out

    # -- cycle recovery --------------------------------------------------

    def find_cycle_in(self, component: Iterable[int]) -> Optional[list[int]]:
        """A shortest cycle within a component: BFS from each vertex back
        to itself, restricted to the component."""
        comp = set(component)
        best: Optional[list[int]] = None
        for start in comp:
            # BFS over comp edges from start; stop when we return.
            parent: dict[int, int] = {}
            q = deque([start])
            seen = {start}
            found = None
            while q and found is None:
                v = q.popleft()
                for w in self.out_edges(v):
                    if w == start:
                        found = v
                        break
                    if w in comp and w not in seen:
                        seen.add(w)
                        parent[w] = v
                        q.append(w)
            if found is not None:
                path = [found]
                while path[-1] != start:
                    path.append(parent[path[-1]])
                path.reverse()
                cycle = path + [start]  # start ... found, start
                if best is None or len(cycle) < len(best):
                    best = cycle
        return best

    def cycle_edge_types(self, cycle: list[int]) -> set:
        types: set = set()
        for a, b in zip(cycle, cycle[1:]):
            types |= self.edge_types(a, b)
        return types


def classify_cycle(graph: DepGraph, cycle: list[int]) -> str:
    """Adya-style classification by participating dependency types:
    G-single = exactly one anti-dependency edge, G2-item = several."""
    rw_edges = 0
    types: set = set()
    for a, b in zip(cycle, cycle[1:]):
        ts = graph.edge_types(a, b)
        types |= ts
        # Any edge carrying an anti-dependency counts: a cycle whose
        # single rw edge also happens to be ww/wr is still G-single
        # (Elle's minimal-explanation rule).
        if "rw" in ts:
            rw_edges += 1
    data = types & {"ww", "wr", "rw"}
    if "rw" in data:
        return "G-single" if rw_edges == 1 else "G2-item"
    if "wr" in data:
        return "G1c"
    if data == {"ww"}:
        return "G0"
    return "cycle"  # realtime/process-only: should not happen alone


def cycle_explanation(graph: DepGraph, cycle: list[int]) -> list[dict]:
    """[{from, to, types}] steps for reporting."""
    return [
        {"from": a, "to": b, "types": sorted(graph.edge_types(a, b))}
        for a, b in zip(cycle, cycle[1:])
    ]


def _cycle_record(graph: DepGraph, cycle: list[int], comp: Iterable[int],
                  forced_type: Optional[str] = None) -> dict:
    return {
        "type": forced_type or classify_cycle(graph, cycle),
        "cycle": cycle,
        "steps": cycle_explanation(graph, cycle),
        "scc-size": len(list(comp)),
    }


def find_cycle_with_edge(
    graph: DepGraph, src: int, dst: int, component: Iterable[int]
) -> Optional[list[int]]:
    """A cycle through the specific edge src->dst: shortest path
    dst ~> src inside the component, closed with the edge."""
    comp = set(component)
    if dst == src:
        return None
    parent: dict[int, int] = {}
    q = deque([dst])
    seen = {dst}
    while q:
        v = q.popleft()
        for w in graph.out_edges(v):
            if w not in comp or w in seen:
                continue
            parent[w] = v
            if w == src:
                path = [src]
                while path[-1] != dst:
                    path.append(parent[path[-1]])
                path.reverse()  # dst ... src
                return [src] + path  # src -> dst -> ... -> src
            seen.add(w)
            q.append(w)
    return None


def check_cycles(graph: DepGraph) -> list[dict]:
    """Anomaly cycles found the way elle finds them: layered searches
    over restricted subgraphs, so a strong-anomaly cycle can't mask a
    weaker one (G0 is searched in the ww-only subgraph, G1c in ww+wr,
    G-single/G2-item in the full graph through an rw edge).  One
    representative cycle per SCC per layer."""
    out = []

    # Layer 1: G0 — pure write cycles.
    g0 = graph.restricted(["ww", "realtime", "process"])
    for comp in g0.sccs():
        cycle = g0.find_cycle_in(comp)
        if cycle is not None:
            out.append(_cycle_record(g0, cycle, comp, "G0"))

    # Layer 2: G1c — cycles of ww+wr containing at least one wr.
    g1 = graph.restricted(["ww", "wr", "realtime", "process"])
    for comp in g1.sccs():
        comp_set = set(comp)
        found = None
        for src in comp_set:
            for dst, types in g1.adj.get(src, {}).items():
                if dst in comp_set and "wr" in types:
                    found = find_cycle_with_edge(g1, src, dst, comp_set)
                    if found is not None:
                        break
            if found is not None:
                break
        if found is not None:
            out.append(_cycle_record(g1, found, comp, "G1c"))

    # Layer 3: G-single / G2-item — cycles through an rw edge in the
    # full graph.
    full_comps = graph.sccs()
    for comp in full_comps:
        comp_set = set(comp)
        found = None
        for src in comp_set:
            for dst, types in graph.adj.get(src, {}).items():
                if dst in comp_set and "rw" in types:
                    found = find_cycle_with_edge(graph, src, dst, comp_set)
                    if found is not None:
                        break
            if found is not None:
                break
        if found is not None:
            out.append(_cycle_record(graph, found, comp))

    # Layer 4: leftovers — an SCC that none of the typed layers could
    # explain is still a cycle (e.g. custom edge types from a
    # user-supplied analyzer, workloads/cycle.py); report it rather
    # than silently passing it as valid, like elle.core/check.
    covered = [set(r["cycle"]) for r in out]
    for comp in full_comps:
        comp_set = set(comp)
        if any(c <= comp_set for c in covered):
            continue
        cycle = graph.find_cycle_in(comp)
        if cycle is not None:
            out.append(_cycle_record(graph, cycle, comp))
    return out
