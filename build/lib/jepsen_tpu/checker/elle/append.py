"""List-append transactional anomaly checking.

Equivalent of elle.list-append as consumed by the reference at
/root/reference/jepsen/src/jepsen/tests/cycle/append.clj:6-27 (the elle
library itself is not vendored; reimplemented from the Elle paper's
list-append inference rules).

Transactions are ops with f="txn" and value = list of micro-ops:
["append", k, v] appends v to the list at key k; ["r", k, vs] observes
the full list vs.  Because appends are unique per key and reads expose
the whole list, the version history of each key is directly recoverable:

  * the version written by an append a = the observed list ending in a;
  * reads of k must be prefix-compatible ("incompatible-order" if not);
  * ww edges chain consecutive elements of the longest observed list;
  * wr edges run from the writer of a read's last element to the reader;
  * rw anti-dependencies run from the reader of a prefix to the writer
    of the next element.

Anomalies reported: G1a (aborted read), G1b (intermediate read),
"dirty-update", internal (txn sees its own writes wrong), duplicates,
incompatible-order, lost-update-ish garbage reads, and the cycle
anomalies G0/G1c/G-single/G2-item from graph.check_cycles.
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Any, Iterable, Optional, Sequence

from ...history.core import History, Op
from .graph import DepGraph, check_cycles

#: Cycle anomaly types forbidden per consistency model.
FORBIDDEN = {
    "read-uncommitted": {"G0"},
    "read-committed": {"G0", "G1c"},
    "repeatable-read": {"G0", "G1c"},
    "serializable": {"G0", "G1c", "G-single", "G2-item"},
    # The stronger models forbid the same Adya classes; their extra
    # power comes from the additional EDGES woven into the graph
    # (realtime order for strict-*, per-process session order for
    # strong-session-*), which create cycles the weaker graphs don't
    # have.  A ww+realtime cycle still classifies G0, as in Elle's
    # "-realtime" variants collapsing to the same forbidden class.
    "strict-serializable": {"G0", "G1c", "G-single", "G2-item"},
    "strong-session-serializable": {"G0", "G1c", "G-single",
                                    "G2-item"},
}

#: Models that weave extra edge sources into the dependency graph.
#: (Realtime subsumes session order — a jepsen process completes each
#: op before invoking the next — so strict-* needs no process edges.)
REALTIME_MODELS = {"strict-serializable"}
SESSION_MODELS = {"strong-session-serializable"}

#: Non-cycle anomalies forbidden from read-committed up.
DIRTY = {"G1a", "G1b", "dirty-update"}


def _txn_ok_ops(history: History) -> list[Op]:
    return [o for o in history if o.is_ok and o.f in ("txn", None)]


def analyze(
    history: History,
    *,
    consistency_model: str = "serializable",
    cycle_fn=None,
) -> dict:
    """Full list-append analysis -> {"valid": ..., "anomaly-types": [...],
    "anomalies": {...}}."""
    oks = _txn_ok_ops(history)
    infos = [o for o in history if o.is_info and o.f in ("txn", None)]
    fails = [o for o in history if o.is_fail and o.f in ("txn", None)]

    anomalies: dict[str, list] = defaultdict(list)

    # -- index writes ---------------------------------------------------
    # writer[(k, v)] = op index that appended v to k (committed and
    # indeterminate appends both count: an info append may well have
    # taken effect).
    writer: dict[tuple, int] = {}
    # Appends from known-failed txns.
    failed_appends: set[tuple] = set()
    # (k, v) -> True when v is NOT the final append to k in its txn.
    intermediate: set[tuple] = set()

    def note_appends(op: Op, target: Optional[dict] = None, fate: Optional[set] = None):
        last_per_key: dict = {}
        for mop in op.value or []:
            f, k, v = mop
            if f == "append":
                kv = (k, v)
                if target is not None:
                    if kv in writer:
                        anomalies["duplicate-appends"].append(
                            {"key": k, "value": v, "ops": [writer[kv], op.index]}
                        )
                    else:
                        target[kv] = op.index
                if fate is not None:
                    fate.add(kv)
                if k in last_per_key:
                    intermediate.add(last_per_key[k])
                last_per_key[k] = kv

    for op in oks:
        note_appends(op, target=writer)
    for op in infos:
        note_appends(op, target=writer)
    for op in fails:
        note_appends(op, fate=failed_appends)

    # -- per-key version order from reads -------------------------------
    # Longest observed list per key + prefix compatibility of all reads.
    longest: dict[Any, list] = {}
    for op in oks:
        for mop in op.value or []:
            f, k, vs = mop
            if f != "r" or vs is None:
                continue
            vs = list(vs)
            if len(set(vs)) != len(vs):
                anomalies["duplicate-elements"].append(
                    {"op": op.index, "key": k, "value": vs}
                )
            cur = longest.get(k, [])
            shorter, larger = (vs, cur) if len(vs) <= len(cur) else (cur, vs)
            if larger[: len(shorter)] != shorter:
                anomalies["incompatible-order"].append(
                    {"key": k, "values": [shorter, larger]}
                )
            if len(vs) > len(cur):
                longest[k] = vs

    # -- read-level anomalies -------------------------------------------
    for op in oks:
        # Internal: a read after an append in the same txn must end with
        # this txn's own appends, in order.
        my_appends: dict[Any, list] = defaultdict(list)
        for mop in op.value or []:
            f, k, v = mop
            if f == "append":
                my_appends[k].append(v)
            elif f == "r" and v is not None:
                vs = list(v)
                mine = my_appends.get(k, [])
                if mine and vs[-len(mine):] != mine:
                    anomalies["internal"].append(
                        {"op": op.index, "key": k, "expected-suffix": mine,
                         "observed": vs}
                    )
                # A read observes the version named by its LAST element;
                # ending at a non-final append from ANOTHER txn =
                # intermediate state (G1b).  Intermediate elements deeper
                # in the list are normal, and a txn reading its own
                # in-progress state is legal.
                if (
                    vs
                    and (k, vs[-1]) in intermediate
                    and writer.get((k, vs[-1])) != op.index
                ):
                    anomalies["G1b"].append(
                        {"op": op.index, "key": k, "value": vs[-1]}
                    )
                for el in vs:
                    kv = (k, el)
                    if kv in failed_appends:
                        anomalies["G1a"].append(
                            {"op": op.index, "key": k, "value": el}
                        )
                    if (
                        kv not in writer
                        and kv not in failed_appends
                        and el not in mine
                    ):
                        anomalies["unobserved-writer"].append(
                            {"op": op.index, "key": k, "value": el}
                        )

    # Dirty update: a committed append whose predecessor in the version
    # order is a failed append.
    for k, vs in longest.items():
        for el in vs:
            if (k, el) in failed_appends:
                anomalies["dirty-update"].append({"key": k, "value": el})

    # -- dependency graph -----------------------------------------------
    g = DepGraph()
    for op in oks:
        g.add_vertex(op.index)

    def w(kv: tuple) -> Optional[int]:
        return writer.get(kv)

    for k, order in longest.items():
        # ww chain along the version order.
        for a, b in zip(order, order[1:]):
            wa, wb = w((k, a)), w((k, b))
            if wa is not None and wb is not None and wa != wb:
                g.add_edge(wa, wb, "ww")

    for op in oks:
        for mop in op.value or []:
            f, k, vs = mop
            if f != "r" or vs is None:
                continue
            vs = list(vs)
            order = longest.get(k, [])
            if vs:
                last_writer = w((k, vs[-1]))
                if last_writer is not None and last_writer != op.index:
                    g.add_edge(last_writer, op.index, "wr")
            # rw: this read observed version len(vs); the next version's
            # writer overwrote it.
            if len(vs) < len(order):
                nxt = w((k, order[len(vs)]))
                if nxt is not None and nxt != op.index:
                    g.add_edge(op.index, nxt, "rw")

    if consistency_model in REALTIME_MODELS:
        _add_realtime_edges(history, g)
    if consistency_model in SESSION_MODELS:
        _add_process_edges(history, g)

    cycles = (cycle_fn or check_cycles)(g)
    for c in cycles:
        anomalies[c["type"]].append(c)

    # -- verdict ---------------------------------------------------------
    forbidden = set(FORBIDDEN.get(consistency_model, FORBIDDEN["serializable"]))
    forbidden |= {"incompatible-order", "duplicate-elements",
                  "duplicate-appends", "internal"}
    if consistency_model != "read-uncommitted":
        # Reads of elements nobody wrote are data corruption, same as
        # wr.py's unwritten-read.
        forbidden |= DIRTY | {"unobserved-writer"}
    found = {t for t in anomalies if anomalies[t]}
    bad = found & forbidden
    valid: Any = True
    if bad:
        valid = False
    elif found:
        valid = "unknown"  # anomalies present but not forbidden by model
    return {
        "valid": valid,
        "anomaly-types": sorted(found),
        "anomalies": {t: v for t, v in anomalies.items() if v},
        "edges": g.n_edges(),
    }


def _add_realtime_edges(history: History, g: DepGraph) -> None:
    """A -> B when A's completion precedes B's invocation (strict
    serializability's realtime order), transitively reduced.

    Reduction: with S = {A : comp(A) < inv(B)} and M = max inv(C) over
    C in S, any A in S with comp(A) < M is covered transitively
    (comp(A) < inv(C) for the maximizing C, so A -> C -> B), so only
    A with comp(A) >= M need direct edges.  The surviving set is
    bounded by the concurrency, keeping this near-linear.  History
    indices are the time order."""
    inv_of = getattr(history, "invocation", None)
    if not callable(inv_of):
        raise ValueError(
            "realtime edges need a paired History (with .invocation), "
            "not a bare op list — completion order alone cannot "
            "recover realtime intervals"
        )
    pairs = []  # (inv_index, comp_index, op.index) for committed txns
    for o in history:
        if o.is_ok and o.f in ("txn", None):
            inv = inv_of(o)
            if inv is not None:
                pairs.append((inv.index, o.index, o.index))
    pairs.sort()
    # Sweep in invocation order.  `done` holds (comp, inv, op) of
    # completed txns sorted by comp.  Since inv(B) is nondecreasing, S
    # only grows, so any entry with comp < M (the running max-inv over
    # everything that has entered S) is covered transitively for every
    # future B too — prune it once, keeping the sweep near-linear.
    import bisect

    done: list[tuple[int, int, int]] = []  # sorted by comp
    m = -1  # running max inv over pruned-or-current S
    for inv_idx, comp_idx, op_idx in pairs:
        cut = bisect.bisect_left(done, (inv_idx, -1, -1))
        if cut:
            m = max(m, max(e[1] for e in done[:cut]))
            survivors = [e for e in done[:cut] if e[0] >= m]
            for comp, inv2, pred in survivors:
                if pred != op_idx:
                    g.add_edge(pred, op_idx, "realtime")
            # Entries below the max-inv bar are done forever.
            done = survivors + done[cut:]
        bisect.insort(done, (comp_idx, inv_idx, op_idx))


def _add_process_edges(history: History, g: DepGraph) -> None:
    """A -> B when B is the next committed txn of A's process (session
    order; Elle's process graph for the strong-session-* models).
    Consecutive pairs only — session order is total per process, so
    the chain is its own transitive reduction."""
    last_by_process: dict = {}
    for o in history:
        if o.is_ok and o.f in ("txn", None) and o.process is not None:
            # process=None (bare literal ops) carries no session
            # identity; chaining those would invent one shared
            # session and falsely convict valid histories.
            prev = last_by_process.get(o.process)
            if prev is not None and prev != o.index:
                g.add_edge(prev, o.index, "process")
            last_by_process[o.process] = o.index


# ---------------------------------------------------------------------------
# Generator (elle.list-append/gen as used by append.clj:11-27)
# ---------------------------------------------------------------------------


class AppendGen:
    """Generates random list-append transactions: each txn is 1..max_len
    mops over a sliding window of active keys; append values are unique
    and monotonically increasing per key."""

    def __init__(
        self,
        *,
        key_count: int = 10,
        min_txn_length: int = 1,
        max_txn_length: int = 4,
        max_writes_per_key: int = 32,
        rng: Optional[random.Random] = None,
    ):
        self.key_count = key_count
        self.min_len = min_txn_length
        self.max_len = max_txn_length
        self.max_writes = max_writes_per_key
        self.rng = rng or random.Random()
        self.next_value: dict[int, int] = defaultdict(int)
        self.active: list[int] = list(range(key_count))
        self.next_key = key_count

    def __call__(self) -> dict:
        n = self.rng.randint(self.min_len, self.max_len)
        txn = []
        for _ in range(n):
            k = self.rng.choice(self.active)
            if self.rng.random() < 0.5:
                txn.append(["r", k, None])
            else:
                v = self.next_value[k]
                self.next_value[k] = v + 1
                txn.append(["append", k, v])
                if v + 1 >= self.max_writes:
                    # Retire the key, activate a fresh one.
                    self.active.remove(k)
                    self.active.append(self.next_key)
                    self.next_key += 1
        return {"f": "txn", "value": txn}
