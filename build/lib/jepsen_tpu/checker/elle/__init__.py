"""Elle-equivalent: transactional anomaly checking via dependency
graphs and cycle search (SURVEY.md §2.4; reimplemented, not ported —
the elle library is not vendored in the reference).

`append` and `wr` provide analyses + generators; `graph` the SCC/cycle
machinery; Checker adapters here plug into the checker protocol.
"""

from __future__ import annotations

from typing import Any, Optional

from ...history.core import History
from ..core import Checker
from . import append as _append
from . import graph, wr as _wr
from .append import AppendGen, analyze as analyze_append
from .graph import DepGraph, check_cycles
from .wr import WrGen, analyze as analyze_wr

__all__ = [
    "AppendChecker",
    "AppendGen",
    "DepGraph",
    "WrChecker",
    "WrGen",
    "analyze_append",
    "analyze_wr",
    "check_cycles",
    "graph",
    "write_artifacts",
]


def _device_cycle_fn(device: str):
    """None (host Tarjan) or the device-screened search (ops/scc.py):
    the MXU closure kernel settles acyclic graphs; small flagged
    graphs get the exact host layered extraction, large flagged ones
    extract their witness cycles on device too — same anomaly-type
    verdicts, but the device path emits one certificate per layer
    rather than the host's one per SCC per layer."""
    if device == "off":
        return None

    def screened(g: DepGraph):
        from ...ops.scc import check_cycles_device

        return check_cycles_device([g])[0]

    return screened


def write_artifacts(result: dict, opts: Optional[dict],
                    subdir: str = "elle") -> None:
    """Persists an invalid analysis into the store directory the way
    elle writes its :directory artifacts (consumed by the reference at
    tests/cycle/append.clj via the :directory option): a JSON anomaly
    dump plus one Graphviz DOT file per reported cycle, so a human can
    `dot -Tsvg` the dependency cycle that failed the test."""
    import json
    import logging
    import os

    directory = (opts or {}).get("dir")
    if not directory or result.get("valid") is True:
        return
    try:
        out = os.path.join(directory, subdir)
        os.makedirs(out, exist_ok=True)
        with open(os.path.join(out, "anomalies.json"), "w") as f:
            json.dump(
                {
                    "valid": result.get("valid"),
                    "anomaly-types": result.get("anomaly-types"),
                    "anomalies": result.get("anomalies"),
                },
                f, indent=2, default=repr,
            )
        cycles = result.get("anomalies")
        if isinstance(cycles, dict):
            cycles = [c for v in cycles.values() if isinstance(v, list)
                      for c in v if isinstance(c, dict) and "cycle" in c]
        elif isinstance(cycles, list):
            cycles = [c for c in cycles
                      if isinstance(c, dict) and "cycle" in c]
        else:
            cycles = []
        for i, c in enumerate(cycles):
            lines = ["digraph cycle {"]
            for step in c.get("steps", []):
                label = ",".join(step.get("types", []))
                lines.append(
                    f'  "T{step["from"]}" -> "T{step["to"]}" '
                    f'[label="{label}"];'
                )
            lines.append("}")
            name = f"cycle-{i}-{c.get('type', 'cycle')}.dot"
            with open(os.path.join(out, name), "w") as f:
                f.write("\n".join(lines) + "\n")
    except Exception as e:
        # A side-output failure (read-only/deleted store dir, full
        # disk, or a malformed anomaly payload that json.dump / the
        # DOT writer chokes on) must never escape and let check_safe
        # downgrade an already-computed invalid verdict to "unknown".
        # Same policy as IndependentChecker._write_key_artifacts.
        logging.getLogger(__name__).warning(
            "could not write elle artifacts to %s: %r", directory, e
        )


class AppendChecker(Checker):
    """checker for list-append workloads (append.clj:6-27).  `device`:
    "auto"/"on" screens cycle search on the accelerator, "off" keeps it
    on host."""

    def __init__(self, consistency_model: str = "serializable",
                 device: str = "auto"):
        self.consistency_model = consistency_model
        self.device = device

    def check(self, test: dict, history: History, opts: dict) -> dict:
        res = analyze_append(
            history.client_ops(),
            consistency_model=self.consistency_model,
            cycle_fn=_device_cycle_fn(self.device),
        )
        write_artifacts(res, opts, "elle-append")
        return res


class WrChecker(Checker):
    """checker for rw-register workloads (wr.clj:5-25).  `device` as in
    AppendChecker.  `sequential_keys` opts into the declared per-key
    sequential-write version-order inference (see wr.analyze) for
    systems that promise it."""

    def __init__(self, consistency_model: str = "serializable",
                 device: str = "auto", sequential_keys: bool = False):
        self.consistency_model = consistency_model
        self.device = device
        self.sequential_keys = sequential_keys

    def check(self, test: dict, history: History, opts: dict) -> dict:
        res = analyze_wr(
            history.client_ops(),
            consistency_model=self.consistency_model,
            cycle_fn=_device_cycle_fn(self.device),
            sequential_keys=self.sequential_keys,
        )
        write_artifacts(res, opts, "elle-wr")
        return res
