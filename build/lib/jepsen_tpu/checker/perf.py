"""Performance graphs: latency quantiles and throughput over time.

Equivalent of /root/reference/jepsen/src/jepsen/checker/perf.clj
(`bucket-points` :43, `quantiles` :52, `latencies->quantiles` :64,
`invokes-by-type` :96, nemesis activity shading) and the
latency-graph/rate-graph/perf checkers (checker.clj:821-853) — rendered
with matplotlib instead of gnuplot, and bucketed with numpy instead of
host loops.
"""

from __future__ import annotations

import logging
import os
from collections import defaultdict
from typing import Any, Optional, Sequence

import numpy as np

from ..history.core import History, Op
from ..utils import nemesis_intervals
from .core import Checker

log = logging.getLogger(__name__)

DEFAULT_QUANTILES = (0.5, 0.95, 0.99, 1.0)
DT_S = 1.0  # bucket width in seconds

_TYPE_COLORS = {"ok": "#81BFFC", "info": "#FFA400", "fail": "#FF1E90"}


def points(history: History) -> dict[str, dict[Any, np.ndarray]]:
    """{type: {f: array[(t_secs, latency_ms)]}} for completed client
    ops (perf.clj:96-130)."""
    out: dict[str, dict[Any, list]] = {
        "ok": defaultdict(list),
        "info": defaultdict(list),
        "fail": defaultdict(list),
    }
    for op in history:
        if op.is_invoke or not op.is_client_op:
            continue
        inv = history.invocation(op)
        if inv is None:
            continue
        t = inv.time / 1e9
        latency_ms = (op.time - inv.time) / 1e6
        if op.type in out:
            out[op.type][op.f].append((t, latency_ms))
    return {
        typ: {f: np.asarray(v) for f, v in d.items() if v}
        for typ, d in out.items()
    }


def latencies_to_quantiles(
    pts: np.ndarray,
    qs: Sequence[float] = DEFAULT_QUANTILES,
    dt: float = DT_S,
) -> dict[float, np.ndarray]:
    """Buckets (t, latency) points into dt-wide windows and takes
    latency quantiles per window (perf.clj:43-94) — vectorized."""
    if len(pts) == 0:
        return {q: np.zeros((0, 2)) for q in qs}
    t = pts[:, 0]
    lat = pts[:, 1]
    buckets = np.floor(t / dt).astype(np.int64)
    order = np.argsort(buckets, kind="stable")
    buckets, lat = buckets[order], lat[order]
    uniq, starts = np.unique(buckets, return_index=True)
    out: dict[float, list] = {q: [] for q in qs}
    for i, b in enumerate(uniq):
        lo = starts[i]
        hi = starts[i + 1] if i + 1 < len(starts) else len(lat)
        window = lat[lo:hi]
        mid = (b + 0.5) * dt
        for q in qs:
            out[q].append((mid, float(np.quantile(window, q))))
    return {q: np.asarray(v) for q, v in out.items()}


def rates(history: History, dt: float = DT_S) -> dict[tuple, np.ndarray]:
    """{(f, type): array[(t, ops/sec)]} (perf.clj rate graphs)."""
    counts: dict[tuple, dict[int, int]] = defaultdict(lambda: defaultdict(int))
    for op in history:
        if op.is_invoke or not op.is_client_op:
            continue
        b = int(op.time / 1e9 / dt)
        counts[(op.f, op.type)][b] += 1
    out = {}
    for key, bs in counts.items():
        out[key] = np.asarray(
            [((b + 0.5) * dt, n / dt) for b, n in sorted(bs.items())]
        )
    return out


def _nemesis_spans(test: dict, history: History) -> list[tuple[float, float]]:
    spans = []
    nem_ops = [o for o in history if not o.is_client_op]
    for a, b in nemesis_intervals(nem_ops):
        t0 = a.time / 1e9 if a is not None else 0.0
        t1 = b.time / 1e9 if b is not None else (
            history[-1].time / 1e9 if len(history) else t0
        )
        spans.append((t0, t1))
    return spans


def _plot_common(ax, test: dict, history: History) -> None:
    for t0, t1 in _nemesis_spans(test, history):
        ax.axvspan(t0, t1, color="#FDD", alpha=0.5, zorder=0)
    ax.set_xlabel("time (s)")
    ax.grid(True, alpha=0.3)


def plot_latencies(test: dict, history: History, path: str) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(10, 5))
    pts = points(history)
    for typ, by_f in pts.items():
        for f, arr in by_f.items():
            if typ == "ok":
                qs = latencies_to_quantiles(arr)
                for q, series in qs.items():
                    if len(series):
                        ax.plot(
                            series[:, 0], series[:, 1],
                            label=f"{f} q={q}", linewidth=1,
                        )
            else:
                ax.scatter(
                    arr[:, 0], arr[:, 1], s=6,
                    color=_TYPE_COLORS.get(typ),
                    label=f"{f} {typ}", alpha=0.6,
                )
    _plot_common(ax, test, history)
    ax.set_ylabel("latency (ms)")
    ax.set_yscale("log")
    ax.set_title(f"{test.get('name', 'test')} latency")
    ax.legend(fontsize=7, ncol=2)
    fig.savefig(path, dpi=110, bbox_inches="tight")
    plt.close(fig)


def plot_rates(test: dict, history: History, path: str) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(10, 5))
    for (f, typ), arr in sorted(rates(history).items(), key=lambda kv: repr(kv[0])):
        if len(arr):
            ax.plot(
                arr[:, 0], arr[:, 1],
                label=f"{f} {typ}",
                color=_TYPE_COLORS.get(typ),
                alpha=0.9, linewidth=1.2,
            )
    _plot_common(ax, test, history)
    ax.set_ylabel("throughput (ops/s)")
    ax.set_title(f"{test.get('name', 'test')} rate")
    ax.legend(fontsize=7, ncol=2)
    fig.savefig(path, dpi=110, bbox_inches="tight")
    plt.close(fig)


class LatencyGraph(Checker):
    """checker.clj:821-836."""

    def check(self, test, history, opts):
        d = opts.get("dir")
        if not d:
            return {"valid": True, "note": "no dir; skipped"}
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "latency-raw.png")
        plot_latencies(test, history, path)
        return {"valid": True, "file": path}


class RateGraph(Checker):
    """checker.clj:838-848."""

    def check(self, test, history, opts):
        d = opts.get("dir")
        if not d:
            return {"valid": True, "note": "no dir; skipped"}
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "rate.png")
        plot_rates(test, history, path)
        return {"valid": True, "file": path}


def perf() -> Checker:
    """Both graphs (checker.clj:850-853)."""
    from .core import compose

    return compose({"latency-graph": LatencyGraph(), "rate-graph": RateGraph()})
