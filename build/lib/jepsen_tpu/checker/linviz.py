"""Non-linearizable counterexample rendering.

Equivalent of knossos's `linear.svg` as the reference invokes it
(knossos.linear.report/render-analysis! at
/root/reference/jepsen/src/jepsen/checker.clj:223-229): when the WGL
search proves a history non-linearizable, draw the window of operations
around the op that could not be linearized — per-process time bars with
op labels, the crashed op highlighted — plus the deepest configurations
the search reached (their model states and missing ops), so a human can
see *why* every linearization path dies.

Hand-rolled SVG: no plotting dependency, deterministic output, small
files.
"""

from __future__ import annotations

import html
from typing import Any, Optional

from ..history.packed import ST_OK, PackedOps
from ..models.base import PackedModel
from .wgl_cpu import WGLResult

#: Ops drawn before/after the crashed op.
WINDOW_BEFORE = 18
WINDOW_AFTER = 6

ROW_H = 26
BAR_H = 18
LEFT = 90
PX_PER_EVENT = 28
TOP = 34


def _describe(pm: PackedModel, packed: PackedOps, a: int) -> str:
    if pm.describe_op is not None:
        return pm.describe_op(
            int(packed.f[a]), int(packed.a0[a]), int(packed.a1[a])
        )
    return f"f={int(packed.f[a])}({int(packed.a0[a])},{int(packed.a1[a])})"


def _state_str(pm: PackedModel, state: list) -> str:
    try:
        vals = [pm.interner.value(int(s)) for s in state]
    except (IndexError, TypeError):
        vals = list(state)
    return "/".join(repr(v) for v in vals)


def render_analysis(
    packed: PackedOps,
    pm: PackedModel,
    res: WGLResult,
    path: str,
) -> Optional[str]:
    """Writes the counterexample SVG; returns the path (None when the
    result carries nothing renderable)."""
    if res.valid is not False or res.crashed_at is None:
        return None
    crash = res.crashed_at
    n = packed.n
    lo = max(0, crash - WINDOW_BEFORE)
    hi = min(n, crash + WINDOW_AFTER + 1)
    rows_ops = list(range(lo, hi))
    if not rows_ops:
        return None

    # Event-index -> x coordinate, compressed to the events we draw.
    events = sorted(
        {int(packed.inv[a]) for a in rows_ops}
        | {
            int(packed.ret[a])
            for a in rows_ops
            if packed.status[a] == ST_OK
        }
    )
    ex = {e: i for i, e in enumerate(events)}
    right_x = LEFT + (len(events) + 1) * PX_PER_EVENT

    procs = sorted({int(packed.process[a]) for a in rows_ops})
    py = {p: TOP + i * ROW_H for i, p in enumerate(procs)}

    linearized_sets = [
        set(c.get("linearized", [])) for c in res.final_configs
    ]
    in_any_config = set().union(*linearized_sets) if linearized_sets else set()

    parts: list[str] = []
    h_chart = TOP + len(procs) * ROW_H + 10
    config_lines = min(len(res.final_configs), 10)
    height = h_chart + 26 + config_lines * 18 + 16
    parts.append(
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{right_x + 20}" '
        f'height="{height}" font-family="monospace" font-size="11">'
    )
    parts.append(
        f'<text x="{LEFT}" y="16" font-size="13">non-linearizable window: '
        f"op {html.escape(_describe(pm, packed, crash))} "
        f"(history index {int(packed.src_index[crash])}) "
        f"cannot be linearized</text>"
    )

    for p in procs:
        parts.append(
            f'<text x="8" y="{py[p] + BAR_H - 5}">proc {p}</text>'
        )

    for a in rows_ops:
        p = int(packed.process[a])
        x0 = LEFT + ex[int(packed.inv[a])] * PX_PER_EVENT
        if packed.status[a] == ST_OK:
            x1 = LEFT + ex[int(packed.ret[a])] * PX_PER_EVENT + PX_PER_EVENT
        else:
            x1 = right_x  # indeterminate: open to the edge
        y = py[p]
        if a == crash:
            fill, stroke = "#fbb", "#c00"
        elif a in in_any_config:
            fill, stroke = "#bfe3bf", "#4a4"  # linearized in some config
        elif packed.status[a] == ST_OK:
            fill, stroke = "#dde6f0", "#88a"
        else:
            fill, stroke = "#eee", "#aaa"
        parts.append(
            f'<rect x="{x0}" y="{y}" width="{max(x1 - x0, 4)}" '
            f'height="{BAR_H}" fill="{fill}" stroke="{stroke}" rx="3"/>'
        )
        label = _describe(pm, packed, a)
        if packed.status[a] != ST_OK:
            label += " (info)"
        parts.append(
            f'<text x="{x0 + 3}" y="{y + BAR_H - 5}" '
            f'clip-path="none">{html.escape(label)}</text>'
        )

    y = h_chart + 14
    parts.append(
        f'<text x="8" y="{y}" font-size="12">deepest configurations '
        f"(model state | linearized count | missing ok ops):</text>"
    )
    for c in res.final_configs[:10]:
        y += 18
        missing = ", ".join(
            html.escape(_describe(pm, packed, m))
            for m in c.get("missing_ok_ops", [])[:4]
        )
        parts.append(
            f'<text x="20" y="{y}">state '
            f"{html.escape(_state_str(pm, c.get('state', [])))} | "
            f"{len(c.get('linearized', []))} linearized | missing: "
            f"{missing}</text>"
        )
    parts.append("</svg>")

    with open(path, "w") as f:
        f.write("\n".join(parts))
    return path
