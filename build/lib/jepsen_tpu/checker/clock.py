"""Clock-offset plot.

Equivalent of /root/reference/jepsen/src/jepsen/checker/clock.clj:
collects the {"clock-offsets": {node: offset}} values that the clock
nemesis attaches to its completions (:14-35 history->datasets) and
plots per-node offsets over time.
"""

from __future__ import annotations

import os
from collections import defaultdict
from typing import Any

from ..history.core import History
from .core import Checker


def datasets(history: History) -> dict[Any, list[tuple[float, float]]]:
    """{node: [(t_secs, offset_secs)]} (clock.clj:14-35)."""
    out: dict[Any, list] = defaultdict(list)
    for op in history:
        v = op.value
        if isinstance(v, dict) and "clock-offsets" in v:
            t = op.time / 1e9
            for node, off in (v["clock-offsets"] or {}).items():
                try:
                    out[node].append((t, float(off)))
                except (TypeError, ValueError):
                    continue
    return dict(out)


class ClockPlot(Checker):
    def check(self, test: dict, history: History, opts: dict) -> dict:
        d = opts.get("dir")
        data = datasets(history)
        if not data:
            return {"valid": True, "note": "no clock data"}
        if not d:
            return {"valid": True, "note": "no dir; skipped"}
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        os.makedirs(d, exist_ok=True)
        fig, ax = plt.subplots(figsize=(10, 4))
        for node, pts in sorted(data.items(), key=lambda kv: str(kv[0])):
            xs = [p[0] for p in pts]
            ys = [p[1] for p in pts]
            ax.plot(xs, ys, marker="o", markersize=3, label=str(node))
        ax.set_xlabel("time (s)")
        ax.set_ylabel("clock offset (s)")
        ax.grid(True, alpha=0.3)
        ax.legend(fontsize=8)
        path = os.path.join(d, "clock.png")
        fig.savefig(path, dpi=110, bbox_inches="tight")
        plt.close(fig)
        return {"valid": True, "file": path}


def clock_plot() -> ClockPlot:
    return ClockPlot()
