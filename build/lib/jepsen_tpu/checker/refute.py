"""Sound O(n log n) non-linearizability screens over op intervals.

The exact engines (wgl_cpu.py's DFS, wgl_event.py's event walk) decide
both directions but share WGL's worst case: once accumulated :info ops
unlock every model state, the per-barrier closure is the full subset
lattice of open ops — exponential in concurrency — and no constant
factor saves a 50k-op invalid history.  The reference hits the same
wall: knossos times out on BASELINE.md's north-star history.

This module is the third racer (knossos.competition races solvers the
same way, consumed at checker.clj:214-233): *necessary conditions* for
linearizability of register-family histories, checked columnar in
numpy.  When a condition fails, the history is PROVEN non-linearizable
and the screen returns a certificate; when none fails it returns None
and the exact engines carry on.  Sound, incomplete, O(n log n) — it
settles at any scale the two invalid families that dominate practice:

* unsupported read — an :ok op asserts a value no op could have
  produced before it returned (a read of a never-acknowledged write);
* stale read — every producer of the asserted value is *necessarily*
  overwritten: some :ok non-producer op's whole window fits between
  the producer's return and the reader's invocation (the async-
  replication shape: a backup serving a value the primary overwrote
  long ago, e.g. demo/repkv's unsafe reads).

Soundness argument (zone conditions in the style of Gibbons & Korach,
"Testing Shared Memories", SIAM J. Comput. 1997): suppose a
linearization exists and :ok op r asserts value v at its point t_r ∈
(inv_r, ret_r).  Let q be the op whose effect last established v
before t_r (or "initial state" if none).  Then q is a producer of v
with inv_q < t_r ≤ ret_r.  If some :ok op w with a forced effect ≠ v
on the same key has its whole window inside (ret_q, inv_r), then w's
effect lands strictly between t_q and t_r, so state left v after q —
contradicting q being last (whatever re-established v would be a later
producer, considered separately).  An :info producer can linearize
arbitrarily late, so it is never killable this way; it blocks
refutation whenever inv < ret_r.  Hence: if EVERY candidate q is
killed and v is not an unperturbed initial value, no linearization
exists.

Models opt in through `PackedModel.refute_view` returning a
`RefuteView`; models without one (queues, sets) simply skip the
screen.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..history.packed import NIL, ST_OK, PackedOps
from ..models.base import PackedModel
from .wgl_cpu import WGLResult

#: "before everything" / "no overwriter" sentinel for M values.
_NEG = np.iinfo(np.int64).min // 4


@dataclass
class RefuteView:
    """Per-row facets the screens run on.

    - key:      (n,) int32 — state word the op touches (0 for scalar
                registers; the register index for multi-register).
    - asserts:  (n,) int32 — value code the state must equal at the
                op's linearization point (reads: the value read; cas:
                the expected value), or NIL.
    - produces: (n,) int32 — value code the op forces the key to on
                success (writes and cas new-values), or NIL.  For :ok
                rows the effect is certain (the op returned); for
                :info rows it is possible.
    - init:     (n_keys,) int32 — initial value code per key.
    """

    key: np.ndarray
    asserts: np.ndarray
    produces: np.ndarray
    init: np.ndarray


def _top2_distinct(ret_w: np.ndarray, inv_w: np.ndarray,
                   label_w: np.ndarray):
    """Prefix structure over overwriters sorted by ret: for each prefix,
    the max inv and, with a different produce-label, the runner-up max
    inv.  Lets M(t, v) = "latest inv among ops with ret ≤ t whose label
    ≠ v" be answered per query from two tracks."""
    order = np.argsort(ret_w, kind="stable")
    ret_s, inv_s, lab_s = ret_w[order], inv_w[order], label_w[order]
    m = len(order)
    best = np.full(m, _NEG, dtype=np.int64)
    best_lab = np.full(m, NIL, dtype=np.int64)
    alt = np.full(m, _NEG, dtype=np.int64)
    b, bl, a = _NEG, NIL, _NEG
    for i in range(m):
        iv, lb = int(inv_s[i]), int(lab_s[i])
        if lb == bl:
            if iv > b:
                b = iv
        elif iv > b:
            # New champion with a new label; old champion becomes the
            # best-with-different-label iff its inv beats the alt.
            if b > a and bl != NIL:
                a = b
            b, bl = iv, lb
        elif iv > a:
            a = iv
        best[i], best_lab[i], alt[i] = b, bl, a
    return ret_s, best, best_lab, alt


def check_refute(
    packed: PackedOps,
    pm: PackedModel,
    *,
    time_limit_s: Optional[float] = None,
    report_configs: int = 10,
) -> Optional[WGLResult]:
    """Runs the screens; WGLResult(valid=False, ...) with a certificate
    when a violation is proven, else None (no opinion — NOT "valid")."""
    if pm.refute_view is None or packed.n == 0:
        return None
    t0 = time.monotonic()
    # The screen is O(n log n) and a pre-pass, not a search: even with
    # no configured limit it must not stall the engines behind it.
    limit = 60.0 if time_limit_s is None else time_limit_s
    view = pm.refute_view(packed)

    inv = packed.inv.astype(np.int64)
    ret = packed.ret.astype(np.int64)
    ok = packed.status == ST_OK
    key = view.key.astype(np.int64)
    asserts = view.asserts.astype(np.int64)
    produces = view.produces.astype(np.int64)

    # :info rows may linearize arbitrarily late: their ret is +inf for
    # every screen purpose (packed stores NO_RET; normalize).
    big = np.iinfo(np.int64).max // 4
    ret = np.where(ok, ret, big)

    ass_rows = np.nonzero(ok & (asserts != NIL))[0]
    if len(ass_rows) == 0:
        return None

    refuted: list[dict] = []
    crashed_at: Optional[int] = None
    done = False

    for k in np.unique(key[ass_rows]):
        if done or time.monotonic() - t0 > limit:
            break
        on_key = key == k
        a_rows = ass_rows[key[ass_rows] == k]
        # Forced overwriters: :ok effects on this key.  Label = value
        # produced, so M can exclude producers of the queried value.
        w_rows = np.nonzero(on_key & ok & (produces != NIL))[0]
        have_w = len(w_rows) > 0
        if have_w:
            ret_s, best, best_lab, alt = _top2_distinct(
                ret[w_rows], inv[w_rows], produces[w_rows]
            )
        p_rows = np.nonzero(on_key & (produces != NIL))[0]
        init_v = int(view.init[int(k)])

        # Group asserting rows and producers by value ONCE (sorted +
        # sliced): a per-value boolean rescan would be quadratic on
        # unique-value histories.
        a_sorted = a_rows[np.argsort(asserts[a_rows], kind="stable")]
        a_vals = asserts[a_sorted]
        p_sorted = p_rows[np.argsort(produces[p_rows], kind="stable")]
        p_vals = produces[p_sorted]
        group_vals, group_starts = np.unique(a_vals, return_index=True)
        group_ends = np.append(group_starts[1:], len(a_vals))

        for v, g_lo, g_hi in zip(group_vals, group_starts, group_ends):
            v = int(v)
            rows_v = a_sorted[g_lo:g_hi]
            # M per query: latest inv among overwriters (≠ v) whose
            # whole window precedes the query's invocation.
            if have_w:
                j = np.searchsorted(ret_s, inv[rows_v], side="right") - 1
                jc = np.maximum(j, 0)
                M = np.where(
                    j < 0, _NEG,
                    np.where(best_lab[jc] != v, best[jc], alt[jc]),
                )
            else:
                M = np.full(len(rows_v), _NEG, dtype=np.int64)

            alive = (v == init_v) & (M == _NEG)
            pv = p_sorted[
                np.searchsorted(p_vals, v, side="left"):
                np.searchsorted(p_vals, v, side="right")
            ]
            if len(pv):
                # :info producers are never killable: they may
                # linearize arbitrarily late.
                pi = pv[~ok[pv]]
                if len(pi):
                    alive = alive | (int(inv[pi].min()) < ret[rows_v])
                # An :ok producer survives when no overwriter window
                # fits after its return: among producers with
                # ret > M, the earliest invocation must precede the
                # query's return.
                po = pv[ok[pv]]
                if len(po):
                    o = np.argsort(ret[po], kind="stable")
                    ret_p = ret[po][o]
                    # suffix-min of inv over producers sorted by ret
                    sufmin = np.minimum.accumulate(inv[po][o][::-1])[::-1]
                    sufmin = np.append(sufmin, big)
                    idx = np.searchsorted(ret_p, M, side="right")
                    alive = alive | (sufmin[idx] < ret[rows_v])

            for r in rows_v[~alive]:
                refuted.append(
                    _certificate(packed, pm, view, int(r), v,
                                 int(M[np.nonzero(rows_v == r)[0][0]]),
                                 pv, ok)
                )
                if crashed_at is None or ret[r] < ret[crashed_at]:
                    crashed_at = int(r)
                if len(refuted) >= report_configs:
                    done = True
                    break
            if done or time.monotonic() - t0 > limit:
                done = True
                break

    if not refuted:
        return None
    return WGLResult(
        valid=False,
        configs_explored=len(ass_rows),
        final_configs=refuted,
        crashed_at=crashed_at,
        elapsed_s=time.monotonic() - t0,
    )


def _certificate(packed, pm, view, r: int, v: int, M: int, pv, ok):
    desc = (
        pm.describe_op(int(packed.f[r]), int(packed.a0[r]),
                       int(packed.a1[r]))
        if pm.describe_op else None
    )
    val = pm.interner.value(v) if v != NIL else None
    producers = [
        {
            "history-index": int(packed.src_index[p]),
            "status": "ok" if ok[p] else "info",
            "killed-by-overwrite-before": int(M),
        }
        for p in pv[:8]
    ]
    return {
        "screen": "unsupported-read" if len(pv) == 0 else "stale-read",
        "op": desc,
        "history-index": int(packed.src_index[r]),
        "asserted-value": val,
        "producers-considered": producers,
        "proof": (
            "no op that could produce the asserted value is "
            "linearizable before this op returns"
            if len(pv) == 0 else
            "every producer of the asserted value is necessarily "
            "overwritten by an acknowledged op whose whole window "
            "precedes this op's invocation"
        ),
    }
