"""Exact event-walk WGL with a domination quotient.

The exhaustive counterpart of the device witness search
(ops/wgl_witness.py): same event-walk formulation — :ok operations are
*barriers* processed in completion order; by induction every earlier-
returning :ok op is linearized in all live configs, so the candidate
rule collapses to "invoked before the current barrier's return" — but
instead of a beam it keeps the FULL reachable configuration set, so a
dead frontier proves non-linearizability (knossos's role for invalid
verdicts, consumed by the reference at checker.clj:214-233).

What makes it survive high-:info histories where the memoized DFS
(wgl_cpu.py) and the level-synchronous BFS (ops/wgl.py) explode:

* Indeterminate ops quotient by PAYLOAD CLASS: two info ops with the
  same encoded (f, a0, a1) are interchangeable as helpers — identical
  transition, no deadline, and availability (inv < barrier ret) only
  ever grows — so a configuration needs only the COUNT of consumed
  ops per class, not their identity.  This is exact, and it collapses
  the antichain blowup of identity-based member sets (consuming w3(5)
  vs w7(5) produced incomparable sets whose minimal frontier still
  grew combinatorially).
* Configurations group by (model state, open :ok membership); within a
  group only the ANTICHAIN of pointwise-minimal class-count vectors is
  kept.  Domination is exact: consumed info ops never loosen the
  candidate rule (a non-member info op has ret = ∞ and constrains
  nobody), so a config that consumed pointwise-fewer per class can
  simulate every future of the greater one.
* Between barriers only the *filtered* frontier is carried: configs
  that failed to contain the barrier op die with their whole subtree
  (the closure is recomputed from survivors, which is complete because
  linearization is monotone).

Exact verdicts both ways; `max_configs`/`time_limit_s` degrade to
"unknown" like the reference's timeout.
"""

from __future__ import annotations

import time
from typing import Optional

from ..history.packed import ST_OK, PackedOps
from ..models.base import PackedModel
from .wgl_cpu import WGLResult


def check_wgl_event(
    packed: PackedOps,
    pm: PackedModel,
    *,
    max_configs: int = 5_000_000,
    time_limit_s: Optional[float] = None,
    report_configs: int = 10,
) -> WGLResult:
    t0 = time.monotonic()
    n = packed.n
    if n == 0 or packed.n_ok == 0:
        return WGLResult(valid=True, configs_explored=1,
                         elapsed_s=time.monotonic() - t0)

    inv = packed.inv.tolist()
    ret = packed.ret.tolist()
    f = packed.f.tolist()
    a0 = packed.a0.tolist()
    a1 = packed.a1.tolist()
    status = packed.status.tolist()
    step = pm.py_step
    init = tuple(pm.init_state)

    is_info = [status[i] != ST_OK for i in range(n)]
    ok_rows = [i for i in range(n) if not is_info[i]]
    bars = sorted(ok_rows, key=lambda i: ret[i])

    # Info payload classes: identity never matters, only the count of
    # consumed ops per class vs the count available.
    class_of: dict[tuple, int] = {}
    info_class = [0] * n
    for i in range(n):
        if is_info[i]:
            key = (f[i], a0[i], a1[i])
            info_class[i] = class_of.setdefault(key, len(class_of))
    n_classes = len(class_of)
    class_ops = [None] * n_classes  # one representative (f, a0, a1)
    for key, c in class_of.items():
        class_ops[c] = key
    zero_counts = (0,) * n_classes

    explored = 0
    passed_mask = 0  # barriers already passed: members everywhere
    # Frontier: {(state, ok_members_mask): [count-vector antichain]}
    frontier: dict[tuple, list[tuple]] = {(init, 0): [zero_counts]}
    avail_upto = 0            # rows with index < avail_upto are available
    avail_ok: list[int] = []  # available, un-barriered :ok rows
    avail_counts = [0] * n_classes

    def insert(store: dict, state, okm: int, cnt: tuple) -> bool:
        """Antichain insert over count vectors; True if genuinely new."""
        key = (state, okm)
        chain = store.get(key)
        if chain is None:
            store[key] = [cnt]
            return True
        keep = []
        for other in chain:
            le = ge = True
            for x, y in zip(other, cnt):
                if x > y:
                    le = False
                if x < y:
                    ge = False
            if le:   # other ≤ cnt pointwise: dominated
                return False
            if not ge:
                keep.append(other)
            # other ≥ cnt (strictly somewhere): drop other
        keep.append(cnt)
        store[key] = keep
        return True

    for a in bars:
        r = ret[a]
        # New rows became available before this barrier's return.
        while avail_upto < n and inv[avail_upto] < r:
            h = avail_upto
            if is_info[h]:
                avail_counts[info_class[h]] += 1
            else:
                avail_ok.append(h)
            avail_upto += 1

        # Closure from the frontier over available candidates, pruned
        # by domination, then filtered on membership of `a`.
        seen: dict[tuple, list[tuple]] = {}
        queue: list[tuple] = []
        for (state, okm), chain in frontier.items():
            for cnt in chain:
                if insert(seen, state, okm, cnt):
                    queue.append((state, okm, cnt))
        survivors: dict[tuple, list[tuple]] = {}
        a_bit = 1 << a

        while queue:
            state, okm, cnt = queue.pop()
            explored += 1
            if explored > max_configs:
                return WGLResult(
                    valid="unknown", configs_explored=explored,
                    reason="config-limit",
                    elapsed_s=time.monotonic() - t0,
                )
            if not (explored & 0xFFF) and time_limit_s is not None:
                if time.monotonic() - t0 > time_limit_s:
                    return WGLResult(
                        valid="unknown", configs_explored=explored,
                        reason="time-limit",
                        elapsed_s=time.monotonic() - t0,
                    )
            if okm & a_bit:
                insert(survivors, state, okm, cnt)
                continue
            # :ok candidates (early linearization of open ops + a).
            for h in avail_ok:
                h_bit = 1 << h
                if okm & h_bit:
                    continue
                ns, legal = step(state, f[h], a0[h], a1[h])
                if not legal:
                    continue
                if insert(seen, ns, okm | h_bit, cnt):
                    queue.append((ns, okm | h_bit, cnt))
            # Info candidates, one per class with spare availability.
            for c in range(n_classes):
                if cnt[c] >= avail_counts[c]:
                    continue
                fc, a0c, a1c = class_ops[c]
                ns, legal = step(state, fc, a0c, a1c)
                if not legal:
                    continue
                cnt2 = cnt[:c] + (cnt[c] + 1,) + cnt[c + 1:]
                if insert(seen, ns, okm, cnt2):
                    queue.append((ns, okm, cnt2))

        if not survivors:
            # Dead frontier: `a` cannot be linearized from any
            # reachable configuration — non-linearizable.
            final = []
            for (state, okm), chain in list(frontier.items())[:report_configs]:
                members = okm | passed_mask
                final.append({
                    "linearized": [i for i in range(n)
                                   if members >> i & 1],
                    "info-consumed": {
                        repr(class_ops[c]): k
                        for c, k in enumerate(chain[0]) if k
                    },
                    "state": list(state),
                    "missing_ok_ops": [a],
                })
            return WGLResult(
                valid=False, configs_explored=explored,
                final_configs=final, crashed_at=a,
                elapsed_s=time.monotonic() - t0,
            )

        # `a` is now a guaranteed member everywhere: drop it from the
        # candidate pool and from the ok-membership key (its bit is
        # implied), keeping keys compact.
        avail_ok = [h for h in avail_ok if h != a]
        passed_mask |= a_bit
        frontier = {}
        for (state, okm), chain in survivors.items():
            okm2 = okm & ~a_bit
            for cnt in chain:
                insert(frontier, state, okm2, cnt)

    return WGLResult(
        valid=True, configs_explored=explored,
        elapsed_s=time.monotonic() - t0,
    )
