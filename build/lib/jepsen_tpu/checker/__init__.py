"""Checkers: history analysis (jepsen.checker equivalents) with the
linearizability search TPU-offloaded (the BASELINE.json north star)."""

from .core import (
    Checker,
    Compose,
    CounterChecker,
    LogFilePattern,
    NoOp,
    Queue,
    SetChecker,
    SetFull,
    Stats,
    TotalQueue,
    UnhandledExceptions,
    UniqueIds,
    check_safe,
    checker,
    compose,
    concurrency_limit,
    merge_valid,
    valid_rank,
)
from .linearizable import Linearizable, linearizable
from .wgl_cpu import WGLResult, check_wgl_cpu, check_wgl_host_model

__all__ = [
    "Checker",
    "Compose",
    "CounterChecker",
    "LogFilePattern",
    "NoOp",
    "Queue",
    "SetChecker",
    "SetFull",
    "Stats",
    "TotalQueue",
    "UnhandledExceptions",
    "UniqueIds",
    "check_safe",
    "checker",
    "compose",
    "concurrency_limit",
    "merge_valid",
    "valid_rank",
    "Linearizable",
    "linearizable",
    "WGLResult",
    "check_wgl_cpu",
    "check_wgl_host_model",
]
