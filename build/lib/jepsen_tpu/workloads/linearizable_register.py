"""Per-key linearizable register workload — the north-star workload.

Equivalent of /root/reference/jepsen/src/jepsen/tests/
linearizable_register.clj:22-53: independent per-key registers driven by
concurrent thread groups, checked by the TPU-batched WGL linearizability
search sharded across keys (parallel/independent.py).  Caps per-key ops
(`per-key-limit`) because crashed ops blow up search width
(linearizable_register.clj:39-53).
"""

from __future__ import annotations

import random
import threading
from collections import defaultdict
from typing import Optional

from .. import client as jc
from ..checker.linearizable import linearizable
from ..generator.core import FnGen, limit
from ..generator.independent import concurrent_generator
from ..history import FAIL, OK
from ..models import cas_register
from ..parallel.independent import KV, independent_checker


class InMemoryKVRegisterClient(jc.Client):
    """Per-key CAS registers; op values are KV tuples."""

    def __init__(self, state=None, lock=None):
        self.state = state if state is not None else {}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return InMemoryKVRegisterClient(self.state, self.lock)

    def invoke(self, test, op):
        k, v = op.value.key, op.value.value
        with self.lock:
            if op.f == "write":
                self.state[k] = v
                return op.complete(OK)
            if op.f == "read":
                return op.complete(OK, value=KV(k, self.state.get(k)))
            old, new = v
            if self.state.get(k) == old:
                self.state[k] = new
                return op.complete(OK)
            return op.complete(FAIL)

    def reusable(self, test):
        return True


def _key_gen(per_key_limit: int, rng: random.Random):
    def fgen(key):
        def step():
            r = rng.random()
            if r < 0.4:
                return {"f": "read", "value": KV(key, None)}
            if r < 0.8:
                return {"f": "write", "value": KV(key, rng.randrange(5))}
            return {
                "f": "cas",
                "value": KV(key, (rng.randrange(5), rng.randrange(5))),
            }

        return limit(per_key_limit, FnGen(step))

    return fgen


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    rng = random.Random(opts.get("seed"))
    n_keys = opts.get("key-count", 8)
    per_key = opts.get("per-key-limit", 128)
    group = opts.get("threads-per-key", 4)
    algorithm = opts.get("algorithm", "wgl-tpu")
    return {
        "name": "linearizable-register",
        "model": cas_register(),
        "generator": concurrent_generator(
            group, list(range(n_keys)), _key_gen(per_key, rng)
        ),
        "checker": independent_checker(
            linearizable(model=cas_register(), algorithm=algorithm)
        ),
        "client": InMemoryKVRegisterClient(),
    }
