"""Long-fork anomaly workload.

Equivalent of /root/reference/jepsen/src/jepsen/tests/long_fork.clj
(spec in its docstring :1-60): writers write each register key exactly
once; readers read a whole group of n keys in one txn.  Under parallel
snapshot isolation, two reads can observe the writes in contradictory
orders — read A sees w1 but not w2 while read B sees w2 but not w1 —
the "long fork" (an instance of G2).
"""

from __future__ import annotations

import itertools
import random
import threading
from collections import defaultdict
from typing import Any, Optional

from .. import client as jc
from ..checker.core import Checker
from ..generator.core import FnGen
from ..history import OK, History


def read_txn_mops(op_value) -> Optional[dict]:
    """{k: v} for a read txn's mops, or None for a write txn."""
    if not op_value:
        return None
    if any(m[0] != "r" for m in op_value):
        return None
    return {m[1]: m[2] for m in op_value}


class LongForkChecker(Checker):
    """Finds contradictory read pairs (long_fork.clj:62-250 condensed:
    with single-write-per-key groups, two group reads fork iff each
    sees a write the other missed)."""

    def check(self, test: dict, history: History, opts: dict) -> dict:
        reads_by_group: dict[frozenset, list] = defaultdict(list)
        for op in history:
            if not (op.is_ok and op.f == "txn"):
                continue
            r = read_txn_mops(op.value)
            if r is not None and len(r) > 1:
                reads_by_group[frozenset(r.keys())].append((op.index, r))

        forks = []
        for group, reads in reads_by_group.items():
            for i in range(len(reads)):
                for j in range(i + 1, len(reads)):
                    ia, ra = reads[i]
                    ib, rb = reads[j]
                    # a key A saw written that B didn't, and vice versa
                    a_ahead = any(
                        ra[k] is not None and rb[k] is None for k in group
                    )
                    b_ahead = any(
                        rb[k] is not None and ra[k] is None for k in group
                    )
                    if a_ahead and b_ahead:
                        forks.append(
                            {"ops": [ia, ib], "reads": [ra, rb]}
                        )
        return {
            "valid": not forks,
            "early-read-count": sum(len(v) for v in reads_by_group.values()),
            "fork-count": len(forks),
            "forks": forks[:8],
        }


class InMemoryLongForkClient(jc.Client):
    """Atomic txn store over registers."""

    def __init__(self, state=None, lock=None):
        self.state = state if state is not None else {}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return InMemoryLongForkClient(self.state, self.lock)

    def invoke(self, test, op):
        with self.lock:
            out = []
            for f, k, v in op.value:
                if f == "w":
                    self.state[k] = v
                    out.append([f, k, v])
                else:
                    out.append(["r", k, self.state.get(k)])
            return op.complete(OK, value=out)

    def reusable(self, test):
        return True


def generator(group_size: int = 2, rng: Optional[random.Random] = None):
    """Write each key of the current group once (value 1), read whole
    groups; move to a fresh group when exhausted
    (long_fork.clj:252-332)."""
    rng = rng or random.Random()
    state = {"group": 0, "written": set()}

    def step():
        g = state["group"]
        keys = list(range(g * group_size, (g + 1) * group_size))
        unwritten = [k for k in keys if k not in state["written"]]
        if unwritten and rng.random() < 0.4:
            k = rng.choice(unwritten)
            state["written"].add(k)
            if not [x for x in keys if x not in state["written"]]:
                state["group"] = g + 1
            return {"f": "txn", "value": [["w", k, 1]]}
        return {"f": "txn", "value": [["r", k, None] for k in keys]}

    return FnGen(step)


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    n = opts.get("group-size", 2)
    return {
        "name": "long-fork",
        "generator": generator(n, random.Random(opts.get("seed"))),
        "checker": LongForkChecker(),
        "client": InMemoryLongForkClient(),
    }
