"""List-append workload (tests/cycle/append.clj:11-46 equivalent).

Transactions of ["append", k, v] / ["r", k, list] micro-ops, checked by
the Elle-equivalent list-append analysis.
"""

from __future__ import annotations

import random
import threading
from collections import defaultdict
from typing import Any, Optional

from .. import client as jc
from ..checker.elle import AppendChecker, AppendGen
from ..generator.core import FnGen
from ..history import OK, Op


class InMemoryAppendClient(jc.Client):
    """Serializable in-memory store of lists: applies whole transactions
    atomically under one lock (the trivially-correct reference client)."""

    def __init__(self, state=None, lock=None):
        self.state = state if state is not None else defaultdict(list)
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return InMemoryAppendClient(self.state, self.lock)

    def invoke(self, test, op):
        with self.lock:
            out = []
            for f, k, v in op.value:
                if f == "append":
                    self.state[k].append(v)
                    out.append([f, k, v])
                else:
                    out.append(["r", k, list(self.state[k])])
            return op.complete(OK, value=out)

    def reusable(self, test):
        return True


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    gen = AppendGen(
        key_count=opts.get("key-count", 10),
        min_txn_length=opts.get("min-txn-length", 1),
        max_txn_length=opts.get("max-txn-length", 4),
        max_writes_per_key=opts.get("max-writes-per-key", 32),
        rng=random.Random(opts.get("seed")),
    )
    return {
        "name": "list-append",
        "generator": FnGen(gen),
        "checker": AppendChecker(
            opts.get("consistency-model", "serializable")
        ),
        "client": InMemoryAppendClient(),
    }
