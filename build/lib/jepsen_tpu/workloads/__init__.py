"""Stock workloads: {generator, checker, model, client?} maps.

Equivalent of the reference's /root/reference/jepsen/src/jepsen/tests/
subtree — each module exposes a `workload(opts) -> dict` whose keys are
merged into a test map (the tests/bank.clj:178-191 pattern), plus an
in-memory reference client so every workload runs whole-stack in CI
(tests.clj:26-66 atom-client strategy).
"""

from . import (
    adya,
    append,
    bank,
    causal,
    causal_reverse,
    cycle,
    kafka,
    linearizable_register,
    long_fork,
    register_set,
    wr,
)

__all__ = [
    "adya",
    "append",
    "bank",
    "causal",
    "causal_reverse",
    "cycle",
    "kafka",
    "linearizable_register",
    "long_fork",
    "register_set",
    "wr",
]
