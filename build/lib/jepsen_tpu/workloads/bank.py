"""Bank workload: transfers with a conserved total.

Equivalent of /root/reference/jepsen/src/jepsen/tests/bank.clj: the
generator mixes reads of all accounts with random transfers (:40-54),
and the checker (:56-120) asserts every read shows the same total and
(unless negative balances are allowed) no account below zero — the
classic snapshot-isolation probe.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Optional

from .. import client as jc
from ..checker.core import Checker
from ..generator.core import FnGen, mix
from ..history import FAIL, OK, History


DEFAULT_ACCOUNTS = list(range(8))
DEFAULT_TOTAL = 100


class BankChecker(Checker):
    """tests/bank.clj:56-120."""

    def __init__(self, *, negative_balances: bool = False):
        self.negative_balances = negative_balances

    def check(self, test: dict, history: History, opts: dict) -> dict:
        total = test.get("total-amount", DEFAULT_TOTAL)
        accounts = set(test.get("accounts", DEFAULT_ACCOUNTS))
        bad_reads = []
        reads = 0
        for op in history:
            if not (op.is_ok and op.f == "read") or op.value is None:
                continue
            reads += 1
            balances = {int(k): v for k, v in dict(op.value).items()}
            problems = []
            if set(balances.keys()) != accounts:
                problems.append("unexpected-accounts")
            got = sum(balances.values())
            if got != total:
                problems.append(f"wrong-total {got}")
            if not self.negative_balances and any(
                v < 0 for v in balances.values()
            ):
                problems.append("negative-balance")
            if problems:
                bad_reads.append(
                    {"op": op.index, "problems": problems, "value": balances}
                )
        return {
            "valid": not bad_reads,
            "read-count": reads,
            "bad-reads": bad_reads[:16],
            "bad-read-count": len(bad_reads),
        }


class InMemoryBankClient(jc.Client):
    """Atomic in-memory ledger."""

    def __init__(self, state=None, lock=None, accounts=None, total=DEFAULT_TOTAL):
        if state is None:
            accounts = accounts or DEFAULT_ACCOUNTS
            state = {a: 0 for a in accounts}
            state[accounts[0]] = total
        self.state = state
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return InMemoryBankClient(self.state, self.lock)

    def invoke(self, test, op):
        with self.lock:
            if op.f == "read":
                return op.complete(OK, value=dict(self.state))
            t = op.value
            frm, to, amount = t["from"], t["to"], t["amount"]
            if self.state.get(frm, 0) < amount:
                return op.complete(FAIL, error="insufficient funds")
            self.state[frm] -= amount
            self.state[to] += amount
            return op.complete(OK)

    def reusable(self, test):
        return True


def generator(accounts=None, max_transfer: int = 5, rng: Optional[random.Random] = None):
    """Mix of reads and random transfers (tests/bank.clj:40-54)."""
    accounts = accounts or DEFAULT_ACCOUNTS
    rng = rng or random.Random()

    def transfer():
        a, b = rng.sample(accounts, 2)
        return {
            "f": "transfer",
            "value": {
                "from": a,
                "to": b,
                "amount": 1 + rng.randrange(max_transfer),
            },
        }

    return mix([FnGen(lambda: {"f": "read"}), FnGen(transfer)])


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    accounts = opts.get("accounts", DEFAULT_ACCOUNTS)
    total = opts.get("total-amount", DEFAULT_TOTAL)
    return {
        "name": "bank",
        "accounts": accounts,
        "total-amount": total,
        "generator": generator(
            accounts,
            opts.get("max-transfer", 5),
            random.Random(opts.get("seed")),
        ),
        "checker": BankChecker(
            negative_balances=opts.get("negative-balances", False)
        ),
        "client": InMemoryBankClient(accounts=accounts, total=total),
    }
