"""Causal-consistency register probe.

Equivalent of /root/reference/jepsen/src/jepsen/tests/causal.clj: a
causal order of five ops (read-init, write 1, read, write 2, read) is
issued per key through one worker; the checker replays completions
through a `CausalRegister` model that tracks the register value, a
write counter, and the last-seen position — writes must arrive in
counter order and every op must link to the previously-observed
position (:10-82).

Ops carry two ext fields: "position" (a unique id assigned by the
store for this op) and "link" (the position this op causally follows,
or "init").
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from .. import client as jc
from ..checker.core import Checker
from ..generator.independent import concurrent_generator
from ..history import OK, History
from ..parallel.independent import independent_checker


class CausalRegister:
    """causal.clj:32-81, one key's model."""

    __slots__ = ("value", "counter", "last_pos")

    def __init__(self, value: int = 0, counter: int = 0,
                 last_pos: Any = None):
        self.value = value
        self.counter = counter
        self.last_pos = last_pos

    def step(self, op) -> "CausalRegister | str":
        """Next model, or an error string."""
        link = op.ext.get("link")
        pos = op.ext.get("position")
        v = op.value
        if link != "init" and link != self.last_pos:
            return f"cannot link {link!r} to last-seen position {self.last_pos!r}"
        if op.f == "write":
            expect = self.counter + 1
            if v != expect:
                return f"expected value {expect}, attempting to write {v}"
            return CausalRegister(v, expect, pos)
        if op.f == "read-init":
            if self.counter == 0 and v not in (None, 0):
                return f"expected init value 0, read {v}"
            if v is None or v == self.value or (self.counter == 0 and v == 0):
                return CausalRegister(self.value, self.counter, pos)
            return f"can't read {v} from register {self.value}"
        if op.f == "read":
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return f"can't read {v} from register {self.value}"
        return f"unknown f {op.f!r}"


class CausalChecker(Checker):
    """Replays :ok ops through the model (causal.clj:86-108)."""

    def check(self, test: dict, history: History, opts: dict) -> dict:
        s: CausalRegister | str = CausalRegister()
        for op in history:
            if not op.is_ok:
                continue
            nxt = s.step(op)
            if isinstance(nxt, str):
                return {"valid": False, "error": nxt,
                        "op-index": op.index}
            s = nxt
        return {"valid": True,
                "model": {"value": s.value, "counter": s.counter}}


class InMemoryCausalClient(jc.Client):
    """A causally-consistent in-memory store: per-key state with
    positions assigned at apply time; each session op links to the
    session's previously returned position."""

    def __init__(self, state=None, lock=None):
        self.state = state if state is not None else {}
        self.lock = lock or threading.Lock()
        # Causal order is per key here (each 5-op causal order runs
        # against one key): first op on a key links to "init".
        self.last_pos: dict = {}

    def open(self, test, node):
        return InMemoryCausalClient(self.state, self.lock)

    def invoke(self, test, op):
        from ..parallel.independent import KV

        k, payload = op.value.key, op.value.value
        with self.lock:
            st = self.state.setdefault(k, {"value": 0, "pos": 0})
            st["pos"] += 1
            pos = (k, st["pos"])
            link = self.last_pos.get(k, "init")
            if op.f == "write":
                st["value"] = payload
                out = payload
            else:
                out = st["value"]
            self.last_pos[k] = pos
            return op.complete(
                OK, value=KV(k, out), position=pos, link=link,
            )

    def reusable(self, test):
        return True


def generator(keys=None):
    """Five-op causal order per key, one worker per key
    (causal.clj:111-131)."""
    def fgen(k):
        return [
            {"f": "read-init", "value": None},
            {"f": "write", "value": 1},
            {"f": "read", "value": None},
            {"f": "write", "value": 2},
            {"f": "read", "value": None},
        ]

    return concurrent_generator(1, keys or range(1_000_000), fgen)


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    return {
        "name": "causal",
        "generator": generator(opts.get("keys")),
        "checker": independent_checker(CausalChecker()),
        "client": InMemoryCausalClient(),
    }
