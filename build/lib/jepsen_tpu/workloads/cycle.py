"""Generic transactional-cycle workload: user-supplied dependency
analyzers.

Equivalent of /root/reference/jepsen/src/jepsen/tests/cycle.clj:9-16,
which wraps `elle.core/check` around a caller-provided analyzer
function.  Here an analyzer is any callable

    analyzer(history: History) -> DepGraph

building a typed dependency graph over operation indices; the checker
runs the layered cycle search of checker/elle/graph.py over it (plus
the device screen when requested) and reports each cycle with its
Adya classification.  Several analyzers may be combined — their edges
are unioned into one graph, like elle.core's `combine`.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

from ..checker.core import Checker
from ..checker.elle.graph import DepGraph, check_cycles
from ..history.core import History, Op

Analyzer = Callable[[History], DepGraph]


def combine(graphs: Iterable[DepGraph]) -> DepGraph:
    """Unions several dependency graphs into one (edge types merge)."""
    out = DepGraph()
    for g in graphs:
        out.vertices |= g.vertices
        for src, dsts in g.adj.items():
            for dst, types in dsts.items():
                for t in types:
                    out.add_edge(src, dst, t)
    return out


def realtime_graph(history: History) -> DepGraph:
    """Stock analyzer: op A happens-before op B when A's completion
    precedes B's invocation (elle.core's realtime analyzer).  Edges
    land on invocation indices.

    Sparse but reachability-preserving reduction over the interval
    order: sweep events in history order, keeping a frontier of
    completed ops that is always an antichain (mutually concurrent).
    Every invocation links from the whole frontier; when an op A
    completes, frontier members that finished before A *invoked* are
    retired — any later op C has inv(C) > comp(A), so the path
    X -> A -> C covers the direct X -> C edge.  Edge count is bounded
    by ops x max-concurrency instead of ops^2."""
    g = DepGraph()
    events: list[tuple[int, int, Op, int]] = []  # (t, kind, inv, comp-t)
    for o in history:
        if not o.is_invoke:
            continue
        comp = history.completion(o)
        # Only :ok ops are realtime-ordered: an :info op's effect may
        # land arbitrarily later than its info marker, and a :fail op
        # never took effect (elle.core's realtime analyzer).
        if comp is None or not comp.is_ok:
            continue
        events.append((o.index, 0, o, comp.index))
        events.append((comp.index, 1, o, comp.index))
    events.sort(key=lambda e: (e[0], e[1]))
    frontier: list[tuple[Op, int]] = []  # completed, pairwise concurrent
    for _, kind, inv, comp_t in events:
        if kind == 0:
            for done, _dt in frontier:
                g.add_edge(done.index, inv.index, "realtime")
        else:
            frontier = [
                (x, dt) for (x, dt) in frontier if dt >= inv.index
            ]
            frontier.append((inv, comp_t))
    return g


def process_graph(history: History) -> DepGraph:
    """Stock analyzer: successive invocations of the same process are
    ordered (elle.core's process analyzer)."""
    g = DepGraph()
    last: dict[Any, Op] = {}
    for o in history:
        if not o.is_invoke:
            continue
        prev = last.get(o.process)
        if prev is not None:
            g.add_edge(prev.index, o.index, "process")
        last[o.process] = o
    return g


class CycleChecker(Checker):
    """checker(analyze-fn) of tests/cycle.clj:9-16.  `device` as in
    elle's Append/Wr checkers: "auto"/"on" screens the graph on the
    accelerator first, "off" is host-only."""

    def __init__(self, *analyzers: Analyzer, device: str = "off"):
        if not analyzers:
            raise ValueError("need at least one analyzer")
        self.analyzers = analyzers
        self.device = device

    def check(self, test: dict, history: History, opts: dict) -> dict:
        from ..checker.elle import _device_cycle_fn

        h = history.client_ops()
        graph = combine(a(h) for a in self.analyzers)
        cycles = (_device_cycle_fn(self.device) or check_cycles)(graph)
        anomaly_types = sorted({c["type"] for c in cycles})
        res = {
            "valid": not cycles,
            "anomaly-types": anomaly_types,
            "anomalies": cycles,
            "vertices": len(graph.vertices),
            "edges": graph.n_edges(),
        }
        from ..checker.elle import write_artifacts

        write_artifacts(res, opts, "elle-cycle")
        return res


def checker(*analyzers: Analyzer, device: str = "off") -> CycleChecker:
    return CycleChecker(*analyzers, device=device)
