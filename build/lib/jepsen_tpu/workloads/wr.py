"""Read-write register workload (tests/cycle/wr.clj:10-43 equivalent)."""

from __future__ import annotations

import random
import threading
from typing import Optional

from .. import client as jc
from ..checker.elle import WrChecker, WrGen
from ..generator.core import FnGen
from ..history import OK


class InMemoryWrClient(jc.Client):
    """Atomic multi-register store: whole transactions under one lock."""

    def __init__(self, state=None, lock=None):
        self.state = state if state is not None else {}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return InMemoryWrClient(self.state, self.lock)

    def invoke(self, test, op):
        with self.lock:
            out = []
            for f, k, v in op.value:
                if f == "w":
                    self.state[k] = v
                    out.append([f, k, v])
                else:
                    out.append(["r", k, self.state.get(k)])
            return op.complete(OK, value=out)

    def reusable(self, test):
        return True


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    gen = WrGen(
        key_count=opts.get("key-count", 10),
        min_txn_length=opts.get("min-txn-length", 1),
        max_txn_length=opts.get("max-txn-length", 4),
        rng=random.Random(opts.get("seed")),
    )
    return {
        "name": "rw-register",
        "generator": FnGen(gen),
        "checker": WrChecker(opts.get("consistency-model", "serializable")),
        "client": InMemoryWrClient(),
    }
