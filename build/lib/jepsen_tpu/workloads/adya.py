"""Adya G2 (anti-dependency cycle) predicate probe.

Equivalent of /root/reference/jepsen/src/jepsen/tests/adya.clj: for
each unique key, two concurrent transactions each read a *predicate*
over two tables (any row for this key), and insert into their own
table only if both reads came back empty.  Under serializability at
most one insert can commit per key; two commits form a G2 cycle via
predicate anti-dependencies (:10-56).

Op values are independent tuples (key, [a_id, b_id]) where exactly one
id is set — which one picks the table the txn would insert into.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Optional

from .. import client as jc
from ..checker.core import Checker
from ..generator.core import once
from ..generator.independent import concurrent_generator
from ..history import FAIL, OK, History
from ..parallel.independent import KV, independent_checker


class G2Checker(Checker):
    """At most one :ok insert per key (adya.clj:58-86)."""

    def check(self, test: dict, history: History, opts: dict) -> dict:
        ok = 0
        for op in history:
            if op.f == "insert" and op.is_ok:
                ok += 1
        return {"valid": ok <= 1, "ok-inserts": ok}


def g2_generator():
    """Two one-shot inserts per key: [nil b-id] and [a-id nil], two
    workers per key (adya.clj:12-56)."""
    ids = itertools.count(1)

    def fgen(k):
        return [
            once({"f": "insert", "value": [None, next(ids)]}),
            once({"f": "insert", "value": [next(ids), None]}),
        ]

    return concurrent_generator(2, range(1_000_000), fgen)


class InMemoryG2Client(jc.Client):
    """Reference client over two in-memory "tables".  `racy=True`
    makes the read-check-insert non-atomic (predicate read outside the
    lock), producing real G2 anomalies for the checker to catch."""

    def __init__(self, state=None, lock=None, racy: bool = False,
                 barrier=None):
        self.state = state if state is not None else {"a": {}, "b": {}}
        self.lock = lock or threading.Lock()
        self.racy = racy
        self.barrier = barrier

    def open(self, test, node):
        return InMemoryG2Client(self.state, self.lock, self.racy,
                                self.barrier)

    def _empty(self, k) -> bool:
        return not (self.state["a"].get(k) or self.state["b"].get(k))

    def invoke(self, test, op):
        k, (a_id, b_id) = op.value.key, op.value.value
        table = "a" if a_id is not None else "b"
        row_id = a_id if a_id is not None else b_id
        if self.racy:
            # Predicate read outside the critical section: both txns
            # can see empty tables and both insert — G2.
            empty = self._empty(k)
            if self.barrier is not None:
                try:
                    self.barrier.wait(timeout=1.0)
                except threading.BrokenBarrierError:
                    pass
            if not empty:
                return op.complete(FAIL)
            with self.lock:
                self.state[table][k] = row_id
            return op.complete(OK)
        with self.lock:
            if not self._empty(k):
                return op.complete(FAIL)
            self.state[table][k] = row_id
            return op.complete(OK)

    def reusable(self, test):
        return True


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    return {
        "name": "adya-g2",
        "generator": g2_generator(),
        "checker": independent_checker(G2Checker()),
        "client": InMemoryG2Client(racy=opts.get("racy", False)),
    }
